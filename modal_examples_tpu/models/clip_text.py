"""CLIP text encoder — the prompt side of the SD family.

The reference's diffusion pipelines condition on CLIP text embeddings
(text_to_image.py loads the full SD3.5 pipeline whose text encoders are
CLIP-L/G (+T5); flux.py likewise). This is the TPU-native counterpart: the
HF CLIPTextModel architecture in JAX with a safetensors loader, so a
standard `text_encoder/model.safetensors` checkout drops in.

Architecture (CLIPTextModel):
- token + learned position embeddings;
- pre-LN transformer with causal attention and quick_gelu MLP;
- final layer norm; pooled output = hidden state at each sequence's
  EOS token (the highest token id in CLIP's vocab convention).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    max_len: int = 77
    eos_token_id: int = 49407
    norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def clip_l() -> "CLIPTextConfig":
        """CLIP-L/14 text tower (SD1/2/XL/3 primary text encoder)."""
        return CLIPTextConfig()

    @staticmethod
    def tiny(vocab_size: int = 512) -> "CLIPTextConfig":
        return CLIPTextConfig(
            vocab_size=vocab_size, dim=64, n_layers=2, n_heads=2, max_len=32,
            eos_token_id=vocab_size - 1,
        )


def init_params(key: jax.Array, cfg: CLIPTextConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_layers
    ks = iter(jax.random.split(key, 12))

    def dense(*shape):
        return layers.init_dense(next(ks), shape, dtype=dt)

    return {
        "token_emb": layers.init_dense(
            next(ks), (cfg.vocab_size, D), scale=0.02, dtype=dt
        ),
        "pos_emb": layers.init_dense(next(ks), (cfg.max_len, D), scale=0.02, dtype=dt),
        "layers": {
            "ln1_scale": jnp.ones((L, D), dt), "ln1_bias": jnp.zeros((L, D), dt),
            "wq": dense(L, D, D), "bq": jnp.zeros((L, D), dt),
            "wk": dense(L, D, D), "bk": jnp.zeros((L, D), dt),
            "wv": dense(L, D, D), "bv": jnp.zeros((L, D), dt),
            "wo": dense(L, D, D), "bo": jnp.zeros((L, D), dt),
            "ln2_scale": jnp.ones((L, D), dt), "ln2_bias": jnp.zeros((L, D), dt),
            "fc1": dense(L, D, 4 * D), "fc1_b": jnp.zeros((L, 4 * D), dt),
            "fc2": dense(L, 4 * D, D), "fc2_b": jnp.zeros((L, D), dt),
        },
        "final_ln_scale": jnp.ones((D,), dt),
        "final_ln_bias": jnp.zeros((D,), dt),
    }


def _ln(x, scale, bias, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def forward(
    params: dict,
    tokens: jax.Array,  # [B, S] int32 (padded to max_len or shorter)
    cfg: CLIPTextConfig,
) -> tuple[jax.Array, jax.Array]:
    """Returns (hidden [B, S, D] — the per-token states diffusion models
    cross-attend to, pooled [B, D] — the EOS-position state)."""
    B, S = tokens.shape
    x = params["token_emb"][tokens] + params["pos_emb"][None, :S]
    mask = jnp.tril(jnp.ones((S, S), bool))  # causal (CLIP convention)

    def layer_fn(x, l):
        h = _ln(x, l["ln1_scale"], l["ln1_bias"], cfg.norm_eps)
        q = h @ l["wq"] + l["bq"]
        k = h @ l["wk"] + l["bk"]
        v = h @ l["wv"] + l["bv"]
        hd = cfg.dim // cfg.n_heads
        q = q.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
        s = jnp.where(mask[None, None], s * hd**-0.5, -jnp.inf)
        a = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.dim)
        x = x + (o @ l["wo"] + l["bo"])
        h = _ln(x, l["ln2_scale"], l["ln2_bias"], cfg.norm_eps)
        h = layers.quick_gelu(h @ l["fc1"] + l["fc1_b"]) @ l["fc2"] + l["fc2_b"]
        return x + h, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    hidden = _ln(x, params["final_ln_scale"], params["final_ln_bias"], cfg.norm_eps)
    # pooled = state at the first EOS token per sequence (CLIP convention)
    is_eos = tokens == cfg.eos_token_id
    idx = jnp.where(
        is_eos.any(axis=1), jnp.argmax(is_eos, axis=1), S - 1
    )  # [B]
    pooled = jnp.take_along_axis(
        hidden, idx[:, None, None].repeat(cfg.dim, -1), axis=1
    )[:, 0]
    return hidden, pooled


# -- HF (transformers CLIPTextModel) interop ---------------------------------


def load_hf_weights(model_dir: str | Path, cfg: CLIPTextConfig, dtype=None) -> dict:
    """Map a transformers CLIPTextModel safetensors checkpoint
    (text_encoder/model.safetensors naming) into this tree."""
    import numpy as np
    from safetensors import safe_open

    dt = dtype or cfg.jnp_dtype
    raw = {}
    for f in sorted(Path(model_dir).glob("*.safetensors")):
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    P = "text_model."

    def stack_lin(fmt):
        return jnp.asarray(
            np.stack([raw.pop(fmt.format(i)).T for i in range(cfg.n_layers)]), dt
        )

    def stack_vec(fmt):
        return jnp.asarray(
            np.stack([raw.pop(fmt.format(i)) for i in range(cfg.n_layers)]), dt
        )

    E = P + "encoder.layers.{}."
    return {
        "token_emb": jnp.asarray(
            raw.pop(P + "embeddings.token_embedding.weight"), dt
        ),
        "pos_emb": jnp.asarray(
            raw.pop(P + "embeddings.position_embedding.weight"), dt
        ),
        "layers": {
            "ln1_scale": stack_vec(E + "layer_norm1.weight"),
            "ln1_bias": stack_vec(E + "layer_norm1.bias"),
            "wq": stack_lin(E + "self_attn.q_proj.weight"),
            "bq": stack_vec(E + "self_attn.q_proj.bias"),
            "wk": stack_lin(E + "self_attn.k_proj.weight"),
            "bk": stack_vec(E + "self_attn.k_proj.bias"),
            "wv": stack_lin(E + "self_attn.v_proj.weight"),
            "bv": stack_vec(E + "self_attn.v_proj.bias"),
            "wo": stack_lin(E + "self_attn.out_proj.weight"),
            "bo": stack_vec(E + "self_attn.out_proj.bias"),
            "ln2_scale": stack_vec(E + "layer_norm2.weight"),
            "ln2_bias": stack_vec(E + "layer_norm2.bias"),
            "fc1": stack_lin(E + "mlp.fc1.weight"),
            "fc1_b": stack_vec(E + "mlp.fc1.bias"),
            "fc2": stack_lin(E + "mlp.fc2.weight"),
            "fc2_b": stack_vec(E + "mlp.fc2.bias"),
        },
        "final_ln_scale": jnp.asarray(raw.pop(P + "final_layer_norm.weight"), dt),
        "final_ln_bias": jnp.asarray(raw.pop(P + "final_layer_norm.bias"), dt),
    }
