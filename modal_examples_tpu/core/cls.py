"""Cls — stateful load-once-serve-many services with lifecycle hooks.

Reference spec: ``@app.cls`` + ``@modal.enter`` / ``@modal.method`` /
``@modal.exit`` (stable_diffusion/text_to_image.py:92-137);
``@modal.enter(snap=True)`` for snapshot-eligible setup (gpu_snapshot.py:47);
typed instance parameters via ``modal.parameter()`` (9 uses);
``Cls.with_options(gpu=...)`` (cls_with_options.py:57); ``Cls.from_name``
(gpu_snapshot.py:64).

TPU semantics of ``@enter``: this is where weights go to HBM and the XLA
compile (or persistent-cache hit) happens — the analog of the reference's
pipeline-load + CUDA warmup. The container then serves many inputs against
the resident, compiled state.
"""

from __future__ import annotations

import dataclasses
import inspect
import threading
from typing import Any, Callable

from . import serialization as ser
from .function import FunctionSpec, _Invoker, _GenInvoker, FunctionCall, _drain_gen

_LIFECYCLE_ATTR = "__mtpu_lifecycle__"


def method(*, is_generator: bool | None = None) -> Callable:
    def deco(fn):
        fn.__mtpu_method__ = {
            "is_generator": (
                inspect.isgeneratorfunction(fn) if is_generator is None else is_generator
            )
        }
        return fn

    return deco


def enter(*, snap: bool = False) -> Callable:
    """Lifecycle hook run once at container start (before any input).

    ``snap=True`` marks the hook as snapshot-eligible: its effects (weights in
    host memory, XLA executables in the persistent compile cache) are captured
    by the memory-snapshot layer so later cold starts resume past it
    (gpu_snapshot.py:41-47 analog).
    """

    def deco(fn):
        fn.__mtpu_enter__ = {"snap": snap}
        return fn

    return deco


def exit() -> Callable:
    def deco(fn):
        fn.__mtpu_exit__ = True
        return fn

    return deco


@dataclasses.dataclass
class _Parameter:
    default: Any = None
    init: bool = True


def parameter(*, default: Any = None, init: bool = True) -> Any:
    return _Parameter(default=default, init=init)


def _collect_lifecycle(user_cls: type) -> dict:
    meta = {"enter": [], "exit": [], "methods": {}, "parameters": {}}
    for name, member in inspect.getmembers(user_cls):
        if hasattr(member, "__mtpu_enter__"):
            meta["enter"].append(name)
        if hasattr(member, "__mtpu_exit__"):
            meta["exit"].append(name)
        if hasattr(member, "__mtpu_method__"):
            meta["methods"][name] = dict(member.__mtpu_method__)
            if hasattr(member, "__mtpu_batched__"):
                meta["methods"][name]["batched"] = member.__mtpu_batched__
        if getattr(member, "__mtpu_web__", None):
            meta["methods"].setdefault(name, {"is_generator": False})
    for name, val in list(vars(user_cls).items()):
        if isinstance(val, _Parameter):
            meta["parameters"][name] = val
            setattr(user_cls, name, val.default)
    # run snap=True enters first, matching snapshot-restore ordering
    meta["enter"].sort(
        key=lambda n: not getattr(getattr(user_cls, n), "__mtpu_enter__", {}).get(
            "snap", False
        )
    )
    # snapshot-eligible hooks, in run order (the memory-snapshot layer skips
    # these on a restored boot; see modal_examples_tpu.snapshot)
    meta["snap_enter"] = [
        n
        for n in meta["enter"]
        if getattr(getattr(user_cls, n), "__mtpu_enter__", {}).get("snap", False)
    ]
    return meta


class _BoundMethod:
    """``obj.generate`` — carries .remote/.local/.spawn/.map for one method."""

    def __init__(self, obj: "Obj", name: str, is_generator: bool):
        self._obj = obj
        self._name = name
        self.is_generator = is_generator
        self.remote = (_GenInvoker if is_generator else _Invoker)(self._remote)
        self.remote_gen = _GenInvoker(self._remote_gen)
        self.map = _GenInvoker(self._map)
        self.starmap = _GenInvoker(self._starmap)
        self.spawn = _Invoker(self._spawn)
        self.for_each = _Invoker(self._for_each)

    def local(self, *args, **kwargs):
        return getattr(self._obj._local_instance(), self._name)(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        return self.local(*args, **kwargs)

    def _submit(self, args, kwargs):
        from .function import split_priority

        target = getattr(self._obj._cls._user_cls, self._name, None)
        priority, kwargs = split_priority(target, kwargs)
        return self._obj._pool().submit(
            self._name, args, kwargs, priority=priority
        )

    def _remote(self, *args, **kwargs):
        call = self._submit(args, kwargs)
        if self.is_generator:
            return _drain_gen(call)
        return call.result()

    def _remote_gen(self, *args, **kwargs):
        return _drain_gen(self._submit(args, kwargs))

    def _spawn(self, *args, **kwargs) -> FunctionCall:
        return FunctionCall._register(self._submit(args, kwargs))

    def _map(self, *iters, order_outputs=True, return_exceptions=False):
        inputs = zip(*iters) if len(iters) > 1 else ((x,) for x in iters[0])
        yield from self._run_many(list(inputs), order_outputs, return_exceptions)

    def _starmap(self, it, *, order_outputs=True, return_exceptions=False):
        yield from self._run_many(
            [tuple(t) for t in it], order_outputs, return_exceptions
        )

    def _for_each(self, *iters, ignore_exceptions=False):
        for _ in self._map(
            *iters, order_outputs=False, return_exceptions=ignore_exceptions
        ):
            pass

    def _run_many(self, arg_tuples, order_outputs, return_exceptions):
        from .function import run_many

        yield from run_many(
            lambda args: self._submit(args, {}),
            arg_tuples,
            order_outputs,
            return_exceptions,
        )


class Obj:
    """A parameterized instance handle of a Cls (client side)."""

    def __init__(self, cls: "Cls", params: dict[str, Any]):
        self._cls = cls
        self._params = params
        self._local_obj = None
        self._local_lock = threading.Lock()

    def _spec(self) -> FunctionSpec:
        spec = dataclasses.replace(
            self._cls._spec,
            cls_params_bytes=ser.serialize(self._params) if self._params else None,
        )
        return spec

    def _pool(self):
        from .app import current_run

        return current_run(self._cls._app).pool_for(self._spec())

    def _local_instance(self):
        with self._local_lock:
            if self._local_obj is None:
                obj = self._cls._user_cls()
                for k, v in self._params.items():
                    setattr(obj, k, v)
                for name in self._cls._meta["enter"]:
                    getattr(obj, name)()
                self._local_obj = obj
            return self._local_obj

    def __getattr__(self, name: str):
        meta = self._cls._meta
        if name in meta["methods"]:
            return _BoundMethod(self, name, meta["methods"][name]["is_generator"])
        raise AttributeError(
            f"{self._cls._user_cls.__name__}.{name} is not a @method"
        )


class Cls:
    """Client-side handle for an ``@app.cls``-decorated class."""

    def __init__(self, app, user_cls: type, spec: FunctionSpec, meta: dict):
        self._app = app
        self._user_cls = user_cls
        self._spec = spec
        self._meta = meta

    def __call__(self, **params) -> Obj:
        known = self._meta["parameters"]
        unknown = set(params) - set(known)
        if unknown:
            raise TypeError(
                f"{self._user_cls.__name__}() got unexpected parameters {sorted(unknown)}; "
                f"declare them with modal.parameter()"
            )
        resolved = {k: p.default for k, p in known.items()}
        resolved.update(params)
        return Obj(self, resolved)

    def with_options(self, *, tpu=None, retries=None, **kw) -> "Cls":
        """Override resource/scheduling options (cls_with_options.py:57).

        Any FunctionSpec scheduling field can be overridden; unknown options
        raise rather than being silently dropped.
        """
        from .resources import parse_tpu_request
        from .retries import normalize_retries

        spec = dataclasses.replace(self._spec)
        if tpu is not None:
            spec.tpu = parse_tpu_request(tpu)
        if retries is not None:
            spec.retries = normalize_retries(retries)
        valid = {f.name for f in dataclasses.fields(spec)} - {
            "tag", "app_name", "raw_target", "is_cls_method", "cls_params_bytes",
        }
        for key, value in kw.items():
            if key not in valid:
                raise TypeError(
                    f"with_options got unknown option {key!r}; valid: {sorted(valid)}"
                )
            setattr(spec, key, value)
        return Cls(self._app, self._user_cls, spec, self._meta)

    @staticmethod
    def from_name(app_name: str, name: str, environment_name: str | None = None) -> "Cls":
        from .app import App

        app = App.lookup(app_name)
        try:
            return app.registered_classes[name]
        except KeyError:
            raise KeyError(
                f"class {name!r} not found in app {app_name!r}; "
                f"registered: {sorted(app.registered_classes)}"
            ) from None

    # lifecycle-free attribute passthrough for introspection
    @property
    def user_cls(self) -> type:
        return self._user_cls

    def __repr__(self) -> str:
        return f"Cls({self._spec.tag!r})"
