"""Layered container image DSL — TPU flavored (no CUDA anywhere).

Reference spec: the chainable builder
``modal.Image.debian_slim().uv_pip_install(...).apt_install(...).env(...)``
(text_embeddings_inference.py:63-71, vllm_inference.py:35-45), registry bases
via ``from_registry(..., add_python=...)`` (install_cuda.py:40),
``run_function`` build steps, ``add_local_dir/file``
(simple_torch_cluster.py:35-38), and the ``image.imports()`` context manager
(import_sklearn.py:25-27).

Design: an :class:`Image` is an immutable chain of content-addressed layers.
The local backend doesn't build OCI images; it *applies* the layers it can
(env vars, run_function build steps — cached by layer hash in the state dir,
the analog of Modal's image build cache) and records the rest (apt/pip) as the
build recipe a real container builder would execute. The default base,
:meth:`Image.tpu_base`, declares the JAX/libtpu stack — the TPU replacement
for the reference's CUDA bases.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import sys
from pathlib import Path
from typing import Any, Callable, Sequence

from .._internal import config as _config


@dataclasses.dataclass(frozen=True)
class ImageLayer:
    kind: str  # base | pip | apt | env | run_commands | run_function | workdir | entrypoint | add_local
    payload: tuple  # hashable description
    # run_function layers carry the callable out-of-band (not hashed by code id)
    fn: Callable | None = dataclasses.field(default=None, compare=False)

    def digest_item(self) -> str:
        return json.dumps([self.kind, list(map(str, self.payload))])


class Image:
    """Immutable chainable image definition."""

    def __init__(self, layers: tuple[ImageLayer, ...] = ()):
        self._layers = layers

    # -- constructors -------------------------------------------------------

    @staticmethod
    def debian_slim(python_version: str | None = None) -> "Image":
        return Image((ImageLayer("base", ("debian_slim", python_version or "")),))

    @staticmethod
    def tpu_base(python_version: str | None = None) -> "Image":
        """Base layer: Python + jax[tpu] + libtpu. The CUDA-free foundation."""
        img = Image((ImageLayer("base", ("tpu_base", python_version or "")),))
        return img.uv_pip_install("jax[tpu]", "flax", "optax", "orbax-checkpoint")

    @staticmethod
    def from_registry(tag: str, add_python: str | None = None) -> "Image":
        return Image((ImageLayer("base", ("registry", tag, add_python or "")),))

    @staticmethod
    def micromamba(python_version: str | None = None) -> "Image":
        return Image((ImageLayer("base", ("micromamba", python_version or "")),))

    # -- chainable layers ---------------------------------------------------

    def _add(self, layer: ImageLayer) -> "Image":
        return Image(self._layers + (layer,))

    def pip_install(self, *packages: str, **kw) -> "Image":
        return self._add(ImageLayer("pip", tuple(sorted(packages))))

    def uv_pip_install(self, *packages: str, **kw) -> "Image":
        return self._add(ImageLayer("pip", tuple(sorted(packages))))

    def micromamba_install(self, *packages: str, channels: Sequence[str] = (), **kw) -> "Image":
        return self._add(ImageLayer("pip", tuple(sorted(packages)) + tuple(channels)))

    def apt_install(self, *packages: str) -> "Image":
        return self._add(ImageLayer("apt", tuple(sorted(packages))))

    def env(self, vars: dict[str, str]) -> "Image":
        return self._add(ImageLayer("env", tuple(sorted(vars.items()))))

    def workdir(self, path: str) -> "Image":
        return self._add(ImageLayer("workdir", (path,)))

    def entrypoint(self, cmd: Sequence[str]) -> "Image":
        return self._add(ImageLayer("entrypoint", tuple(cmd)))

    def run_commands(self, *commands: str) -> "Image":
        return self._add(ImageLayer("run_commands", tuple(commands)))

    def run_function(self, fn: Callable, **kw) -> "Image":
        """Run ``fn`` once at build time (e.g. weight pre-download); cached."""
        name = getattr(fn, "__qualname__", repr(fn))
        return self._add(ImageLayer("run_function", (name,), fn=fn))

    def add_local_dir(self, local_path: str, remote_path: str, copy: bool = False) -> "Image":
        return self._add(ImageLayer("add_local", ("dir", local_path, remote_path)))

    def add_local_file(self, local_path: str, remote_path: str, copy: bool = False) -> "Image":
        return self._add(ImageLayer("add_local", ("file", local_path, remote_path)))

    def add_local_python_source(self, *modules: str) -> "Image":
        return self._add(ImageLayer("add_local", ("pysource",) + tuple(modules)))

    # -- introspection / application ---------------------------------------

    def export_oci(self, dest: str, *, tag: str = "latest") -> dict:
        """Serialize as a spec-valid OCI image layout at ``dest`` (local
        content becomes real layer blobs; network steps become provenance
        history). See :mod:`modal_examples_tpu.core.oci`."""
        from .oci import export_oci

        return export_oci(self, dest, tag=tag)

    @property
    def layers(self) -> tuple[ImageLayer, ...]:
        return self._layers

    def digest(self) -> str:
        h = hashlib.sha256()
        for layer in self._layers:
            h.update(layer.digest_item().encode())
        return h.hexdigest()[:16]

    def env_vars(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for layer in self._layers:
            if layer.kind == "env":
                out.update(dict(layer.payload))
        return out

    def python_packages(self) -> list[str]:
        out: list[str] = []
        for layer in self._layers:
            if layer.kind == "pip":
                out.extend(layer.payload)
        return out

    def sys_path_additions(self) -> list[str]:
        """Local dirs that must be importable inside the container."""
        out = []
        for layer in self._layers:
            if layer.kind == "add_local" and layer.payload[0] == "dir":
                out.append(layer.payload[1])
        return out

    @contextlib.contextmanager
    def imports(self):
        """Import block tolerant of locally-missing container-only packages.

        Reference: ``with image.imports(): import sklearn``
        (02_building_containers/import_sklearn.py:25-27) — inside a container
        the import must succeed; on the client it is silently skipped.
        """
        try:
            yield
        except ImportError:
            if _config.in_container():
                raise

    def build_local(self) -> dict[str, str]:
        """Apply this image for a local-backend container; returns env vars.

        run_function build steps execute once and are cached by layer-chain
        digest (the build-cache analog). pip/apt layers are validated against
        the current interpreter where possible but not installed (the
        environment is pre-baked; see repo AGENTS note — no network installs).
        """
        marker_dir = _config.state_dir() / "image_builds"
        marker_dir.mkdir(parents=True, exist_ok=True)
        env = self.env_vars()
        running_digest = hashlib.sha256()
        for layer in self._layers:
            running_digest.update(layer.digest_item().encode())
            if layer.kind == "run_function" and layer.fn is not None:
                marker = marker_dir / (running_digest.hexdigest()[:16] + ".done")
                if not marker.exists():
                    old_env = dict(os.environ)
                    os.environ.update(env)
                    try:
                        layer.fn()
                    finally:
                        os.environ.clear()
                        os.environ.update(old_env)
                    marker.write_text("ok")
        return env


#: Default image used when a Function doesn't specify one.
DEFAULT_IMAGE = Image.debian_slim()
