"""Sandbox — dynamically created containers for untrusted code.

Reference spec (SURVEY.md §2.1): ``Sandbox.create(app=, image=, volumes=,
timeout=)`` (13_sandboxes/safe_code_execution.py:28), ``sandbox.exec(...)``
with streamed stdout/stderr and ``.wait()`` (:37-41), agent-driven use
(sandbox_agent.py:29-62), warm pools coordinated through Queues
(sandbox_pool.py:6-30), ``modal.forward`` tunnels
(11_notebooks/jupyter_inside_modal.py:9).

Local control plane: a sandbox is an isolated working directory + scrubbed
environment; ``exec`` spawns OS processes inside it with piped stdio, a
sandbox-wide deadline reaper, and volume mounts materialized as symlinks.
(The platform backend would run these under gvisor/runc — per-example
``runtimes`` frontmatter in the reference, internal/utils.py:133; the
process API is identical.)
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import threading
import time
import uuid
from pathlib import Path

from .._internal import config as _config


class SandboxTimeoutError(TimeoutError):
    pass


class ContainerProcess:
    """Handle for one exec'd process: streamed stdio + wait/kill."""

    def __init__(self, proc: subprocess.Popen, sandbox: "Sandbox"):
        self._proc = proc
        self._sandbox = sandbox
        self.stdout = proc.stdout
        self.stderr = proc.stderr
        self.stdin = proc.stdin

    @property
    def returncode(self) -> int | None:
        return self._proc.returncode

    def poll(self) -> int | None:
        return self._proc.poll()

    def wait(self, timeout: float | None = None) -> int:
        remaining = self._sandbox._remaining()
        if timeout is None or (remaining is not None and remaining < timeout):
            timeout = remaining
        try:
            return self._proc.wait(timeout)
        except subprocess.TimeoutExpired:
            raise SandboxTimeoutError(
                f"process exceeded sandbox deadline in {self._sandbox.object_id}"
            ) from None

    def kill(self) -> None:
        try:
            self._proc.kill()
        except ProcessLookupError:
            pass


class Tunnel:
    """Forwarded-port handle (modal.forward analog). Locally ports are
    already reachable; the platform backend would allocate a public host."""

    def __init__(self, port: int):
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        self.tls_socket = ("127.0.0.1", port)


class Sandbox:
    def __init__(self, sandbox_dir: Path, env: dict[str, str], timeout: float):
        self.object_id = f"sb-{uuid.uuid4().hex[:12]}"
        self._dir = sandbox_dir
        self._env = env
        self._deadline = time.monotonic() + timeout if timeout else None
        self._procs: list[subprocess.Popen] = []
        self._lock = threading.Lock()
        self._terminated = False
        self._tags: dict[str, str] = {}
        _live_sandboxes[self.object_id] = self
        self._timeout_timer: threading.Timer | None = None
        if timeout:
            # daemon + cancelled on terminate: a live timer must not pin the
            # interpreter open for the full sandbox timeout after the user is
            # done with the sandbox
            self._timeout_timer = threading.Timer(timeout, self.terminate)
            self._timeout_timer.daemon = True
            self._timeout_timer.start()

    # -- creation -----------------------------------------------------------

    @classmethod
    def create(
        cls,
        *entrypoint_args: str,
        app=None,
        image=None,
        volumes: dict | None = None,
        secrets: list | None = None,
        timeout: float = 300,
        workdir: str | None = None,
        cpu: float | None = None,
        memory: int | None = None,
        unencrypted_ports: list[int] | None = None,
        encrypted_ports: list[int] | None = None,
    ) -> "Sandbox":
        root = _config.state_dir() / "sandboxes"
        root.mkdir(parents=True, exist_ok=True)
        sb_dir = root / f"sb-{uuid.uuid4().hex[:12]}"
        sb_dir.mkdir()
        # scrubbed environment: image/secrets env only + a minimal base —
        # untrusted code must not inherit the control plane's environment
        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": str(sb_dir),
            "LANG": os.environ.get("LANG", "C.UTF-8"),
        }
        if image is not None:
            env.update(image.env_vars())
        for s in secrets or []:
            env.update(s.env_vars())
        for mount_path, vol in (volumes or {}).items():
            target = sb_dir / mount_path.lstrip("/")
            target.parent.mkdir(parents=True, exist_ok=True)
            if not target.exists():
                target.symlink_to(vol.local_path)
        sb = cls(sb_dir, env, timeout)
        sb._volumes = dict(volumes or {})
        if workdir:
            (sb_dir / workdir.lstrip("/")).mkdir(parents=True, exist_ok=True)
            sb._workdir = str(sb_dir / workdir.lstrip("/"))
        else:
            sb._workdir = str(sb_dir)
        if entrypoint_args:
            sb.exec(*entrypoint_args)
        return sb

    @classmethod
    def from_id(cls, object_id: str) -> "Sandbox":
        try:
            return _live_sandboxes[object_id]
        except KeyError:
            raise KeyError(f"sandbox {object_id!r} not found in this process") from None

    @staticmethod
    def list() -> list["Sandbox"]:
        return [s for s in _live_sandboxes.values() if not s._terminated]

    # -- execution ----------------------------------------------------------

    def _remaining(self) -> float | None:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def exec(
        self,
        *cmd: str,
        workdir: str | None = None,
        timeout: float | None = None,
        text: bool = True,
        pty_info=None,
    ) -> ContainerProcess:
        if self._terminated:
            raise RuntimeError(f"sandbox {self.object_id} is terminated")
        proc = subprocess.Popen(
            list(cmd),
            cwd=workdir or self._workdir,
            env=self._env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=text,
            start_new_session=True,  # its own process group for clean kills
        )
        with self._lock:
            self._procs.append(proc)
        if timeout:
            t = threading.Timer(
                timeout, lambda: proc.poll() is None and proc.kill()
            )
            t.daemon = True
            t.start()
        return ContainerProcess(proc, self)

    # -- filesystem ---------------------------------------------------------

    def open(self, path: str, mode: str = "r"):
        p = (Path(self._workdir) / path.lstrip("/")).resolve()
        root = self._dir.resolve()
        # proper containment check (str.startswith lets /tmp/sb-abcd pass a
        # /tmp/sb-abc root); volume mounts resolve outside the sandbox dir
        # via symlinks and are legitimate targets
        allowed = [root] + [
            Path(v.local_path).resolve()
            for v in getattr(self, "_volumes", {}).values()
            if hasattr(v, "local_path")
        ]
        if not any(p == a or p.is_relative_to(a) for a in allowed):
            raise PermissionError(f"path escapes sandbox: {path}")
        p.parent.mkdir(parents=True, exist_ok=True)
        return open(p, mode)

    @property
    def workdir(self) -> str:
        return self._workdir

    # -- lifecycle ----------------------------------------------------------

    def poll(self) -> int | None:
        """None while any process runs; else last exit code."""
        with self._lock:
            procs = list(self._procs)
        codes = [p.poll() for p in procs]
        if any(c is None for c in codes):
            return None
        return codes[-1] if codes else 0

    def wait(self, raise_on_termination: bool = False) -> int:
        while True:
            code = self.poll()
            if code is not None:
                return code
            if self._remaining() == 0.0:
                self.terminate()
                if raise_on_termination:
                    raise SandboxTimeoutError(self.object_id)
                return -1
            time.sleep(0.05)

    def terminate(self) -> None:
        if self._timeout_timer is not None:
            self._timeout_timer.cancel()
        with self._lock:
            self._terminated = True
            procs = list(self._procs)
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()

    def cleanup(self, remove_dir: bool = True) -> None:
        self.terminate()
        _live_sandboxes.pop(self.object_id, None)
        if remove_dir:
            shutil.rmtree(self._dir, ignore_errors=True)

    def set_tags(self, tags: dict[str, str]) -> None:
        self._tags.update(tags)

    @property
    def tags(self) -> dict[str, str]:
        return dict(self._tags)

    def tunnels(self) -> dict[int, Tunnel]:
        return dict(self._tunnels) if hasattr(self, "_tunnels") else {}


_live_sandboxes: dict[str, Sandbox] = {}


class forward:
    """``with mtpu.forward(port) as tunnel: tunnel.url`` — port tunnel
    context (jupyter_inside_modal.py:9). Local backend: the port is already
    reachable on localhost."""

    def __init__(self, port: int, unencrypted: bool = False):
        self.port = port

    def __enter__(self) -> Tunnel:
        return Tunnel(self.port)

    def __exit__(self, *exc):
        return False
