"""Function — the serverless unit, with every reference invocation mode.

Reference spec (SURVEY.md §2.1 "Invocation modes"):
``.local`` / ``.remote`` / ``.map`` (hello_world.py:56-69), ``.remote_gen``
(generators.py:21), ``.starmap`` (hp_sweep_gpt.py:320), ``.spawn`` + ``.get``
(parallel_execution.py:33-48, long-training.py:153), ``.for_each``
(inference_map.py:39), ``FunctionCall.from_id`` / ``gather``
(poll_delayed_result.py, parallel_execution.py), and async ``.aio`` variants
(08_advanced/dynamic_batching.py:81-93).

Resource/scheduling options mirror ``@app.function(...)``
(unsloth_finetune.py:276-289): ``tpu=`` (our ``gpu=`` analog), image, volumes,
secrets, timeout, retries, max/min_containers, scaledown_window,
single_use_containers, schedule, and the ``@concurrent`` / ``@batched``
markers.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import inspect
import os
import pickle
import queue as _queue
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator

from .._internal import config as _config
from . import executor as _exec
from . import serialization as ser
from .image import DEFAULT_IMAGE, Image
from .resources import TPUSpec, parse_tpu_request
from .retries import Retries, normalize_retries
from .schedules import Schedule


@dataclasses.dataclass
class BatchedConfig:
    max_batch_size: int
    wait_ms: int


def batched(*, max_batch_size: int, wait_ms: int = 10) -> Callable:
    """``@batched`` — server-side dynamic batching (dynamic_batching.py:29)."""

    def deco(fn):
        fn.__mtpu_batched__ = BatchedConfig(max_batch_size, wait_ms)
        return fn

    return deco


def concurrent(*, max_inputs: int, target_inputs: int | None = None) -> Callable:
    """``@concurrent`` — input concurrency per container (text_to_image.py:238).

    Works on functions and on ``@app.cls`` classes (applied under the app
    decorator, like the reference stacks them).
    """

    def deco(fn_or_cls):
        fn_or_cls.__mtpu_concurrent__ = max_inputs
        fn_or_cls.__mtpu_target_concurrent__ = target_inputs or max_inputs
        return fn_or_cls

    return deco


@dataclasses.dataclass
class FunctionSpec:
    """Fully-resolved execution spec for one Function (or one Cls)."""

    tag: str
    app_name: str
    raw_target: Any  # callable, or (cls, lifecycle meta) for Cls pools
    is_cls_method: bool = False
    cls_params_bytes: bytes | None = None
    tpu: list[TPUSpec] = dataclasses.field(default_factory=list)
    cpu: float | None = None
    memory: int | None = None
    image: Image = dataclasses.field(default_factory=lambda: DEFAULT_IMAGE)
    volumes: dict[str, Any] = dataclasses.field(default_factory=dict)
    secrets: list[Any] = dataclasses.field(default_factory=list)
    timeout: float | None = 300.0
    retries: Retries | None = None
    max_containers: int = 8
    min_containers: int = 0
    scaledown_window: float = 60.0
    single_use_containers: bool = False
    max_concurrent_inputs: int = 1
    batched: BatchedConfig | None = None
    schedule: Schedule | None = None
    methods_meta: dict | None = None  # Cls: per-method {batched, is_generator}
    is_generator: bool = False
    web: dict | None = None
    region: str | None = None
    force_inline: bool = False
    cluster_size: int = 0  # >0: gang-scheduled multi-host slice (@clustered)
    cluster_chips_per_host: int | None = None
    #: scheduling class for this function's inputs (interactive|default|
    #: batch); per-call override via .remote(..., priority=)
    priority: str = "default"
    #: bound on queued (undispatched) inputs; None = unbounded. Exceeding it
    #: sheds: pool.submit raises ShedError, the gateway answers 429.
    max_pending_inputs: int | None = None
    enable_memory_snapshot: bool = False
    serialized: bool = False  # ship-by-value parity flag (reference: serialized=True)
    experimental_options: dict = dataclasses.field(default_factory=dict)

    def container_config(self) -> _exec.ContainerConfig:
        env: dict[str, str] = {}
        env.update(self.image.env_vars())
        for s in self.secrets:
            env.update(s.env_vars())
        if self.tpu:
            env["MTPU_TPU_SPEC"] = str(self.tpu[0])
        volumes = []
        for mount_path, vol in self.volumes.items():
            volumes.append((mount_path, str(vol.local_path)))
        sys_paths = self.image.sys_path_additions() + self._source_dirs()
        fn_bytes = ser.function_to_bytes(self.raw_target)
        snapshot_key = snapshot_dir = None
        if self.enable_memory_snapshot and self.is_cls_method:
            # key + store root are resolved client-side so the supervisor (the
            # autoscaler's first-warm-boot gate) and the container agree on
            # exactly which entry a boot will hit
            from ..snapshot.store import (
                compute_snapshot_key,
                default_root,
                source_hash_for,
            )

            snapshot_key = compute_snapshot_key(
                image_digest=self.image.digest(),
                source_hash=source_hash_for(self.raw_target, fn_bytes),
                env=env,
                cls_params=self.cls_params_bytes,
            )
            snapshot_dir = str(default_root())
        return _exec.ContainerConfig(
            function_tag=self.tag,
            fn_bytes=fn_bytes,
            is_cls=self.is_cls_method,
            cls_params=self.cls_params_bytes,
            env=env,
            sys_paths=sys_paths,
            max_concurrent_inputs=self.max_concurrent_inputs,
            volumes=volumes,
            snapshot_key=snapshot_key,
            snapshot_dir=snapshot_dir,
        )

    def batched_for(self, method_name: str) -> "BatchedConfig | None":
        """Batching config for one dispatch target (per-method on a Cls)."""
        if self.is_cls_method and self.methods_meta is not None:
            return (self.methods_meta.get(method_name) or {}).get("batched")
        return self.batched

    def _source_dirs(self) -> list[str]:
        """Dir of the module defining the function/class, so by-reference
        pickles (module-level helpers the remote code calls) resolve in the
        container — the local analog of the platform mounting the user's
        source into the container (SURVEY.md §3.1: container imports module).
        """
        target = self.raw_target[0] if self.is_cls_method else self.raw_target
        try:
            src = inspect.getsourcefile(target)
        except TypeError:
            src = None
        if not src:
            return []
        # walk up past package __init__.py files so 'import pkg.sub' resolves
        # (and so we never put a package's own dir on sys.path, which would
        # let sibling modules shadow stdlib names)
        d = os.path.dirname(os.path.abspath(src))
        while os.path.exists(os.path.join(d, "__init__.py")):
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        return [d]

    def pool_key(self) -> str:
        import hashlib

        params = self.cls_params_bytes or b""
        return f"{self.tag}:{hashlib.sha1(params).hexdigest()[:8]}"


# --------------------------------------------------------------------------
# FunctionCall — spawned-call handle
# --------------------------------------------------------------------------

_local_calls: dict[str, _exec._Call] = {}
_local_calls_lock = threading.Lock()


#: Spawned-call results are retained this long (reference: 7-day retention of
#: spawned results, amazon_embeddings.py:18).
_CALL_RETENTION_S = 7 * 86400
_last_gc = [0.0]


def _calls_dir() -> Path:
    p = _config.state_dir() / "calls"
    p.mkdir(parents=True, exist_ok=True)
    now = time.monotonic()
    if now - _last_gc[0] > 300:  # opportunistic sweep, at most every 5 min
        _last_gc[0] = now
        cutoff = time.time() - _CALL_RETENTION_S
        for f in p.glob("fc-*.pkl"):
            try:
                if f.stat().st_mtime < cutoff:
                    f.unlink()
            except OSError:
                pass
    return p


class FunctionCall:
    """Handle to a spawned input; survives across processes via the state dir.

    Reference: ``call = f.spawn(x)``; later ``call.get(timeout=...)`` or
    ``FunctionCall.from_id(call_id)`` from a *different* process
    (08_advanced/poll_delayed_result.py). Spawned results persist (reference:
    up to 7 days, amazon_embeddings.py:18); ours persist in the state dir
    until garbage-collected.
    """

    def __init__(self, object_id: str):
        self.object_id = object_id
        #: the underlying input id == trace id (``tpurun trace <call_id>``);
        #: None on handles rehydrated via from_id in another process
        self.call_id: str | None = None

    @classmethod
    def _register(cls, call: _exec._Call) -> "FunctionCall":
        object_id = f"fc-{uuid.uuid4().hex[:16]}"
        with _local_calls_lock:
            _local_calls[object_id] = call
        record = _calls_dir() / f"{object_id}.pkl"

        def persist():
            call.done.wait()
            try:
                if call.ok:
                    payload = ("ok", ser.serialize(call.value))
                else:
                    payload = ("err", ser.serialize_exception(call.exc))
                # atomic publish: cross-process readers poll exists()+read
                tmp = record.with_suffix(f".tmp.{os.getpid()}")
                tmp.write_bytes(pickle.dumps(payload))
                os.replace(tmp, record)
            except Exception:
                pass
            finally:
                # result is durable on disk; drop the in-memory handle so
                # long-lived spawn loops don't accumulate _Call objects
                with _local_calls_lock:
                    _local_calls.pop(object_id, None)

        threading.Thread(target=persist, daemon=True).start()
        fc = cls(object_id)
        fc.call_id = call.input_id
        return fc

    @classmethod
    def from_id(cls, object_id: str) -> "FunctionCall":
        return cls(object_id)

    def get(self, timeout: float | None = None):
        with _local_calls_lock:
            call = _local_calls.get(self.object_id)
        if call is not None:
            return call.result(timeout)
        # cross-process: poll the persisted record
        record = _calls_dir() / f"{self.object_id}.pkl"
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if record.exists():
                kind, payload = pickle.loads(record.read_bytes())
                if kind == "ok":
                    return ser.deserialize(payload)
                exc, _tb = ser.deserialize_exception(payload)
                raise exc
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"function call {self.object_id} still running")
            time.sleep(0.05)

    def cancel(self) -> None:
        with _local_calls_lock:
            call = _local_calls.get(self.object_id)
        if call is not None:
            call.cancelled = True

    async def get_async(self, timeout: float | None = None):
        return await asyncio.to_thread(self.get, timeout)


class _FCGather:
    def __call__(self, *calls: FunctionCall):
        return [c.get() for c in calls]

    async def aio(self, *calls: FunctionCall):
        return await asyncio.gather(*(c.get_async() for c in calls))


gather = _FCGather()


# --------------------------------------------------------------------------
# Invoker descriptors: f.remote(...) callable with f.remote.aio(...)
# --------------------------------------------------------------------------


class _Invoker:
    def __init__(self, sync_fn: Callable, aio_fn: Callable | None = None):
        self._sync = sync_fn
        self._aio = aio_fn

    def __call__(self, *args, **kwargs):
        return self._sync(*args, **kwargs)

    def aio(self, *args, **kwargs):
        if self._aio is not None:
            return self._aio(*args, **kwargs)
        return asyncio.to_thread(self._sync, *args, **kwargs)


class _GenInvoker(_Invoker):
    def aio(self, *args, **kwargs):
        sync_gen = self._sync(*args, **kwargs)

        async def agen():
            loop = asyncio.get_running_loop()
            it = iter(sync_gen)
            sentinel = object()
            while True:
                item = await loop.run_in_executor(None, next, it, sentinel)
                if item is sentinel:
                    return
                yield item

        return agen()


# --------------------------------------------------------------------------
# Function
# --------------------------------------------------------------------------


def split_priority(target: Callable, kwargs: dict) -> tuple[str | None, dict]:
    """Pop the reserved ``priority=`` scheduling kwarg from a ``.remote``
    call — UNLESS the user function declares its own ``priority`` parameter
    (or ``**kwargs``), in which case the name belongs to the function and
    scheduling falls back to the spec default."""
    if "priority" not in kwargs:
        return None, kwargs
    try:
        params = inspect.signature(target).parameters
    except (TypeError, ValueError):
        return None, kwargs
    if "priority" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    ):
        return None, kwargs
    from ..scheduling.policy import validate_class

    rest = dict(kwargs)
    # a typo'd class must fail HERE at the call site, not silently degrade
    # to default rank inside the pool
    return validate_class(rest.pop("priority")), rest


class Function:
    """A registered serverless function bound to an App."""

    def __init__(self, app, raw_f: Callable, spec: FunctionSpec):
        self.app = app
        self.raw_f = raw_f
        self.spec = spec
        functools.update_wrapper(self, raw_f)
        self.remote = _Invoker(self._remote)
        self.remote_gen = _GenInvoker(self._remote_gen)
        self.map = _GenInvoker(self._map)
        self.starmap = _GenInvoker(self._starmap)
        self.spawn = _Invoker(self._spawn)
        self.for_each = _Invoker(self._for_each)

    # direct call == local call (matching reference ergonomics for plain fns)
    def __call__(self, *args, **kwargs):
        return self.raw_f(*args, **kwargs)

    def local(self, *args, **kwargs):
        return self.raw_f(*args, **kwargs)

    @property
    def is_generator(self) -> bool:
        return self.spec.is_generator

    def _pool(self):
        from .app import current_run

        return current_run(self.app).pool_for(self.spec)

    def _submit(self, args, kwargs) -> _exec._Call:
        # .remote(..., priority="interactive"): reserved scheduling kwarg
        # (skipped when the user function declares its own `priority`)
        priority, kwargs = split_priority(self.raw_f, kwargs)
        return self._pool().submit("", args, kwargs, priority=priority)

    def _remote(self, *args, **kwargs):
        call = self._submit(args, kwargs)
        if self.spec.is_generator:
            # .remote on a generator function: drain and return list-like
            return list(_drain_gen(call))
        return call.result()

    def _remote_gen(self, *args, **kwargs) -> Iterator:
        call = self._submit(args, kwargs)
        return _drain_gen(call)

    def _spawn(self, *args, **kwargs) -> FunctionCall:
        return FunctionCall._register(self._submit(args, kwargs))

    def _map(
        self,
        *input_iterators: Iterable,
        order_outputs: bool = True,
        return_exceptions: bool = False,
        wrap_returned_exceptions: bool = False,
    ) -> Iterator:
        inputs = zip(*input_iterators) if len(input_iterators) > 1 else (
            (x,) for x in input_iterators[0]
        )
        return self._run_many(
            list(inputs), order_outputs, return_exceptions
        )

    def _starmap(
        self,
        input_iterator: Iterable[tuple],
        *,
        order_outputs: bool = True,
        return_exceptions: bool = False,
    ) -> Iterator:
        return self._run_many(
            [tuple(t) for t in input_iterator], order_outputs, return_exceptions
        )

    def _for_each(self, *input_iterators: Iterable, ignore_exceptions: bool = False):
        for _ in self._map(
            *input_iterators,
            order_outputs=False,
            return_exceptions=ignore_exceptions,
        ):
            pass

    def _run_many(
        self, arg_tuples: list[tuple], order_outputs: bool, return_exceptions: bool
    ) -> Iterator:
        pool = self._pool()
        return run_many(
            lambda args: pool.submit("", args, {}),
            arg_tuples,
            order_outputs,
            return_exceptions,
        )

    # -- web ----------------------------------------------------------------

    def get_web_url(self) -> str | None:
        if self.spec.web is None:
            return None
        from ..web.registry import web_url_for

        return web_url_for(self.spec)

    @property
    def web_url(self) -> str | None:
        return self.get_web_url()

    @staticmethod
    def from_name(app_name: str, name: str, environment_name: str | None = None) -> "Function":
        from .app import App

        return App.lookup(app_name).registered_functions[name]

    def __repr__(self) -> str:
        return f"Function({self.spec.tag!r})"


def run_many(
    submit: Callable[[tuple], _exec._Call],
    arg_tuples: list[tuple],
    order_outputs: bool,
    return_exceptions: bool,
) -> Iterator:
    """Shared fan-out driver for .map/.starmap/.for_each (Function and Cls)."""
    calls = [submit(args) for args in arg_tuples]
    if order_outputs:
        ordered: Iterable[_exec._Call] = calls
    else:
        done_q: _queue.Queue = _queue.Queue()
        for c in calls:
            threading.Thread(
                target=lambda c=c: (c.done.wait(), done_q.put(c)), daemon=True
            ).start()
        ordered = (done_q.get() for _ in range(len(calls)))
    for c in ordered:
        try:
            yield c.result()
        except BaseException as e:
            if return_exceptions:
                yield e
            else:
                raise


def _drain_gen(call: _exec._Call) -> Iterator:
    while True:
        kind, item = call.gen_queue.get()
        if kind == "item":
            yield item
        elif kind == "done":
            return
        else:  # ("error", exc)
            raise item
