"""App — unit of deployment; registry of functions, classes, entrypoints.

Reference spec: ``app = modal.App("name", image=..., secrets=...)``
(hello_world.py:18); ``@app.function`` / ``@app.cls`` /
``@app.local_entrypoint`` decorators; ``with app.run():`` for script-driven
ephemeral apps (import_sklearn.py:51); ``App.lookup(name,
create_if_missing=True)`` for programmatic apps (safe_code_execution.py:21);
``app.registered_functions`` used by the generic profiler wrapper
(torch_profiling.py:131-135); ``modal run/deploy/serve`` CLI (README.md:17-21).

A *run context* owns the container pools; entering one (explicitly via
``app.run()`` or implicitly on the first ``.remote``) is the local analog of
starting an ephemeral app on the platform. ``app.deploy()`` records the app in
the state-dir registry so other processes can ``lookup``/``from_name`` it and
the scheduler daemon can fire its cron/period functions.
"""

from __future__ import annotations

import atexit
import contextlib
import dataclasses
import datetime as _dt
import inspect
import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable

from .._internal import config as _config
from . import executor as _exec
from .cls import Cls, _collect_lifecycle
from .function import BatchedConfig, Function, FunctionSpec
from .image import DEFAULT_IMAGE, Image
from .resources import parse_tpu_request
from .retries import normalize_retries
from .schedules import Schedule


def _registry_path() -> Path:
    return _config.state_dir() / "apps.json"


def _validate_priority(priority: str) -> None:
    from ..scheduling.policy import validate_class

    validate_class(priority)


#: All App objects instantiated in this process, by name (for App.lookup).
_app_instances: dict[str, "App"] = {}


class AppRun:
    """Holds the live container pools for one app run."""

    def __init__(self, app: "App", detach: bool = False):
        self.app = app
        self.detach = detach
        self._pools: dict[str, Any] = {}
        self._lock = threading.Lock()
        self.closed = False

    def pool_for(self, spec: FunctionSpec):
        key = spec.pool_key()
        with self._lock:
            if self.closed:
                raise RuntimeError("app run context is closed")
            pool = self._pools.get(key)
            if pool is None:
                pool = _exec.make_pool(spec, self)
                self._pools[key] = pool
            return pool

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            pools = list(self._pools.values())
        for p in pools:
            p.shutdown()
        # push this run's metric series to the file gateway: the process is
        # ephemeral, so a scraper (or `tpurun metrics`) reads the pushed
        # exposition after we're gone — the pushgateway-for-ephemeral-
        # containers pattern (observability.export).
        try:
            from ..observability.export import push_metrics_file

            push_metrics_file(f"app-{self.app.name}-{os.getpid()}")
        except Exception:
            pass  # metrics must never break shutdown


class _LocalEntrypoint:
    def __init__(self, app: "App", fn: Callable):
        self.app = app
        self.raw_f = fn
        self.__name__ = fn.__name__
        self.__doc__ = fn.__doc__

    def __call__(self, *args, **kwargs):
        with self.app.run():
            return self.raw_f(*args, **kwargs)


class App:
    def __init__(
        self,
        name: str | None = None,
        *,
        image: Image | None = None,
        secrets: list | None = None,
        volumes: dict | None = None,
        include_source: bool = True,
    ):
        self.name = name or "anonymous-app"
        self.image = image or DEFAULT_IMAGE
        self.secrets = list(secrets or [])
        self.volumes = dict(volumes or {})
        self.registered_functions: dict[str, Function] = {}
        self.registered_classes: dict[str, Cls] = {}
        self.registered_entrypoints: dict[str, _LocalEntrypoint] = {}
        self.registered_web_endpoints: list[str] = []
        self._current_run: AppRun | None = None
        self._implicit_run: AppRun | None = None
        _app_instances[self.name] = self

    def __getstate__(self):
        """Serialize the app DEFINITION, not its runtime: live runs hold
        container pools, threads, and locks (unpicklable, and meaningless in
        another process). A Function handle captured in a spawned function's
        globals (launcher patterns: amazon_embeddings.py:108-112) rehydrates
        against the receiving process's own run context."""
        d = self.__dict__.copy()
        d["_current_run"] = None
        d["_implicit_run"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        _app_instances.setdefault(self.name, self)

    # -- decorators ---------------------------------------------------------

    def function(
        self,
        *,
        tpu: str | list[str] | None = None,
        gpu: Any = None,  # explicit error below — this framework is TPU-native
        cpu: float | None = None,
        memory: int | None = None,
        image: Image | None = None,
        volumes: dict | None = None,
        secrets: list | None = None,
        timeout: float | None = 300.0,
        retries=None,
        max_containers: int = 8,
        min_containers: int = 0,
        scaledown_window: float = 60.0,
        single_use_containers: bool = False,
        schedule: Schedule | None = None,
        region: str | None = None,
        name: str | None = None,
        serialized: bool = False,
        priority: str = "default",
        max_pending_inputs: int | None = None,
        enable_memory_snapshot: bool = False,
        experimental_options: dict | None = None,
    ) -> Callable[[Callable], Function]:
        if gpu is not None:
            raise ValueError(
                "this framework is TPU-native: use tpu='v5e-8' (see "
                "modal_examples_tpu.core.resources), not gpu=..."
            )
        _validate_priority(priority)

        def deco(fn: Callable) -> Function:
            fn_name = name or fn.__name__
            cluster_cfg = getattr(fn, "__mtpu_cluster__", None) or {}
            spec = FunctionSpec(
                tag=f"{self.name}.{fn_name}",
                app_name=self.name,
                raw_target=fn,
                tpu=parse_tpu_request(tpu),
                cpu=cpu,
                memory=memory,
                image=image or self.image,
                volumes={**self.volumes, **(volumes or {})},
                secrets=self.secrets + list(secrets or []),
                timeout=timeout,
                retries=normalize_retries(retries),
                max_containers=max_containers,
                min_containers=min_containers,
                scaledown_window=scaledown_window,
                single_use_containers=single_use_containers,
                max_concurrent_inputs=getattr(fn, "__mtpu_concurrent__", 1),
                batched=getattr(fn, "__mtpu_batched__", None),
                schedule=schedule,
                is_generator=inspect.isgeneratorfunction(fn),
                web=getattr(fn, "__mtpu_web__", None),
                region=region,
                cluster_size=cluster_cfg.get("size", 0),
                cluster_chips_per_host=cluster_cfg.get("chips_per_host"),
                priority=priority,
                max_pending_inputs=max_pending_inputs,
                enable_memory_snapshot=enable_memory_snapshot,
                serialized=serialized,
                experimental_options=dict(experimental_options or {}),
            )
            f = Function(self, fn, spec)
            self.registered_functions[fn_name] = f
            if spec.web is not None:
                self.registered_web_endpoints.append(fn_name)
            return f

        return deco

    def cls(
        self,
        *,
        tpu: str | list[str] | None = None,
        gpu: Any = None,
        cpu: float | None = None,
        memory: int | None = None,
        image: Image | None = None,
        volumes: dict | None = None,
        secrets: list | None = None,
        timeout: float | None = 300.0,
        retries=None,
        max_containers: int = 8,
        min_containers: int = 0,
        scaledown_window: float = 60.0,
        priority: str = "default",
        max_pending_inputs: int | None = None,
        enable_memory_snapshot: bool = False,
        experimental_options: dict | None = None,
        region: str | None = None,
    ) -> Callable[[type], Cls]:
        if gpu is not None:
            raise ValueError("TPU-native framework: use tpu=, not gpu=")
        _validate_priority(priority)

        def deco(user_cls: type) -> Cls:
            meta = _collect_lifecycle(user_cls)
            spec = FunctionSpec(
                tag=f"{self.name}.{user_cls.__name__}",
                app_name=self.name,
                raw_target=(user_cls, meta),
                is_cls_method=True,
                tpu=parse_tpu_request(tpu),
                cpu=cpu,
                memory=memory,
                image=image or self.image,
                volumes={**self.volumes, **(volumes or {})},
                secrets=self.secrets + list(secrets or []),
                timeout=timeout,
                retries=normalize_retries(retries),
                max_containers=max_containers,
                min_containers=min_containers,
                scaledown_window=scaledown_window,
                max_concurrent_inputs=getattr(user_cls, "__mtpu_concurrent__", 1),
                methods_meta=meta["methods"],
                region=region,
                priority=priority,
                max_pending_inputs=max_pending_inputs,
                enable_memory_snapshot=enable_memory_snapshot,
                experimental_options=dict(experimental_options or {}),
            )
            c = Cls(self, user_cls, spec, meta)
            self.registered_classes[user_cls.__name__] = c
            return c

        return deco

    def local_entrypoint(self, name: str | None = None) -> Callable:
        def deco(fn: Callable) -> _LocalEntrypoint:
            ep = _LocalEntrypoint(self, fn)
            self.registered_entrypoints[name or fn.__name__] = ep
            return ep

        return deco

    def server(self, **kwargs) -> Callable:
        """``@app.server`` — raw-port low-latency serving (vllm_inference.py:139).

        Implemented in the web layer; see modal_examples_tpu.web.server.
        """
        from ..web.server import make_server_decorator

        return make_server_decorator(self, **kwargs)

    # -- run context --------------------------------------------------------

    @contextlib.contextmanager
    def run(self, detach: bool = False):
        if self._current_run is not None:
            yield self._current_run  # reentrant: reuse the outer context
            return
        run = AppRun(self, detach=detach)
        self._current_run = run
        try:
            yield run
        finally:
            self._current_run = None
            if not detach:
                run.close()

    def _get_or_create_implicit_run(self) -> AppRun:
        if self._implicit_run is None or self._implicit_run.closed:
            self._implicit_run = AppRun(self)
            atexit.register(self._implicit_run.close)
        return self._implicit_run

    # -- deploy / lookup ----------------------------------------------------

    def deploy(self, source_file: str | None = None) -> None:
        """Record this app in the state-dir registry (local control plane)."""
        src = source_file
        if src is None:
            for ep in list(self.registered_entrypoints.values()):
                src = inspect.getsourcefile(ep.raw_f)
                break
            if src is None:
                for f in list(self.registered_functions.values()):
                    src = inspect.getsourcefile(f.raw_f)
                    break
        reg_path = _registry_path()
        try:
            registry = json.loads(reg_path.read_text())
        except (FileNotFoundError, json.JSONDecodeError):
            registry = {}
        registry[self.name] = {
            "source_file": str(src) if src else None,
            "deployed_at": time.time(),
            "functions": sorted(self.registered_functions),
            "classes": sorted(self.registered_classes),
        }
        reg_path.write_text(json.dumps(registry, indent=2))

    @staticmethod
    def lookup(name: str, create_if_missing: bool = False) -> "App":
        # In-process apps first
        app = _app_instances.get(name)
        if app is not None:
            return app
        try:
            registry = json.loads(_registry_path().read_text())
        except (FileNotFoundError, json.JSONDecodeError):
            registry = {}
        entry = registry.get(name)
        if entry and entry.get("source_file"):
            module = load_module_from_path(entry["source_file"])
            for obj in vars(module).values():
                if isinstance(obj, App) and obj.name == name:
                    return obj
        if create_if_missing:
            return App(name)
        raise KeyError(f"app {name!r} not found (deploy it with `tpurun deploy`)")

    # -- schedules ----------------------------------------------------------

    def scheduled_functions(self) -> dict[str, Function]:
        return {
            n: f
            for n, f in self.registered_functions.items()
            if f.spec.schedule is not None
        }

    def run_scheduler(self, duration: float | None = None, poll: float = 1.0) -> int:
        """Fire schedules (Period/Cron) until ``duration`` elapses.

        Returns the number of invocations fired. ``tpurun deploy`` keeps this
        loop alive for deployed apps (reference: schedules fire on deployed
        apps, 05_scheduling/schedule_simple.py).
        """
        fired = 0
        next_fire: dict[str, _dt.datetime] = {}
        now = _dt.datetime.now()
        for tag, f in self.scheduled_functions().items():
            next_fire[tag] = f.spec.schedule.next_fire(now)
        start = time.monotonic()
        with self.run():
            while duration is None or time.monotonic() - start < duration:
                now = _dt.datetime.now()
                for tag, f in self.scheduled_functions().items():
                    if now >= next_fire[tag]:
                        f.spawn()
                        fired += 1
                        next_fire[tag] = f.spec.schedule.next_fire(now)
                time.sleep(poll)
        return fired

    def __repr__(self) -> str:
        return f"App({self.name!r})"


def current_run(app: App) -> AppRun:
    if app._current_run is not None:
        return app._current_run
    return app._get_or_create_implicit_run()


def load_module_from_path(path: str):
    import importlib.util

    p = Path(path)
    mod_name = p.stem.replace("-", "_")
    if mod_name in sys.modules and getattr(
        sys.modules[mod_name], "__file__", None
    ) == str(p):
        return sys.modules[mod_name]
    spec = importlib.util.spec_from_file_location(mod_name, p)
    module = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = module
    parent = str(p.parent)
    if parent not in sys.path:
        sys.path.insert(0, parent)
    spec.loader.exec_module(module)
    return module
