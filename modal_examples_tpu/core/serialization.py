"""Serialization of args/results across the client -> container boundary.

The reference SDK serializes function arguments and results when crossing the
process/network boundary on every ``.remote/.map/.spawn`` call (SURVEY.md
§3.1). We use pickle for plain data and fall back to cloudpickle for
closures/lambdas/``__main__``-defined callables, which is what lets
``tpurun run script.py`` ship entrypoint-local functions to containers.

Exceptions raised in a container are wrapped in :class:`RemoteError` carrying
the remote traceback, mirroring how the reference surfaces user exceptions
with the container-side stack.
"""

from __future__ import annotations

import io
import pickle
import traceback
from typing import Any

import cloudpickle


class SerializationError(Exception):
    pass


class RemoteError(Exception):
    """A user exception re-raised on the client, with the remote traceback."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        base = super().__str__()
        if self.remote_traceback:
            return f"{base}\n--- remote traceback ---\n{self.remote_traceback}"
        return base


def serialize(obj: Any) -> bytes:
    """Pickle ``obj``; cloudpickle fallback for non-importable callables."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        try:
            return cloudpickle.dumps(obj)
        except Exception as e:
            raise SerializationError(
                f"cannot serialize {type(obj).__name__!r} for the remote boundary: {e}"
            ) from e


def deserialize(data: bytes) -> Any:
    return pickle.loads(data)


def serialize_exception(exc: BaseException) -> bytes:
    """Best-effort pickle of the exception itself; else a RemoteError shim."""
    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        payload = pickle.dumps((exc, tb), protocol=pickle.HIGHEST_PROTOCOL)
        # Verify round-trip: some exceptions pickle but fail to unpickle.
        pickle.loads(payload)
        return payload
    except Exception:
        shim = RemoteError(f"{type(exc).__name__}: {exc}", tb)
        return pickle.dumps((shim, tb), protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_exception(data: bytes) -> tuple[BaseException, str]:
    exc, tb = pickle.loads(data)
    return exc, tb


def function_to_bytes(fn: Any) -> bytes:
    """Serialize a callable definition for execution inside a container.

    Module-level functions pickle by reference (the container re-imports the
    defining module — matching the reference's container-imports-module
    semantics, SURVEY.md §3.1); closures and ``__main__`` callables are
    captured by value via cloudpickle.
    """
    buf = io.BytesIO()
    cloudpickle.dump(fn, buf)
    return buf.getvalue()


def function_from_bytes(data: bytes) -> Any:
    return cloudpickle.loads(data)
