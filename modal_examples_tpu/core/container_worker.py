"""Container entrypoint: ``python -m modal_examples_tpu.core.container_worker``.

Launched by the executor supervisor for every container. See
``executor.worker_entry`` for the boot protocol (AF_UNIX connect + config
handshake). Keeping this a dedicated module means a container boots from a
clean interpreter — the client's ``__main__`` is never re-executed, matching
real container semantics (the container imports the function's module, not
the launching script; SURVEY.md §3.1).
"""

from .executor import worker_entry

if __name__ == "__main__":
    worker_entry()
