"""Cron/periodic schedules for deployed functions.

Reference spec: ``schedule=modal.Period(seconds=5)`` and
``modal.Cron("* * * * *")`` (05_scheduling/schedule_simple.py:27,34); daily
jobs like hackernews_alerts.py:97 use ``modal.Cron("0 9 * * *")``. Schedules
fire on *deployed* apps; ``tpurun serve/deploy`` starts the scheduler loop.

The cron parser supports the standard 5-field syntax with ``*``, lists,
ranges, and ``*/step``.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt


class InvalidSchedule(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Period:
    days: float = 0
    hours: float = 0
    minutes: float = 0
    seconds: float = 0

    @property
    def total_seconds(self) -> float:
        return (
            self.days * 86400 + self.hours * 3600 + self.minutes * 60 + self.seconds
        )

    def __post_init__(self):
        if self.total_seconds <= 0:
            raise InvalidSchedule("Period must be positive")

    def next_fire(self, now: _dt.datetime) -> _dt.datetime:
        return now + _dt.timedelta(seconds=self.total_seconds)


_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]  # min hr dom mon dow


def _parse_field(field: str, lo: int, hi: int) -> frozenset[int]:
    values: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step < 1:
                raise InvalidSchedule(f"bad step in cron field {field!r}")
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise InvalidSchedule(f"cron field {field!r} out of range [{lo},{hi}]")
        values.update(range(start, end + 1, step))
    return frozenset(values)


@dataclasses.dataclass(frozen=True)
class Cron:
    expression: str

    def __post_init__(self):
        fields = self.expression.split()
        if len(fields) != 5:
            raise InvalidSchedule(
                f"cron expression needs 5 fields, got {len(fields)}: {self.expression!r}"
            )
        parsed = tuple(
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _FIELD_RANGES)
        )
        object.__setattr__(self, "_fields", parsed)

    def matches(self, t: _dt.datetime) -> bool:
        minute, hour, dom, month, dow = self._fields  # type: ignore[attr-defined]
        return (
            t.minute in minute
            and t.hour in hour
            and t.day in dom
            and t.month in month
            and t.weekday() in _cron_dow(dow)
        )

    def next_fire(self, now: _dt.datetime) -> _dt.datetime:
        """Next minute boundary strictly after ``now`` matching the expression."""
        t = now.replace(second=0, microsecond=0) + _dt.timedelta(minutes=1)
        # 4 years of minutes bounds the scan for any valid expression.
        for _ in range(4 * 366 * 24 * 60):
            if self.matches(t):
                return t
            t += _dt.timedelta(minutes=1)
        raise InvalidSchedule(f"cron expression never fires: {self.expression!r}")


def _cron_dow(dow: frozenset[int]) -> frozenset[int]:
    # cron: 0=Sunday..6=Saturday; datetime.weekday(): 0=Monday..6=Sunday.
    return frozenset((d - 1) % 7 for d in dow)


Schedule = Period | Cron
