"""Retry policies for serverless functions.

Reference spec: ``retries=modal.Retries(initial_delay=0.0, max_retries=10)``
plus ``timeout=`` and ``single_use_containers=True`` drive the
interruption-tolerant training loop in 06_gpu_and_ml/long-training.py:109-137;
a bare integer (``retries=3``) is also accepted (train.py:38-39).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Retries:
    max_retries: int = 2
    backoff_coefficient: float = 2.0
    initial_delay: float = 1.0
    max_delay: float = 60.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_coefficient < 1.0:
            raise ValueError("backoff_coefficient must be >= 1.0")

    def delay_for_attempt(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (1-based)."""
        d = self.initial_delay * (self.backoff_coefficient ** max(0, attempt - 1))
        return min(d, self.max_delay)


def normalize_retries(retries: "Retries | int | None") -> Retries | None:
    if retries is None:
        return None
    if isinstance(retries, Retries):
        return retries
    if isinstance(retries, int):
        return Retries(max_retries=retries, initial_delay=1.0)
    raise TypeError(f"retries must be an int or Retries, got {type(retries)!r}")
