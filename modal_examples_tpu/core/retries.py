"""Retry policies for serverless functions.

Reference spec: ``retries=modal.Retries(initial_delay=0.0, max_retries=10)``
plus ``timeout=`` and ``single_use_containers=True`` drive the
interruption-tolerant training loop in 06_gpu_and_ml/long-training.py:109-137;
a bare integer (``retries=3``) is also accepted (train.py:38-39).

Backoff is exponential with **deterministic, seedable jitter**: a fixed
exponential schedule synchronizes retry storms — N replicas that fail
together retry together, forever (the thundering-herd failure the chaos
harness exercises, docs/faults.md). Passing a per-caller ``key`` (the
executor uses the input id, the disagg transport its transfer id)
decorrelates the waits while keeping every delay reproducible from
``(key, attempt)`` alone — no RNG state, no flaky tests.
"""

from __future__ import annotations

import dataclasses

from ..utils.determinism import unit_float as _unit_float


@dataclasses.dataclass(frozen=True)
class Retries:
    max_retries: int = 2
    backoff_coefficient: float = 2.0
    initial_delay: float = 1.0
    max_delay: float = 60.0
    #: fraction of each delay that jitters DOWNWARD (0 = fixed schedule,
    #: 0.5 = "equal jitter": delay in [d/2, d]). Jitter only ever shortens
    #: a wait, so the exponential schedule stays the worst-case retry
    #: budget: total wait <= sum of the un-jittered delays.
    jitter: float = 0.5

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_coefficient < 1.0:
            raise ValueError("backoff_coefficient must be >= 1.0")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def delay_for_attempt(self, attempt: int, *, key: str | None = None) -> float:
        """Delay before retry number ``attempt`` (1-based).

        Without ``key`` the delay is the bare exponential schedule (exact,
        test-friendly). With ``key`` (callers pass their input/transfer
        id), the delay is deterministically jittered into
        ``[d * (1 - jitter), d]`` so concurrent retriers spread out instead
        of stampeding in lockstep."""
        d = self.initial_delay * (self.backoff_coefficient ** max(0, attempt - 1))
        d = min(d, self.max_delay)
        if key is None or not self.jitter:
            return d
        return d * (1.0 - self.jitter * _unit_float(key, attempt))


def normalize_retries(retries: "Retries | int | None") -> Retries | None:
    if retries is None:
        return None
    if isinstance(retries, Retries):
        return retries
    if isinstance(retries, int):
        return Retries(max_retries=retries, initial_delay=1.0)
    raise TypeError(f"retries must be an int or Retries, got {type(retries)!r}")
