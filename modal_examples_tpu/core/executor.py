"""Container executor: the local control plane for serverless functions.

The reference platform schedules containers for every ``.remote/.map/.spawn``
call — autoscaling a pool per Function, streaming logs, enforcing timeouts,
retrying on failure, and scaling to zero after an idle window (SURVEY.md L3;
vllm_inference.py:139-152 sets scaledown_window/target_concurrency;
long-training.py:109-137 sets retries/timeout/single_use_containers).

This module implements those semantics with supervised worker **processes**
("containers"): spawned (never forked — forking a process that may own a TPU
deadlocks libtpu), fed over pipes with pickled inputs, scaled between
``min_containers`` and ``max_containers``, reaped after ``scaledown_window``
idle seconds, and killed on per-input ``timeout`` with the input retried per
its :class:`~modal_examples_tpu.core.retries.Retries` policy.

Container model:
- one process per container; inside it, up to ``max_concurrent_inputs``
  (``@concurrent``, text_to_image.py:238) threads execute inputs;
- ``@batched`` functions receive grouped inputs: the scheduler coalesces up
  to ``max_batch_size`` queued inputs per dispatch after waiting ``wait_ms``
  (dynamic_batching.py:29,57);
- Cls containers instantiate the user class and run ``@enter`` hooks once
  before serving inputs, and ``@exit`` hooks at shutdown (text_to_image.py:
  92-137) — load-once-serve-many;
- TPU functions serialize on a host-wide TPU lease so two containers never
  fight over the same chips.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue as _queue
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Callable

import inspect
import subprocess
import sys
import tempfile
from multiprocessing.connection import Client, Listener
from pathlib import Path

from .._internal import config as _config
from ..faults import inject as _inject
from ..observability import journal as _journal
from ..observability import metrics as _obs
from ..observability import trace as _tr
from ..scheduling.policy import CLASS_RANK
from ..utils.log import get_logger
from . import serialization as ser
from .retries import Retries

_log = get_logger("executor")

#: host-RSS sampling throttle (process-wide; every pool's tick shares it)
_RSS_SAMPLE_EVERY_S = 2.0
_rss_wall = 0.0
_rss_lock = threading.Lock()


def _maybe_sample_rss() -> None:
    """Sample the supervisor process's RSS into ``mtpu_host_rss_bytes``,
    throttled — scheduler ticks run at 20 Hz per pool."""
    global _rss_wall
    now = time.monotonic()
    with _rss_lock:
        if now - _rss_wall < _RSS_SAMPLE_EVERY_S:
            return
        _rss_wall = now
    _obs.sample_host_rss()


import contextvars

#: the input id being processed by the current container thread
#: (modal.current_input_id parity)
_current_input_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "mtpu-input-id", default=None
)


def current_input_id() -> str | None:
    return _current_input_id.get()


class FunctionTimeoutError(TimeoutError):
    pass


class _ContainerDead(RuntimeError):
    """Raised by dispatch() when racing a container's death.

    ``still_owned`` lists the inputs the dispatcher removed from the
    container's active set itself — only those may be requeued by the caller
    (anything already taken by the reader thread's death path is the death
    path's responsibility; requeueing it too would run the input twice).
    """

    def __init__(self, msg: str, still_owned: list | None = None):
        super().__init__(msg)
        self.still_owned = still_owned or []


class InputCancelled(Exception):
    pass


# --------------------------------------------------------------------------
# Container-side (child process)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ContainerConfig:
    """Everything a container needs to boot, pickled across the spawn."""

    function_tag: str
    fn_bytes: bytes  # cloudpickled callable OR (cls, lifecycle meta) bundle
    is_cls: bool
    cls_params: bytes | None  # pickled dict of modal.parameter overrides
    env: dict[str, str]
    sys_paths: list[str]
    max_concurrent_inputs: int
    volumes: list[tuple[str, str]]  # (mount path, host path)
    # memory snapshots (enable_memory_snapshot=True on a Cls): resolved
    # client-side so supervisor and container agree on the store entry
    snapshot_key: str | None = None
    snapshot_dir: str | None = None


def _mount_volumes(volumes: list[tuple[str, str]]) -> None:
    """Materialize volume mounts as symlinks (local-backend bind mount)."""
    for mount_path, host_path in volumes:
        try:
            if os.path.islink(mount_path):
                if os.readlink(mount_path) == host_path:
                    continue
                os.unlink(mount_path)
            elif os.path.exists(mount_path):
                continue  # a real dir already there; leave it alone
            os.makedirs(os.path.dirname(mount_path) or "/", exist_ok=True)
            os.symlink(host_path, mount_path)
        except OSError as e:
            _log.warning("cannot mount volume at %s: %s", mount_path, e)


def _container_main(conn, cfg_bytes: bytes) -> None:
    """Entry point of a container process."""
    cfg: ContainerConfig = ser.deserialize(cfg_bytes)
    os.environ.update(cfg.env)
    os.environ[_config.TASK_ID_ENV] = f"ta-{uuid.uuid4().hex[:12]}"
    import sys

    for p in cfg.sys_paths:
        if p not in sys.path:
            sys.path.insert(0, p)
    _mount_volumes(cfg.volumes)

    send_lock = threading.Lock()

    def send(msg) -> None:
        with send_lock:
            try:
                conn.send(msg)
            except (BrokenPipeError, OSError):
                os._exit(1)

    exit_hooks: list[Callable] = []
    boot_info: dict = {}
    try:
        target = ser.function_from_bytes(cfg.fn_bytes)
        if cfg.is_cls:
            cls, meta = target  # (user class, lifecycle metadata dict)
            params = ser.deserialize(cfg.cls_params) if cfg.cls_params else {}
            # snapshot-aware boot: restore past snap=True @enter hooks when
            # the store has an entry for this spec, else run them and capture
            from ..snapshot import build_and_enter

            obj, boot_info = build_and_enter(
                cls,
                params,
                meta,
                snapshot_key=cfg.snapshot_key,
                snapshot_dir=cfg.snapshot_dir,
                tag=cfg.function_tag,
            )
            exit_hooks = [getattr(obj, n) for n in meta.get("exit", [])]

            def call_fn(method_name, args, kwargs):
                return getattr(obj, method_name)(*args, **kwargs)

        else:

            def call_fn(method_name, args, kwargs):
                return target(*args, **kwargs)

        send(("ready", boot_info))
    except BaseException as e:  # boot failure
        send(("boot_error", ser.serialize_exception(e)))
        return

    inflight = threading.Semaphore(cfg.max_concurrent_inputs)

    def run_one(
        input_id: str, method_name: str, payload: bytes, trace: dict | None = None
    ) -> None:
        """Execute one input, emitting execute/serialize spans that ship back
        over the pipe and stitch into the caller's trace (the supervisor
        records them before delivering the result, so a trace read right
        after ``.result()`` already sees the child's spans)."""
        _current_input_id.set(input_id)
        spans: list[dict] = []

        def begin(name: str) -> "_tr.Span | None":
            if trace is None:
                return None
            return _tr.Span(
                trace_id=trace["trace_id"],
                name=name,
                parent_id=trace.get("parent_id"),
            )

        def done(sp, status: str = "ok", **attrs) -> None:
            if sp is not None:
                sp.finish(status, **attrs)
                spans.append(sp.to_dict())

        try:
            ex = begin("execute")
            if ex is not None:
                # nested user spans (observability.span) ride the same buffer
                _tr.set_context(
                    _tr.TraceContext(trace["trace_id"], ex.span_id, spans.append)
                )
            try:
                args, kwargs = ser.deserialize(payload)
                result = call_fn(method_name, args, kwargs)
            except BaseException:
                done(ex, "error")
                raise
            if inspect.isgenerator(result):
                ser_s = 0.0
                n_items = 0
                try:
                    while True:
                        try:
                            item = next(result)
                        except StopIteration:
                            break
                        t0 = time.monotonic()
                        out = ser.serialize(item)
                        ser_s += time.monotonic() - t0
                        send(("yield", input_id, out))
                        n_items += 1
                except BaseException:
                    done(ex, "error", items=n_items)
                    raise
                done(ex, "ok", items=n_items)
                sz = begin("serialize")
                if sz is not None:
                    # per-item serialize time accumulated across the stream
                    sz.start = time.time() - ser_s
                    done(sz, "ok", items=n_items, streamed=True)
                if spans:
                    send(("spans", spans))
                send(("gen_done", input_id))
            else:
                done(ex, "ok")
                sz = begin("serialize")
                out = ser.serialize(result)
                done(sz, "ok", bytes=len(out))
                if spans:
                    send(("spans", spans))
                send(("result", input_id, True, out))
        except BaseException as e:
            if spans:
                send(("spans", spans))
            send(("result", input_id, False, ser.serialize_exception(e)))
        finally:
            inflight.release()

    def run_batch(
        input_ids: list[str],
        method_name: str,
        payloads: list[bytes],
        traces: list | None = None,
    ) -> None:
        """Dynamic batching: unzip single-item args, call once with lists."""
        traces = traces or [None] * len(input_ids)
        spans: list[dict] = []

        def phase(name: str, start: float, end: float, status: str) -> None:
            # the batch ran once, but each input's trace gets its own copy of
            # the shared phase span (tagged with the batch size)
            for tr in traces:
                if tr is None:
                    continue
                sp = _tr.Span(
                    trace_id=tr["trace_id"],
                    name=name,
                    parent_id=tr.get("parent_id"),
                    start=start,
                    attrs={"batch_size": len(input_ids)},
                )
                sp.end = end
                sp.status = status
                spans.append(sp.to_dict())

        t_exec = time.time()
        try:
            calls = [ser.deserialize(p) for p in payloads]
            n_args = len(calls[0][0])
            batched_args = [[c[0][i] for c in calls] for i in range(n_args)]
            kw_keys = sorted(calls[0][1])
            batched_kwargs = {k: [c[1][k] for c in calls] for k in kw_keys}
            results = call_fn(method_name, batched_args, batched_kwargs)
            results = list(results)
            if len(results) != len(input_ids):
                raise ValueError(
                    f"@batched function returned {len(results)} outputs for "
                    f"{len(input_ids)} inputs"
                )
            t_ser = time.time()
            phase("execute", t_exec, t_ser, "ok")
            outs = [ser.serialize(r) for r in results]
            phase("serialize", t_ser, time.time(), "ok")
            if spans:
                send(("spans", spans))
            for iid, out in zip(input_ids, outs):
                send(("result", iid, True, out))
        except BaseException as e:
            phase("execute", t_exec, time.time(), "error")
            err = ser.serialize_exception(e)
            if spans:
                send(("spans", spans))
            for iid in input_ids:
                send(("result", iid, False, err))
        finally:
            inflight.release()

    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if msg[0] == "shutdown":
            break
        elif msg[0] == "input":
            _, input_id, method_name, payload, trace = msg
            inflight.acquire()
            threading.Thread(
                target=run_one,
                args=(input_id, method_name, payload, trace),
                daemon=True,
            ).start()
        elif msg[0] == "batch":
            _, input_ids, method_name, payloads, traces = msg
            inflight.acquire()
            threading.Thread(
                target=run_batch,
                args=(input_ids, method_name, payloads, traces),
                daemon=True,
            ).start()

    for hook in exit_hooks:
        try:
            hook()
        except Exception:
            traceback.print_exc()
    try:
        # this process's registry (e.g. engine histograms for a served model
        # living in this container) outlives it via the file push gateway
        from ..observability.export import push_metrics_file

        push_metrics_file(f"container-{cfg.function_tag}-{os.getpid()}")
    except Exception:
        pass  # metrics must never break container shutdown
    try:
        send(("bye",))
    except Exception:
        pass


# --------------------------------------------------------------------------
# Supervisor-side (client process)
# --------------------------------------------------------------------------


class _Call:
    """Client-side handle for one dispatched input (future + stream)."""

    def __init__(self, input_id: str, deadline: float | None, retries: Retries | None):
        self.input_id = input_id
        self.deadline = deadline
        self.retries = retries
        self.attempt = 0
        self.done = threading.Event()
        self.ok: bool | None = None
        self.value: Any = None
        self.exc: BaseException | None = None
        self.gen_queue: _queue.Queue = _queue.Queue()
        self.cancelled = False
        # observability: trace id == input id; the pool opens the root span
        # at submit and registers a finalizer that closes it
        self.trace_id: str | None = None
        self.root_span: "_tr.Span | None" = None
        self._done_callbacks: list[Callable] = []
        self._finalized = False

    def add_done_callback(self, fn: Callable) -> None:
        self._done_callbacks.append(fn)

    def _run_done_callbacks(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        for fn in self._done_callbacks:
            try:
                fn()
            except Exception:
                _log.exception("call done-callback failed")

    def set_result(self, value) -> None:
        self.ok, self.value = True, value
        # finalizers run BEFORE done.set(): a caller unblocked by .result()
        # must find the completed trace on disk
        self._run_done_callbacks()
        self.done.set()

    def set_exception(self, exc: BaseException) -> None:
        self.ok, self.exc = False, exc
        self.gen_queue.put(("error", exc))
        self._run_done_callbacks()
        self.done.set()

    def result(self, timeout: float | None = None):
        if not self.done.wait(timeout):
            raise TimeoutError(f"input {self.input_id} not done after {timeout}s")
        if self.ok:
            return self.value
        raise self.exc


@dataclasses.dataclass
class _QueuedInput:
    call: _Call
    method_name: str
    payload: bytes
    ready_at: float = 0.0  # for retry backoff
    started_at: float | None = None
    # scheduling class (modal_examples_tpu/scheduling): interactive inputs
    # dispatch before default before batch when contending for containers
    priority: str = "default"
    # open phase spans; each is finished + recorded at its phase boundary
    queue_span: "_tr.Span | None" = None
    dispatch_span: "_tr.Span | None" = None

    def trace_ctx(self) -> dict | None:
        """Propagation payload for the container-worker protocol: the child's
        execute/serialize spans parent under this input's dispatch span."""
        if self.dispatch_span is None:
            return None
        return {
            "trace_id": self.call.trace_id,
            "parent_id": self.dispatch_span.span_id,
        }


def _end_dispatch_span(pool, qi: _QueuedInput, status: str, **attrs) -> None:
    """Finish + record an input's dispatch span (shared by the container
    reader's success path and the pool's failure paths)."""
    sp = qi.dispatch_span
    if sp is None:
        return
    qi.dispatch_span = None
    dur = sp.finish(status, **attrs)
    _tr.default_store.record(sp)
    _obs.record_phase(pool.spec.tag, "dispatch", dur)


def worker_entry() -> None:
    """Child-process entry (``python -m modal_examples_tpu.core.container_worker``).

    Containers are plain subprocesses — NOT multiprocessing spawn children —
    so the parent's ``__main__`` is never re-executed in the child (spawn's
    main-module fixup re-runs scripts and re-imports pytest; a real container
    boots from its own entrypoint). The config arrives over an authenticated
    AF_UNIX connection, the same channel used for inputs/results.
    """
    sock = os.environ.pop("MTPU_WORKER_SOCKET")
    authkey = bytes.fromhex(os.environ.pop("MTPU_WORKER_AUTHKEY"))
    conn = Client(sock, family="AF_UNIX", authkey=authkey)
    cfg_bytes = conn.recv()
    _container_main(conn, cfg_bytes)


class _Container:
    _counter = itertools.count()

    def __init__(self, pool, extra_env: dict[str, str] | None = None):
        self.pool = pool
        self.idx = next(self._counter)
        self.extra_env = extra_env or {}
        sock_dir = Path(tempfile.gettempdir()) / "mtpu-socks"
        sock_dir.mkdir(exist_ok=True)
        self._sock_path = str(sock_dir / f"c-{uuid.uuid4().hex[:12]}.sock")
        authkey = os.urandom(16)
        self._listener = Listener(self._sock_path, family="AF_UNIX", authkey=authkey)
        env = dict(os.environ)
        env["MTPU_WORKER_SOCKET"] = self._sock_path
        env["MTPU_WORKER_AUTHKEY"] = authkey.hex()
        pkg_root = str(Path(__file__).resolve().parents[2])
        py_paths = [pkg_root] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p
        ]
        if not pool.spec.tpu or self.extra_env.get("JAX_PLATFORMS") == "cpu":
            # CPU container: don't attach the TPU. The TPU plugin's
            # sitecustomize costs seconds of boot and would contend for the
            # chip; only containers whose Function requests tpu= pay that.
            py_paths = [p for p in py_paths if "axon" not in p]
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS_CPU_OVERRIDE", "cpu")
        env["PYTHONPATH"] = os.pathsep.join(py_paths)
        # persistent XLA compile cache for every container (jax reads the
        # env var natively, keeping core/ jax-free); MTPU_COMPILE_CACHE=0
        # opts out, a path overrides (utils/compile_cache.py is the policy)
        # cache_dir() is jax-free (jax only loads inside
        # enable_compile_cache), so core/ stays jax-free importing it; it
        # also segments the default path by host-CPU fingerprint so foreign
        # AOT entries never load (SIGILL warnings)
        from ..utils.compile_cache import cache_dir as _cache_dir

        cache = _cache_dir()
        if cache is not None:
            env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
            env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
            env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
        env.update(self.extra_env)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "modal_examples_tpu.core.container_worker"],
            env=env,
        )
        self.conn = None
        self.kill_reason: str | None = None
        self.ready = threading.Event()
        self.ever_ready = False
        # observability: boot wall-clock window + snapshot outcome, consumed
        # by the first dispatched input's "boot" span
        self.boot_wall_start = time.time()
        self.ready_wall: float | None = None
        self.boot_info: dict = {}
        self._boot_span_pending = True
        self.retired = False  # single-use containers retire after one dispatch
        self.reaped = False  # autoscaler issued (and journaled) a scale-down
        self.boot_error: BaseException | None = None
        self.active: dict[str, _QueuedInput] = {}
        self.lock = threading.Lock()
        self.last_active = time.monotonic()
        self.dead = False
        self.inputs_served = 0
        self.reader = threading.Thread(target=self._read_loop, daemon=True)
        self.reader.start()
        self.watchdog = threading.Thread(target=self._watch_proc, daemon=True)
        self.watchdog.start()

    def _watch_proc(self) -> None:
        self.proc.wait()
        # If the child died before connecting, unblock the accept().
        if self.conn is None:
            try:
                self._listener.close()
            except Exception:
                pass

    # -- dispatch -----------------------------------------------------------

    def capacity(self) -> int:
        with self.lock:
            if self.dead or self.retired or not self.ready.is_set():
                return 0
            return self.pool.spec_max_concurrent - len(self.active)

    def _trace_dispatch(self, qi: _QueuedInput) -> None:
        """Phase-span bookkeeping at dispatch: close the queue span (observe
        queue wait), emit the boot-or-warm span (cold boots carry the
        snapshot outcome from the ready message), open the dispatch span."""
        call = qi.call
        if call.root_span is None:
            return
        tag = self.pool.spec.tag
        if qi.queue_span is not None:
            wait = qi.queue_span.finish("ok")
            _tr.default_store.record(qi.queue_span)
            qi.queue_span = None
            _obs.record_queue_wait(tag, wait)
        root_id = call.root_span.span_id
        if self._boot_span_pending:
            self._boot_span_pending = False
            sp = _tr.Span(
                trace_id=call.trace_id,
                name="boot",
                parent_id=root_id,
                start=self.boot_wall_start,
                attrs={
                    "mode": "cold",
                    "container": self.idx,
                    "snapshot": (self.boot_info or {}).get("snapshot", "off"),
                },
            )
            sp.end = self.ready_wall or time.time()
            _tr.default_store.record(sp)
            _obs.record_phase(tag, "boot", sp.duration)
        else:
            sp = _tr.Span(
                trace_id=call.trace_id,
                name="boot",
                parent_id=root_id,
                attrs={"mode": "warm", "container": self.idx},
            )
            sp.end = sp.start
            _tr.default_store.record(sp)
        qi.dispatch_span = _tr.Span(
            trace_id=call.trace_id,
            name="dispatch",
            parent_id=root_id,
            attrs={"container": self.idx, "attempt": call.attempt},
        )

    def dispatch(self, qi: _QueuedInput) -> None:
        qi.started_at = time.monotonic()
        # timeout= is per-attempt: the clock starts at dispatch, so a retried
        # input gets a fresh budget rather than inheriting an expired deadline
        if self.pool.spec.timeout:
            qi.call.deadline = qi.started_at + self.pool.spec.timeout
        with self.lock:
            if self.dead:
                raise _ContainerDead(f"container {self.idx} is dead")
            self.active[qi.call.input_id] = qi
            self.last_active = time.monotonic()
        self._trace_dispatch(qi)
        try:
            self.conn.send(
                ("input", qi.call.input_id, qi.method_name, qi.payload,
                 qi.trace_ctx())
            )
        except (BrokenPipeError, OSError) as e:
            _end_dispatch_span(self.pool, qi, "error", reason="container_death")
            with self.lock:
                owned = self.active.pop(qi.call.input_id, None)
            raise _ContainerDead(str(e), [qi] if owned else []) from e

    def dispatch_batch(self, qis: list[_QueuedInput]) -> None:
        now = time.monotonic()
        with self.lock:
            if self.dead:
                raise _ContainerDead(f"container {self.idx} is dead")
            for qi in qis:
                qi.started_at = now
                if self.pool.spec.timeout:
                    qi.call.deadline = now + self.pool.spec.timeout
                self.active[qi.call.input_id] = qi
            self.last_active = now
        for qi in qis:
            self._trace_dispatch(qi)
        try:
            self.conn.send(
                (
                    "batch",
                    [qi.call.input_id for qi in qis],
                    qis[0].method_name,
                    [qi.payload for qi in qis],
                    [qi.trace_ctx() for qi in qis],
                )
            )
        except (BrokenPipeError, OSError) as e:
            for qi in qis:
                _end_dispatch_span(
                    self.pool, qi, "error", reason="container_death"
                )
            with self.lock:
                owned = [
                    qi for qi in qis
                    if self.active.pop(qi.call.input_id, None) is not None
                ]
            raise _ContainerDead(str(e), owned) from e

    # -- reading ------------------------------------------------------------

    def _read_loop(self) -> None:
        try:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return  # child died before connecting (watchdog closed us)
            finally:
                try:
                    self._listener.close()
                    os.unlink(self._sock_path)
                except OSError:
                    pass
            conn.send(ser.serialize(self.pool.container_config))
            self.conn = conn
            while True:
                msg = conn.recv()
                kind = msg[0]
                if kind == "ready":
                    self.ever_ready = True
                    self.ready_wall = time.time()
                    info = msg[1] if len(msg) > 1 else {}
                    self.boot_info = info or {}
                    if info:
                        try:
                            self.pool.on_container_ready(self, info)
                        except Exception:
                            traceback.print_exc()
                    self.ready.set()
                elif kind == "boot_error":
                    exc, _tb = ser.deserialize_exception(msg[1])
                    self.boot_error = exc
                    self.ready.set()
                    break
                elif kind == "yield":
                    _, input_id, payload = msg
                    with self.lock:
                        qi = self.active.get(input_id)
                    if qi:
                        qi.call.gen_queue.put(("item", ser.deserialize(payload)))
                elif kind == "gen_done":
                    _, input_id = msg
                    with self.lock:
                        qi = self.active.pop(input_id, None)
                        self.last_active = time.monotonic()
                        self.inputs_served += 1
                    if qi is not None:
                        _end_dispatch_span(self.pool, qi, "ok")
                        qi.call.gen_queue.put(("done", None))
                        qi.call.set_result(None)
                elif kind == "spans":
                    # child-process phase spans (execute/serialize + any user
                    # spans): record into the owning traces and feed the
                    # per-phase latency histograms
                    _, child_spans = msg
                    for sp in child_spans:
                        _tr.default_store.record(sp)
                        if sp.get("name") in ("execute", "serialize") and sp.get(
                            "end"
                        ) is not None:
                            _obs.record_phase(
                                self.pool.spec.tag,
                                sp["name"],
                                max(0.0, sp["end"] - sp["start"]),
                            )
                elif kind == "result":
                    _, input_id, ok, payload = msg
                    with self.lock:
                        qi = self.active.pop(input_id, None)
                        self.last_active = time.monotonic()
                        self.inputs_served += 1
                    if qi is None:
                        continue
                    if ok:
                        _end_dispatch_span(self.pool, qi, "ok")
                        qi.call.set_result(ser.deserialize(payload))
                    else:
                        exc, _tb = ser.deserialize_exception(payload)
                        self.pool.handle_failure(qi, exc)
                elif kind == "bye":
                    break
        except (EOFError, OSError):
            pass
        finally:
            self._on_death()

    def _on_death(self) -> None:
        with self.lock:
            self.dead = True
            orphans = list(self.active.values())
            self.active.clear()
        self.pool.on_container_dead(self, orphans)

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, graceful: bool = True) -> None:
        with self.lock:
            if self.dead:
                return
        if graceful and self.conn is not None:
            try:
                self.conn.send(("shutdown",))
                return  # reader sees "bye"/EOF and finalizes
            except (BrokenPipeError, OSError):
                pass
        self.kill()

    def kill(self) -> None:
        try:
            self.proc.terminate()
        except Exception:
            pass


class FunctionPool:
    """Autoscaling container pool for one Function (the L3 scheduler unit)."""

    def __init__(self, spec, runner):
        # ``spec`` is a FunctionSpec (function.py); runner is the AppRun owner.
        self.spec = spec
        self.runner = runner
        self.container_config = spec.container_config()
        self.spec_max_concurrent = spec.max_concurrent_inputs
        self.pending: deque[_QueuedInput] = deque()
        self.calls: dict[str, _Call] = {}
        self.containers: list[_Container] = []
        self.boot_crashes = 0
        self._inflight_n = 0  # submitted minus completed (the gauge's source)
        # while True, scale-up is capped at one container so the first warm
        # boot can capture a snapshot every later boot restores from
        self._snapshot_gate = bool(self.container_config.snapshot_key)
        self.lock = threading.Lock()
        self.wake = threading.Condition(self.lock)
        self.closed = False
        self.scheduler = threading.Thread(target=self._schedule_loop, daemon=True)
        self.scheduler.start()

    # -- public API ---------------------------------------------------------

    def submit(
        self, method_name: str, args: tuple, kwargs: dict,
        *, priority: str | None = None,
    ) -> _Call:
        # bounded admission (scheduling PR 4): a spec with
        # max_pending_inputs sheds instead of queueing without limit —
        # the gateway surfaces the ShedError as HTTP 429 + Retry-After
        limit = self.spec.max_pending_inputs
        if limit is not None:
            with self.lock:
                depth = len(self.pending)
            if depth >= limit:
                from ..scheduling.admission import ShedError
                from ..scheduling.policy import DEFAULT_CLASS

                _obs.record_shed(
                    priority or self.spec.priority or DEFAULT_CLASS,
                    "queue_full",
                )
                raise ShedError(
                    "queue_full",
                    1.0 + depth / max(1, limit),
                    f"{self.spec.tag} queue is full ({depth}/{limit})",
                )
        payload = ser.serialize((args, kwargs))
        input_id = f"in-{uuid.uuid4().hex[:16]}"
        call = _Call(input_id, None, self.spec.retries)  # deadline set at dispatch
        qi = _QueuedInput(
            call, method_name, payload, ready_at=time.monotonic(),
            priority=priority or self.spec.priority,
        )
        if _tr.tracing_enabled():
            call.trace_id = input_id
            call.root_span = _tr.Span(
                trace_id=input_id,
                name="call",
                attrs={"function": self.spec.tag, "method": method_name or ""},
            )
            qi.queue_span = _tr.Span(
                trace_id=input_id,
                name="queue",
                parent_id=call.root_span.span_id,
            )
        # register BEFORE queueing: once the input is visible to the
        # scheduler it can complete at any moment, and a finalizer added
        # after completion would never run
        call.add_done_callback(lambda: self._on_call_done(call))
        with self.lock:
            if self.closed:
                raise RuntimeError("app run context is closed")
            self.calls[input_id] = call
            self.pending.append(qi)
            self._inflight_n += 1
            # gauge write under the pool lock: serialized with the
            # completion-side decrement, so the last write always reflects
            # the true count
            _obs.set_inflight(self.spec.tag, self._inflight_n)
            self.wake.notify()
        return call

    def _on_call_done(self, call: _Call) -> None:
        """Completion finalizer (runs inside set_result/set_exception, before
        the caller unblocks): close the root span, observe total latency,
        drop the inflight gauge."""
        with self.lock:
            self._inflight_n = max(0, self._inflight_n - 1)
            _obs.set_inflight(self.spec.tag, self._inflight_n)
        root = call.root_span
        if root is not None:
            call.root_span = None  # idempotence: finalizers never double-record
            dur = root.finish(
                "ok" if call.ok else "error", attempts=call.attempt
            )
            _tr.default_store.record(root)
            _obs.record_phase(self.spec.tag, "total", dur)

    def shutdown(self) -> None:
        with self.lock:
            self.closed = True
            self.wake.notify()
        for c in list(self.containers):
            c.shutdown(graceful=True)
        deadline = time.monotonic() + 5.0
        for c in list(self.containers):
            try:
                c.proc.wait(max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                c.kill()

    def on_container_ready(self, container: "_Container", info: dict) -> None:
        """Boot telemetry from the container's ``ready`` message: cold-start
        snapshot hit/miss accounting (utils/metrics.py -> prometheus)."""
        result = info.get("snapshot")
        if result and result != "off":
            from ..utils.metrics import record_snapshot_boot

            record_snapshot_boot(
                self.spec.tag, result, captured=info.get("captured", False)
            )

    # -- failure/retry ------------------------------------------------------

    def handle_failure(
        self, qi: _QueuedInput, exc: BaseException, reason: str | None = None
    ) -> None:
        """One failed attempt: requeue per the retry policy or surface the
        exception. ``reason`` labels the retry counter/spans —
        timeout | container_death | user_error (inferred when omitted)."""
        if reason is None:
            reason = (
                "timeout"
                if isinstance(exc, FunctionTimeoutError)
                else "user_error"
            )
        _end_dispatch_span(
            self, qi, "error", reason=reason, error=type(exc).__name__
        )
        retries = qi.call.retries
        qi.call.attempt += 1
        if retries is not None and qi.call.attempt <= retries.max_retries:
            # jittered per input id: replicas that failed together must not
            # retry together (thundering herd — docs/faults.md)
            delay = retries.delay_for_attempt(
                qi.call.attempt, key=qi.call.input_id
            )
            _obs.record_retry(self.spec.tag, reason)
            self._trace_requeue(qi, reason, delay, charged=True)
            qi.started_at = None
            qi.ready_at = time.monotonic() + delay
            with self.lock:
                self.pending.append(qi)
                self.wake.notify()
        else:
            qi.call.set_exception(exc)

    def _trace_requeue(
        self, qi: _QueuedInput, reason: str, delay: float, *, charged: bool
    ) -> None:
        """Record an instantaneous retry marker and reopen the queue span —
        the requeued input's wait (backoff included) is queue time again.
        ``charged=False`` marks a free requeue (collateral victim of another
        input's timeout kill) that isn't counted against the retry budget."""
        call = qi.call
        if call.root_span is None:
            return
        sp = _tr.Span(
            trace_id=call.trace_id,
            name="retry",
            parent_id=call.root_span.span_id,
            attrs={
                "reason": reason,
                "attempt": call.attempt,
                "delay_s": round(delay, 4),
                "charged": charged,
            },
        )
        sp.end = sp.start
        _tr.default_store.record(sp)
        qi.queue_span = _tr.Span(
            trace_id=call.trace_id,
            name="queue",
            parent_id=call.root_span.span_id,
            attrs={"requeue": True},
        )

    def on_container_dead(self, container: _Container, orphans: list[_QueuedInput]) -> None:
        with self.lock:
            if container in self.containers:
                self.containers.remove(container)
            self.wake.notify()
        if not container.ever_ready and container.boot_error is None:
            # Crashed before serving anything (e.g. segfault at import).
            self.boot_crashes += 1
            if self.boot_crashes >= 3:
                err = RuntimeError(
                    f"containers for {self.spec.tag} are crash-looping at boot "
                    f"({self.boot_crashes} consecutive failures)"
                )
                with self.lock:
                    doomed = list(self.pending)
                    self.pending.clear()
                for qi in doomed + orphans:
                    _end_dispatch_span(self, qi, "error", reason="crash_loop")
                    qi.call.set_exception(err)
                return
        elif container.ever_ready:
            self.boot_crashes = 0
        if container.boot_error is not None:
            # Boot failures fail every queued input — nothing will ever run.
            with self.lock:
                doomed = list(self.pending)
                self.pending.clear()
            for qi in doomed + orphans:
                _end_dispatch_span(self, qi, "error", reason="boot_error")
                qi.call.set_exception(container.boot_error)
            return
        for qi in orphans:
            timed_out = qi.call.deadline and time.monotonic() >= qi.call.deadline
            if timed_out:
                self.handle_failure(
                    qi,
                    FunctionTimeoutError(
                        f"{self.spec.tag} input exceeded timeout={self.spec.timeout}s"
                    ),
                    reason="timeout",
                )
            elif container.kill_reason == "timeout":
                # Collateral victim of a timeout kill: another input on this
                # @concurrent container blew its deadline. Requeue for free —
                # this input did nothing wrong, so it isn't charged an attempt.
                _end_dispatch_span(
                    self, qi, "error", reason="collateral_timeout"
                )
                self._trace_requeue(qi, "collateral_timeout", 0.0, charged=False)
                qi.started_at = None
                qi.call.deadline = None
                qi.ready_at = time.monotonic()
                with self.lock:
                    self.pending.append(qi)
                    self.wake.notify()
            else:
                self.handle_failure(
                    qi,
                    RuntimeError(
                        f"container for {self.spec.tag} died while processing input"
                    ),
                    reason="container_death",
                )

    # -- scheduling loop ----------------------------------------------------

    def _schedule_loop(self) -> None:
        while True:
            with self.lock:
                if self.closed:
                    return
                self.wake.wait(timeout=0.05)
                if self.closed:
                    return
            try:
                self._tick()
            except Exception:
                traceback.print_exc()

    def _tick(self) -> None:
        now = time.monotonic()
        self._enforce_timeouts(now)
        self._dispatch_ready(now)
        self._autoscale(now)
        _maybe_sample_rss()

    def _journal_decision(
        self, action: str, trigger: str, *, containers_before: int,
        containers_after: int, **extra,
    ) -> None:
        """One autoscaler decision into the journal + the decisions counter
        (never raises; runs inside the scheduler tick)."""
        try:
            with self.lock:
                queue_depth = len(self.pending)
                inflight = self._inflight_n
            _journal.default_journal.record(
                _journal.make_record(
                    function=self.spec.tag,
                    action=action,
                    trigger=trigger,
                    queue_depth=queue_depth,
                    inflight=inflight,
                    containers_before=containers_before,
                    containers_after=containers_after,
                    **extra,
                )
            )
            _obs.record_scaler_decision(self.spec.tag, action)
        except Exception:
            _log.warning("journal write failed", exc_info=True)

    def _enforce_timeouts(self, now: float) -> None:
        for c in list(self.containers):
            with c.lock:
                expired = [
                    qi
                    for qi in c.active.values()
                    if qi.call.deadline is not None and now >= qi.call.deadline
                ]
            if expired:
                # The input holds the container's thread; only a kill frees it.
                # on_container_dead() routes actives through timeout handling.
                # A slow-dying child is re-found by later ticks: count the
                # kill only on the tick that initiates it.
                if c.kill_reason is None:
                    _obs.record_container_kill(self.spec.tag, "timeout")
                    # exclude containers already doomed (kill/reap is
                    # async; dead lands later), so two same-tick kills
                    # journal 3->2 then 2->1, not twice 3->2
                    n_live = len([
                        x for x in self.containers
                        if not x.dead and x.kill_reason is None
                        and not x.reaped
                    ])
                    self._journal_decision(
                        "kill", "timeout",
                        containers_before=n_live,
                        containers_after=n_live - 1,
                        container=c.idx,
                        expired_inputs=len(expired),
                    )
                c.kill_reason = "timeout"
                c.kill()

    def _ready_inputs(self, now: float) -> list[_QueuedInput]:
        ready, cancelled = [], []
        with self.lock:
            n = len(self.pending)
            for _ in range(n):
                qi = self.pending.popleft()
                if qi.call.cancelled:
                    cancelled.append(qi)
                elif qi.ready_at <= now:
                    ready.append(qi)
                else:
                    self.pending.append(qi)
        # completion OUTSIDE the lock: set_exception runs the call's done
        # callbacks (trace finalizer, inflight gauge), which re-take it
        for qi in cancelled:
            qi.call.set_exception(InputCancelled(qi.call.input_id))
        # priority classes: interactive dispatches before default before
        # batch when contending for containers (stable sort keeps FIFO
        # within a class — the engine-side fair-share analog for .remote)
        ready.sort(key=lambda qi: CLASS_RANK.get(qi.priority, 1))
        return ready

    def _dispatch_ready(self, now: float) -> None:
        all_ready = self._ready_inputs(now)
        if not all_ready:
            return
        # split by dispatch target: @batched methods coalesce, others go solo
        batch_groups: dict[str, list[_QueuedInput]] = {}
        ready = []
        for qi in all_ready:
            if self.spec.batched_for(qi.method_name) is not None:
                batch_groups.setdefault(qi.method_name, []).append(qi)
            else:
                ready.append(qi)
        for method_name, group in batch_groups.items():
            self._dispatch_batched(group, now, self.spec.batched_for(method_name))
        for i, qi in enumerate(ready):
            # fault points (docs/faults.md): a container dying mid-input or
            # an input blowing its timeout, routed through the SAME retry
            # path real failures take — handle_failure requeues with
            # jittered backoff or surfaces the exception
            if _inject.fire("executor.container_death"):
                self.handle_failure(
                    qi,
                    RuntimeError(
                        f"injected: container for {self.spec.tag} died "
                        "while processing input"
                    ),
                    reason="container_death",
                )
                continue
            if _inject.fire("executor.timeout"):
                self.handle_failure(
                    qi,
                    FunctionTimeoutError(
                        f"injected: {self.spec.tag} input exceeded its "
                        "timeout"
                    ),
                    reason="timeout",
                )
                continue
            target = next((c for c in self.containers if c.capacity() > 0), None)
            if target is None:
                with self.lock:
                    self.pending.extendleft(reversed(ready[i:]))
                return
            if self.spec.single_use_containers:
                # one input per container: retire from rotation at dispatch
                target.retired = True
            try:
                target.dispatch(qi)
            except _ContainerDead as e:
                with self.lock:
                    self.pending.extendleft(reversed(e.still_owned))

    def _dispatch_batched(self, ready: list[_QueuedInput], now: float, cfg) -> None:
        oldest_wait = max((now - qi.ready_at) for qi in ready) if ready else 0
        full = len(ready) >= cfg.max_batch_size
        waited = oldest_wait * 1000.0 >= cfg.wait_ms
        if not (full or waited):
            with self.lock:
                self.pending.extendleft(reversed(ready))
            return
        while ready:
            batch, ready = ready[: cfg.max_batch_size], ready[cfg.max_batch_size :]
            target = next((c for c in self.containers if c.capacity() > 0), None)
            if target is None:
                with self.lock:
                    self.pending.extendleft(reversed(batch + ready))
                return
            try:
                target.dispatch_batch(batch)
            except _ContainerDead as e:
                with self.lock:
                    self.pending.extendleft(reversed(e.still_owned))

    def _snapshot_pending_first_capture(self) -> bool:
        """True while boots should serialize behind the first warm boot: the
        spec wants memory snapshots but the store has no entry yet, so a
        thundering herd of cold boots would all pay the full @enter cost.
        Once a snapshot exists (or the first boot came up without producing
        one — capture failed or state isn't capturable) the gate opens for
        good."""
        if not self._snapshot_gate:
            return False
        from ..snapshot.store import SnapshotStore

        store = SnapshotStore(root=self.container_config.snapshot_dir)
        if store.has(self.container_config.snapshot_key):
            self._snapshot_gate = False
            return False
        if any(c.ever_ready for c in self.containers):
            self._snapshot_gate = False
            return False
        return True

    def _autoscale(self, now: float) -> None:
        with self.lock:
            pending_n = len(self.pending)
        live = [c for c in self.containers if not c.dead and not c.retired]
        booting = [c for c in live if not c.ready.is_set()]
        free_slots = sum(c.capacity() for c in live) + len(booting) * self.spec_max_concurrent
        # scale up
        want = 0
        if pending_n > free_slots:
            want = min(
                self.spec.max_containers - len(live),
                (pending_n - free_slots + self.spec_max_concurrent - 1)
                // self.spec_max_concurrent,
            )
        if want > 0 and self._snapshot_pending_first_capture():
            want = min(want, max(0, 1 - len(live)))
        if want > 0:
            for _ in range(want):
                self._spawn_container()
            self._journal_decision(
                "scale_up", "queue_pressure",
                containers_before=len(live),
                containers_after=len(live) + want,
                free_slots=free_slots,
                spawned=want,
            )
        # keep min_containers warm (snapshot gate: warm one first, the rest
        # boot as restores once the capture lands)
        warm_spawned = 0
        while len([c for c in self.containers if not c.dead]) < self.spec.min_containers:
            if (
                self._snapshot_pending_first_capture()
                and len([c for c in self.containers if not c.dead]) >= 1
            ):
                break
            self._spawn_container()
            warm_spawned += 1
        if warm_spawned:
            n_live = len([c for c in self.containers if not c.dead])
            self._journal_decision(
                "scale_up", "min_containers",
                containers_before=n_live - warm_spawned,
                containers_after=n_live,
                spawned=warm_spawned,
            )
        # scale down
        idle_cut = now - self.spec.scaledown_window
        for c in list(self.containers):
            if c.dead:
                continue
            with c.lock:
                idle = not c.active and c.last_active < idle_cut
                spent = c.retired and not c.active and c.inputs_served > 0
                idle_age = now - c.last_active
            # count only containers not already doomed: shutdown is async
            # (dead lands when the reader sees EOF), so an already-reaped
            # container must neither satisfy min_containers nor inflate the
            # journaled pool trajectory when several reap in one tick
            live_n = len([
                x for x in self.containers
                if not x.dead and not x.reaped and x.kill_reason is None
            ])
            if (idle or spent) and (spent or live_n > self.spec.min_containers):
                if not c.reaped:
                    # journal once per container: later ticks re-send the
                    # graceful shutdown but record no new decision
                    c.reaped = True
                    self._journal_decision(
                        "scale_down",
                        "single_use_spent" if spent else "idle",
                        containers_before=live_n,
                        containers_after=live_n - 1,
                        container=c.idx,
                        idle_ages=[idle_age],
                        scaledown_window_s=self.spec.scaledown_window,
                    )
                c.shutdown(graceful=True)

    def _spawn_container(self) -> None:
        c = _Container(self)
        self.containers.append(c)


# --------------------------------------------------------------------------
# Cluster gang scheduler — one logical call fans to n co-scheduled hosts
# --------------------------------------------------------------------------


class ClusterPool:
    """Gang scheduling for ``@clustered(size=n)`` functions (SURVEY.md §3.4).

    One ``.remote()`` boots n containers (the "hosts" of the slice), injects
    rank/coordinator env (the cluster-info analog of
    simple_torch_cluster.py:101-111), dispatches the same input to all, and
    resolves with rank 0's return value once every rank finishes. Any rank
    failing fails the call and tears the slice down — a dead host kills the
    whole slice, as on a real pod.

    Local simulation: each host is a CPU-backed process whose visible device
    count equals chips_per_host, so jax.distributed + a global Mesh run for
    real across processes.
    """

    def __init__(self, spec, runner):
        self.spec = spec
        self.runner = runner
        self.container_config = spec.container_config()
        self.spec_max_concurrent = 1
        self.size = spec.cluster_size
        self.chips_per_host = spec.cluster_chips_per_host or (
            spec.tpu[0].chips_per_host if spec.tpu else 1
        )
        self.closed = False
        self._lock = threading.Lock()
        self._active_containers: list[_Container] = []

    def submit(
        self, method_name: str, args: tuple, kwargs: dict,
        *, priority: str | None = None,
    ) -> _Call:
        del priority  # gang slices run one call at a time; nothing to order
        if self.closed:
            raise RuntimeError("app run context is closed")
        call = _Call(f"in-{uuid.uuid4().hex[:16]}", None, self.spec.retries)
        threading.Thread(
            target=self._run_gang, args=(call, method_name, args, kwargs), daemon=True
        ).start()
        return call

    # _Container callbacks ---------------------------------------------------

    def handle_failure(
        self, qi: _QueuedInput, exc: BaseException, reason: str | None = None
    ) -> None:
        qi.call.set_exception(exc)

    def on_container_ready(self, container, info: dict) -> None:
        pass  # gang hosts are plain functions; no snapshot boots to record

    def on_container_dead(self, container, orphans: list[_QueuedInput]) -> None:
        err = container.boot_error or RuntimeError(
            f"cluster host rank={container.extra_env.get('MTPU_CLUSTER_RANK')} died"
        )
        for qi in orphans:
            qi.call.set_exception(err)

    # gang logic -------------------------------------------------------------

    def _run_gang(self, call: _Call, method_name, args, kwargs) -> None:
        while True:
            try:
                self._run_gang_once(call, method_name, args, kwargs)
                return
            except BaseException as e:
                call.attempt += 1
                r = self.spec.retries
                if (
                    r is not None
                    and call.attempt <= r.max_retries
                    and not call.cancelled
                    and not self.closed
                    # generators stream through the caller's queue as they
                    # run; a retry would duplicate already-delivered items
                    and not self.spec.is_generator
                ):
                    time.sleep(
                        r.delay_for_attempt(call.attempt, key=call.input_id)
                    )
                    continue
                call.set_exception(e)
                return

    def _run_gang_once(self, call: _Call, method_name, args, kwargs) -> None:
        import re
        import socket

        # jax-free: parallel.cluster holds only env-var names + dataclasses,
        # and modal_examples_tpu.parallel lazy-loads its jax-importing modules
        from ..parallel import cluster as _cluster

        containers: list[_Container] = []
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                coord_port = s.getsockname()[1]
            ips = ",".join(["127.0.0.1"] * self.size)
            payload = ser.serialize((args, kwargs))
            base_flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                "",
                os.environ.get("XLA_FLAGS", ""),
            ).strip()
            for rank in range(self.size):
                if self.closed:
                    raise RuntimeError("app run context is closed")
                extra = {
                    _cluster.RANK_ENV: str(rank),
                    _cluster.SIZE_ENV: str(self.size),
                    _cluster.COORD_ENV: f"127.0.0.1:{coord_port}",
                    _cluster.IPS_ENV: ips,
                    _cluster.CHIPS_ENV: str(self.chips_per_host),
                    # local simulation: every host is a CPU device mesh
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": (
                        base_flags
                        + f" --xla_force_host_platform_device_count={self.chips_per_host}"
                    ).strip(),
                }
                c = _Container(self, extra_env=extra)
                containers.append(c)
                with self._lock:
                    self._active_containers.append(c)

            boot_deadline = time.monotonic() + 120.0
            while True:
                if call.cancelled:
                    raise InputCancelled(call.input_id)
                dead = next(
                    (c for c in containers if c.dead or c.boot_error is not None),
                    None,
                )
                if dead is not None:
                    raise dead.boot_error or RuntimeError(
                        "cluster host died during boot"
                    )
                if all(c.ready.is_set() for c in containers):
                    break
                if time.monotonic() > boot_deadline:
                    raise TimeoutError("cluster hosts failed to boot within 120s")
                time.sleep(0.05)

            rank_calls = []
            deadline = (
                time.monotonic() + self.spec.timeout if self.spec.timeout else None
            )
            for rank, c in enumerate(containers):
                sub = _Call(f"{call.input_id}-r{rank}", deadline, None)
                if rank == 0:
                    # rank 0's yields stream straight through to the caller,
                    # so @clustered generator functions work like plain ones
                    sub.gen_queue = call.gen_queue
                qi = _QueuedInput(sub, method_name, payload)
                c.dispatch(qi)
                rank_calls.append(sub)
            # fail fast: any rank failing (or dying) kills the whole slice —
            # don't block on rank 0 while another rank deadlocks a collective
            pending = set(rank_calls)
            while pending:
                if call.cancelled:
                    raise InputCancelled(call.input_id)
                if deadline is not None and time.monotonic() > deadline:
                    raise FunctionTimeoutError(
                        f"{self.spec.tag} slice exceeded timeout={self.spec.timeout}s"
                    )
                for sub in list(pending):
                    if sub.done.wait(0.02):
                        pending.discard(sub)
                        if not sub.ok:
                            raise sub.exc
            call.set_result(rank_calls[0].value)
        finally:
            for c in containers:
                c.shutdown(graceful=True)
            deadline = time.monotonic() + 5.0
            for c in containers:
                try:
                    c.proc.wait(max(0.05, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    c.kill()
            with self._lock:
                for c in containers:
                    if c in self._active_containers:
                        self._active_containers.remove(c)

    def shutdown(self) -> None:
        self.closed = True
        with self._lock:
            containers = list(self._active_containers)
        for c in containers:
            c.kill()
        deadline = time.monotonic() + 5.0
        for c in containers:
            try:
                c.proc.wait(max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass


# --------------------------------------------------------------------------
# Inline backend — caller-process execution with serialization round-trip
# --------------------------------------------------------------------------


class InlinePool:
    """Runs inputs in the caller process (``MTPU_BACKEND=inline``).

    Preserves the serialization boundary (args/results round-trip through
    pickle) and retry semantics, but shares the caller's interpreter — the
    mode used for single-chip benches where the caller owns the TPU, matching
    how the reference's ``.local()`` behaves but for every invocation kind.
    """

    def __init__(self, spec, runner):
        self.spec = spec
        self.runner = runner
        self._obj = None
        self._exit_hooks: list[Callable] = []
        self._lock = threading.Lock()
        self._fn = None

    def _ensure_target(self):
        with self._lock:
            if self._fn is not None:
                return self._fn
            cfg = self.spec.container_config()
            _mount_volumes(cfg.volumes)
            os.environ.update(cfg.env)
            target = ser.function_from_bytes(cfg.fn_bytes)
            if cfg.is_cls:
                from ..snapshot import build_and_enter

                cls, meta = target
                params = ser.deserialize(cfg.cls_params) if cfg.cls_params else {}
                obj, boot_info = build_and_enter(
                    cls,
                    params,
                    meta,
                    snapshot_key=cfg.snapshot_key,
                    snapshot_dir=cfg.snapshot_dir,
                    tag=cfg.function_tag,
                )
                if boot_info.get("snapshot", "off") != "off":
                    from ..utils.metrics import record_snapshot_boot

                    record_snapshot_boot(
                        self.spec.tag,
                        boot_info["snapshot"],
                        captured=boot_info.get("captured", False),
                    )
                self._obj = obj
                self._exit_hooks = [getattr(obj, n) for n in meta.get("exit", [])]

                def call_fn(method_name, args, kwargs):
                    return getattr(obj, method_name)(*args, **kwargs)

            else:

                def call_fn(method_name, args, kwargs):
                    return target(*args, **kwargs)

            self._fn = call_fn
            return call_fn

    def submit(
        self, method_name: str, args: tuple, kwargs: dict,
        *, priority: str | None = None,
    ) -> _Call:
        del priority  # inline backend runs the call in-process, immediately
        call = _Call(f"in-{uuid.uuid4().hex[:16]}", None, self.spec.retries)
        if _tr.tracing_enabled():
            call.trace_id = call.input_id
            call.root_span = _tr.Span(
                trace_id=call.input_id,
                name="call",
                attrs={
                    "function": self.spec.tag,
                    "method": method_name or "",
                    "backend": "inline",
                },
            )
            call.add_done_callback(lambda: self._finalize_trace(call))

        def phase_span(name: str, start: float, status: str = "ok", **attrs):
            if call.root_span is None:
                return
            sp = _tr.Span(
                trace_id=call.trace_id,
                name=name,
                parent_id=call.root_span.span_id,
                start=start,
                attrs=attrs,
            )
            sp.finish(status)
            _tr.default_store.record(sp)
            _obs.record_phase(self.spec.tag, name, sp.duration)

        def run():
            payload = ser.serialize((args, kwargs))
            attempt = 0
            while True:
                try:
                    a, kw = ser.deserialize(payload)
                    boot_needed = self._fn is None
                    t0 = time.time()
                    fn = self._ensure_target()
                    if boot_needed:
                        phase_span("boot", t0, mode="inline")
                    t0 = time.time()
                    try:
                        result = fn(method_name, a, kw)
                        if inspect.isgenerator(result):
                            n_items = 0
                            for item in result:
                                call.gen_queue.put(
                                    ("item", ser.deserialize(ser.serialize(item)))
                                )
                                n_items += 1
                            phase_span("execute", t0, items=n_items)
                            call.gen_queue.put(("done", None))
                            call.set_result(None)
                        else:
                            phase_span("execute", t0)
                            t0 = time.time()
                            value = ser.deserialize(ser.serialize(result))
                            phase_span("serialize", t0)
                            call.set_result(value)
                    except BaseException:
                        phase_span("execute", t0, status="error")
                        raise
                    return
                except BaseException as e:
                    attempt += 1
                    call.attempt = attempt
                    r = self.spec.retries
                    if r is not None and attempt <= r.max_retries:
                        _obs.record_retry(self.spec.tag, "user_error")
                        time.sleep(
                            min(
                                r.delay_for_attempt(
                                    attempt, key=call.input_id
                                ),
                                0.1,
                            )
                        )
                        continue
                    exc, _tb = ser.deserialize_exception(ser.serialize_exception(e))
                    call.set_exception(exc)
                    return

        threading.Thread(target=run, daemon=True).start()
        return call

    def _finalize_trace(self, call: _Call) -> None:
        root = call.root_span
        if root is None:
            return
        call.root_span = None
        dur = root.finish("ok" if call.ok else "error", attempts=call.attempt)
        _tr.default_store.record(root)
        _obs.record_phase(self.spec.tag, "total", dur)

    def shutdown(self) -> None:
        for hook in self._exit_hooks:
            try:
                hook()
            except Exception:
                traceback.print_exc()


def make_pool(spec, runner):
    if spec.cluster_size > 0:  # any @clustered function, including size=1
        return ClusterPool(spec, runner)
    if _config.backend() == "inline" or spec.force_inline:
        return InlinePool(spec, runner)
    return FunctionPool(spec, runner)
