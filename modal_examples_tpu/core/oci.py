"""OCI image-layout export for the Image DSL.

The reference's platform builds real container images from its
``modal.Image`` chains (02_building_containers; the builder runs
server-side). This is the TPU framework's offline equivalent: serialize
an :class:`~.image.Image` into a spec-valid **OCI Image Layout**
(opencontainers/image-spec v1.0) that any registry/runtime tool
(skopeo, podman, crane) can consume — without a docker daemon and
without network:

- ``add_local_dir`` / ``add_local_file`` / ``add_local_python_source``
  layers become real gzip'd tar layer blobs (deterministic: sorted
  entries, zeroed mtimes, fixed uid/gid — identical inputs give
  identical digests, the content-addressed build-cache property);
- ``env`` / ``workdir`` / ``entrypoint`` layers land in the image
  config (no filesystem blob);
- network-dependent steps (``pip_install`` / ``apt_install`` /
  ``run_commands`` / ``run_function`` — unexecutable in this zero-egress
  environment) are recorded as ``empty_layer`` history entries carrying
  the exact command a connected builder would run, so the recipe
  survives in the artifact's provenance.

Layout per the spec::

    dest/
      oci-layout          {"imageLayoutVersion": "1.0.0"}
      index.json          -> manifest descriptor
      blobs/sha256/<hex>  config, manifest, layer tars
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
import tarfile
import uuid
from pathlib import Path

from .image import Image

MEDIA_CONFIG = "application/vnd.oci.image.config.v1+json"
MEDIA_MANIFEST = "application/vnd.oci.image.manifest.v1+json"
MEDIA_LAYER = "application/vnd.oci.image.layer.v1.tar+gzip"


def _blob(dest: Path, data: bytes) -> tuple[str, int]:
    """Write a small blob under blobs/sha256/<digest>; returns
    (digest, size). Layer tars stream via :func:`_write_layer_blob`."""
    digest = "sha256:" + hashlib.sha256(data).hexdigest()
    p = dest / "blobs" / "sha256" / digest.split(":", 1)[1]
    p.parent.mkdir(parents=True, exist_ok=True)
    if not p.exists():
        p.write_bytes(data)
    return digest, len(data)


class _HashingWriter:
    """write()-only tee: hashes everything passing through to ``sink``."""

    def __init__(self, sink):
        self._sink = sink
        self.hash = hashlib.sha256()
        self._pos = 0

    def write(self, b) -> int:
        self.hash.update(b)
        self._sink.write(b)
        self._pos += len(b)
        return len(b)

    def tell(self) -> int:  # tarfile (PAX) tracks offsets via tell()
        return self._pos

    def flush(self) -> None:  # gzip/tarfile call this on close
        self._sink.flush()


def _write_layer_blob(
    dest: Path, entries: list[tuple[str, Path]]
) -> tuple[str, int, str]:
    """Stream a deterministic gzip'd tar layer into the blob store;
    returns (digest, size, diff_id of the UNCOMPRESSED tar).

    ``entries`` maps archive paths to local files/dirs (which must
    exist — a missing path raises instead of silently exporting an
    empty layer). Determinism: sorted paths, mtime 0, uid/gid 0, gzip
    mtime 0; the exec bit is the only mode bit carried from the source
    (an entrypoint script stripped to 0644 couldn't exec in a runtime).
    Nothing is buffered whole — tar streams through the diff_id hasher
    into gzip, gzip streams through the blob hasher to disk — so
    multi-GB weight layers don't triple in RAM.
    """
    expanded: list[tuple[str, Path]] = []
    for arcname, local in entries:
        local = Path(local)
        if local.is_dir():
            for f in sorted(local.rglob("*")):
                if f.is_file():
                    rel = f.relative_to(local)
                    expanded.append((f"{arcname.rstrip('/')}/{rel}", f))
        elif local.is_file():
            expanded.append((arcname, local))
        else:
            raise FileNotFoundError(
                f"add_local source {str(local)!r} does not exist"
            )
    expanded.sort(key=lambda e: e[0])

    blob_dir = dest / "blobs" / "sha256"
    blob_dir.mkdir(parents=True, exist_ok=True)
    # unique per-writer staging name: a fixed ".layer.tmp" raced when two
    # exports shared a dest (one writer's replace() shipped the other's
    # half-written bytes under a wrong digest); the rename into the
    # content-addressed final name stays atomic either way
    tmp = blob_dir / f".layer.{uuid.uuid4().hex}.tmp"
    try:
        with open(tmp, "wb") as raw:
            outer = _HashingWriter(raw)  # hashes the COMPRESSED blob
            with gzip.GzipFile(fileobj=outer, mode="wb", mtime=0) as gz:
                inner = _HashingWriter(gz)  # hashes the UNCOMPRESSED tar
                with tarfile.open(
                    fileobj=inner, mode="w", format=tarfile.PAX_FORMAT
                ) as tf:
                    seen_dirs: set[str] = set()
                    for arcname, local in expanded:
                        arcname = arcname.lstrip("/")
                        parts = arcname.split("/")[:-1]
                        for i in range(1, len(parts) + 1):
                            d = "/".join(parts[:i])
                            if d and d not in seen_dirs:
                                seen_dirs.add(d)
                                ti = tarfile.TarInfo(d)
                                ti.type = tarfile.DIRTYPE
                                ti.mode = 0o755
                                ti.mtime = 0
                                tf.addfile(ti)
                        ti = tarfile.TarInfo(arcname)
                        ti.size = local.stat().st_size
                        ti.mode = (
                            0o755 if os.access(local, os.X_OK) else 0o644
                        )
                        ti.mtime = 0
                        with open(local, "rb") as f:
                            tf.addfile(ti, f)
                diff_id = "sha256:" + inner.hash.hexdigest()
            digest = "sha256:" + outer.hash.hexdigest()
        size = tmp.stat().st_size
        final = blob_dir / digest.split(":", 1)[1]
        if final.exists():
            tmp.unlink()
        else:
            tmp.replace(final)
    finally:
        tmp.unlink(missing_ok=True)  # no orphaned staging file on failure
    return digest, size, diff_id


def export_oci(
    image: Image,
    dest: str | Path,
    *,
    tag: str = "latest",
    architecture: str = "amd64",
    os_name: str = "linux",
) -> dict:
    """Export ``image`` as an OCI image layout at ``dest``.

    Returns a summary dict (manifest digest, layer count, history).
    """
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)

    history: list[dict] = []
    diff_ids: list[str] = []
    layer_descriptors: list[dict] = []
    env: dict[str, str] = {}
    workdir: str | None = None
    entrypoint: list[str] | None = None

    for layer in image.layers:
        kind, payload = layer.kind, layer.payload
        if kind == "env":
            env.update(dict(payload))
            history.append(_hist(f"ENV {dict(payload)}", empty=True))
        elif kind == "workdir":
            workdir = payload[0]
            history.append(_hist(f"WORKDIR {workdir}", empty=True))
        elif kind == "entrypoint":
            entrypoint = list(payload)
            history.append(_hist(f"ENTRYPOINT {entrypoint}", empty=True))
        elif kind == "add_local":
            mode = payload[0]
            if mode == "pysource":
                import importlib.util

                entries = []
                for mod in payload[1:]:
                    spec = importlib.util.find_spec(mod)
                    if spec is None or spec.origin is None:
                        raise FileNotFoundError(f"module {mod!r} not found")
                    if spec.submodule_search_locations:
                        entries.append(
                            (f"/root/{mod}",
                             Path(spec.origin).parent)
                        )
                    else:
                        entries.append((f"/root/{mod}.py", Path(spec.origin)))
                created_by = f"ADD (pysource) {list(payload[1:])}"
            else:
                local, remote = payload[1], payload[2]
                entries = [(remote, Path(local))]
                created_by = f"ADD ({mode}) {local} {remote}"
            digest, size, diff_id = _write_layer_blob(dest, entries)
            diff_ids.append(diff_id)
            layer_descriptors.append(
                {"mediaType": MEDIA_LAYER, "digest": digest, "size": size}
            )
            history.append(_hist(created_by))
        else:
            # base / pip / apt / run_commands / run_function: the step a
            # connected builder would execute, preserved as provenance
            history.append(
                _hist(f"{kind.upper()} {json.dumps(list(map(str, payload)))}",
                      empty=True)
            )

    if not layer_descriptors:
        # the image spec requires a base layer at index 0; a chain with no
        # local content gets an empty scratch layer so runtimes accept it
        digest, size, diff_id = _write_layer_blob(dest, [])
        diff_ids.append(diff_id)
        layer_descriptors.append(
            {"mediaType": MEDIA_LAYER, "digest": digest, "size": size}
        )
        history.append(_hist("SCRATCH (no local-content layers)"))

    config = {
        "architecture": architecture,
        "os": os_name,
        "config": {
            **({"Env": [f"{k}={v}" for k, v in env.items()]} if env else {}),
            **({"WorkingDir": workdir} if workdir else {}),
            **({"Entrypoint": entrypoint} if entrypoint else {}),
            "Labels": {
                "org.mtpu.image.digest": image.digest(),
            },
        },
        "rootfs": {"type": "layers", "diff_ids": diff_ids},
        "history": history,
    }
    cfg_bytes = json.dumps(config, sort_keys=True).encode()
    cfg_digest, cfg_size = _blob(dest, cfg_bytes)

    manifest = {
        "schemaVersion": 2,
        "mediaType": MEDIA_MANIFEST,
        "config": {
            "mediaType": MEDIA_CONFIG, "digest": cfg_digest, "size": cfg_size,
        },
        "layers": layer_descriptors,
    }
    man_bytes = json.dumps(manifest, sort_keys=True).encode()
    man_digest, man_size = _blob(dest, man_bytes)

    (dest / "oci-layout").write_text(
        json.dumps({"imageLayoutVersion": "1.0.0"})
    )
    index = {
        "schemaVersion": 2,
        "manifests": [
            {
                "mediaType": MEDIA_MANIFEST,
                "digest": man_digest,
                "size": man_size,
                "annotations": {"org.opencontainers.image.ref.name": tag},
            }
        ],
    }
    (dest / "index.json").write_text(json.dumps(index, sort_keys=True))
    return {
        "manifest_digest": man_digest,
        "config_digest": cfg_digest,
        "n_layers": len(layer_descriptors),
        "n_history": len(history),
    }


def _hist(created_by: str, empty: bool = False) -> dict:
    h = {"created_by": created_by}
    if empty:
        h["empty_layer"] = True
    return h
