"""``tpurun`` CLI — run / deploy / serve, the analog of ``modal run`` etc.

Reference spec: ``modal run 01_getting_started/hello_world.py``
(README.md:17-21); auto-generated CLI flags from the ``local_entrypoint``
signature ("Arguments ... automatically get converted into CLI flags",
unsloth_finetune.py:356-360, 380-441); ``modal run --detach``
(long-training.py:168); ``modal deploy`` / ``modal serve``.

Usage:
    tpurun run path/to/script.py [::entrypoint] [--flag value ...]
    tpurun run --detach script.py
    tpurun deploy script.py            # register + keep scheduler alive
    tpurun serve script.py             # host web endpoints
    tpurun secret create NAME K=V ...
    tpurun app list
    tpurun snapshot [list | inspect KEY | clear [KEY]]   # memory-snapshot store
    tpurun trace [ID [--perfetto] | list [--limit N]]  # call/request traces
    tpurun explain REQUEST_ID          # request lifecycle narrative (either id kind)
    tpurun benchdiff OLD NEW [--threshold PCT]  # BENCH json regression diff
    tpurun metrics [--json]            # merged pushed prometheus expositions
    tpurun metrics --watch S [--rate]  # live tsdb deltas (flight recorder)
    tpurun tsdb [--series NAME]        # on-disk metrics history (MTPU_TSDB=1)
    tpurun alerts [--last N]           # alert rules + fire/clear history
    tpurun incidents [list|show|capture]  # incident bundles
    tpurun scaler [N] [--function TAG] # autoscaler decision journal
    tpurun sched [--watch S]           # live class queues, shed rates, router
    tpurun top [--watch S]             # live serving summary + SLO burn rates
    tpurun disagg [--watch S]          # replica roles, migrations, KV tiers
    tpurun chaos [--last N]            # fault-injection episodes + invariants
    tpurun fleet [--last N]            # fleet-autoscaler decisions + boots
    tpurun usage [N] [--json]          # per-tenant usage meters + roofline MFU/MBU
    tpurun canary [N] [--json]         # golden-set probe results + drift streaks
"""

from __future__ import annotations

import argparse
import inspect
import json
import os
import re
import sys

from .._internal import config as _config


def _build_entrypoint_parser(fn, prog: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, description=fn.__doc__)
    sig = inspect.signature(fn)
    for name, param in sig.parameters.items():
        flag = "--" + name.replace("_", "-")
        ann = param.annotation
        required = param.default is inspect.Parameter.empty
        default = None if required else param.default
        if ann is bool or isinstance(default, bool):
            p.add_argument(
                flag,
                default=default if default is not None else False,
                action=argparse.BooleanOptionalAction,
            )
        else:
            typ = ann if ann in (int, float, str) else (type(default) if default is not None and type(default) in (int, float, str) else str)
            p.add_argument(flag, type=typ, default=default, required=required)
    return p


_NEGATIVE_NUMBER = re.compile(r"^-\d+(\.\d+)?$")


def _pop_flag(
    argv: list[str], flag: str, usage: str
) -> tuple[list[str], str | None]:
    """Extract ``<flag> VALUE`` from argv; returns (rest, value_or_None).
    A flag present without its value — or followed by another flag-shaped
    token (``--x``/``-o``; negative numbers pass) — exits with ``usage``."""
    if flag not in argv:
        return argv, None
    i = argv.index(flag)
    if i + 1 >= len(argv):
        raise SystemExit(usage)
    value = argv[i + 1]
    if value.startswith("-") and not _NEGATIVE_NUMBER.match(value):
        raise SystemExit(usage)
    return argv[:i] + argv[i + 2 :], value


def _pop_dir_flag(argv: list[str], usage: str) -> tuple[list[str], str | None]:
    """Extract ``--dir PATH`` from argv; returns (rest, path_or_None)."""
    return _pop_flag(argv, "--dir", usage)


def _load_app(path: str):
    from .app import App, load_module_from_path

    module = load_module_from_path(path)
    apps = [v for v in vars(module).values() if isinstance(v, App)]
    if not apps:
        raise SystemExit(f"no App found in {path}")
    return module, apps[0]


def cmd_run(argv: list[str]) -> int:
    detach = False
    if argv and argv[0] == "--detach":
        detach = True
        argv = argv[1:]
    if not argv:
        raise SystemExit("usage: tpurun run [--detach] script.py[::entrypoint] [flags]")
    target, *flags = argv
    ep_name = None
    if "::" in target:
        target, ep_name = target.split("::", 1)
    module, app = _load_app(target)
    if ep_name is None:
        if len(app.registered_entrypoints) == 1:
            ep_name = next(iter(app.registered_entrypoints))
        elif "main" in app.registered_entrypoints:
            ep_name = "main"
        elif app.registered_entrypoints:
            raise SystemExit(
                f"multiple entrypoints {sorted(app.registered_entrypoints)}; "
                f"pick one with script.py::name"
            )
    if ep_name is None:
        # no local_entrypoint: if exactly one registered function, invoke it
        if len(app.registered_functions) == 1:
            fn = next(iter(app.registered_functions.values()))
            with app.run(detach=detach):
                print(fn.remote())
            return 0
        raise SystemExit("no local_entrypoint found")
    ep = app.registered_entrypoints[ep_name]
    parser = _build_entrypoint_parser(ep.raw_f, prog=f"tpurun run {target}")
    ns = parser.parse_args(flags)
    with app.run(detach=detach):
        ep.raw_f(**vars(ns))
    return 0


def cmd_deploy(argv: list[str]) -> int:
    keep_alive = "--no-scheduler" not in argv
    argv = [a for a in argv if a != "--no-scheduler"]
    if not argv:
        raise SystemExit("usage: tpurun deploy script.py")
    path = argv[0]
    _module, app = _load_app(path)
    app.deploy(source_file=path)
    print(f"deployed app {app.name!r} "
          f"({len(app.registered_functions)} functions, "
          f"{len(app.registered_classes)} classes)")
    if keep_alive and app.scheduled_functions():
        print(f"scheduler running for {sorted(app.scheduled_functions())} (ctrl-c to stop)")
        try:
            app.run_scheduler()
        except KeyboardInterrupt:
            pass
    return 0


def cmd_serve(argv: list[str]) -> int:
    if not argv:
        raise SystemExit("usage: tpurun serve script.py [--port N] [--timeout S]")
    path = argv[0]
    port = 0
    timeout = None
    import os

    if "--port" in argv:
        port = int(argv[argv.index("--port") + 1])
    if "--timeout" in argv:
        timeout = float(argv[argv.index("--timeout") + 1])
    elif os.environ.get("MTPU_SERVE_TIMEOUT"):
        # test-harness bound, analog of MODAL_SERVE_TIMEOUT (run_example.py:28)
        timeout = float(os.environ["MTPU_SERVE_TIMEOUT"])
    _module, app = _load_app(path)
    from ..web.gateway import Gateway, wait_for_port

    with app.run():
        urls = []
        if app.registered_web_endpoints:
            gw = Gateway(app, port=port).start()
            urls += [f"{gw.base_url}/{label}" for label in gw.routes]
        for name, handle in getattr(app, "registered_servers", {}).items():
            urls.append(handle.serve())
        # @web_server(port) functions start their own server when invoked
        for name, fn in app.registered_functions.items():
            web = fn.spec.web or {}
            if web.get("type") == "web_server":
                fn.raw_f()  # user code binds the port (thread/subprocess)
                if wait_for_port("127.0.0.1", web["port"], web.get("startup_timeout", 30)):
                    urls.append(f"http://127.0.0.1:{web['port']}")
                else:
                    print(f"warning: {name} never opened port {web['port']}")
        if not urls:
            raise SystemExit("no web endpoints or servers registered")
        for u in urls:
            print(f"serving: {u}")
        import time

        try:
            if timeout is None:
                while True:
                    time.sleep(3600)
            else:
                time.sleep(timeout)
        except KeyboardInterrupt:
            pass
    return 0


def cmd_secret(argv: list[str]) -> int:
    from ..storage.secret import Secret

    if len(argv) >= 2 and argv[0] == "create":
        name = argv[1]
        env = dict(kv.split("=", 1) for kv in argv[2:])
        Secret.create(name, env)
        print(f"secret {name!r} created with keys {sorted(env)}")
        return 0
    raise SystemExit("usage: tpurun secret create NAME KEY=VALUE ...")


def cmd_examples(argv: list[str]) -> int:
    """List or run the example corpus (internal/run_example.py parity:
    subprocess per example with a timeout bound)."""
    from ..utils.docs import get_examples, repo_root

    examples = get_examples()
    if not argv or argv[0] == "list":
        for e in examples:
            print(e.path)
        return 0
    if argv[0] == "run":
        import subprocess
        import tempfile

        timeout = 600.0
        cli_timeout = "--timeout" in argv
        if cli_timeout:
            timeout = float(argv[argv.index("--timeout") + 1])
        pattern = argv[1] if len(argv) > 1 and not argv[1].startswith("-") else ""
        targets = [e for e in examples if pattern in str(e.path)]
        if not targets:
            raise SystemExit(f"no examples match {pattern!r}")
        failures = []
        for e in targets:
            env = dict(os.environ)
            env.setdefault("MTPU_STATE_DIR", tempfile.mkdtemp(prefix="mtpu-ex-"))
            # cheap-mode defaults (the reference's frontmatter env overrides,
            # SURVEY §4): CI runs on CPU unless the caller opts into a chip
            env.setdefault("MTPU_TPU", "")
            for k, v in e.env.items():  # per-example frontmatter env
                env.setdefault(k, str(v))
            # precedence: explicit CLI flag > frontmatter > default
            eff_timeout = timeout if cli_timeout else (e.timeout or timeout)
            print(f"=== {e.path} ===", flush=True)
            try:
                proc = subprocess.run(
                    [sys.executable, "-m", "modal_examples_tpu", "run",
                     str(repo_root() / e.path)],
                    timeout=eff_timeout,
                    env=env,
                )
                if proc.returncode != 0:
                    failures.append(str(e.path))
            except subprocess.TimeoutExpired:
                failures.append(f"{e.path} (timeout {eff_timeout}s)")
        if failures:
            print(f"FAILED ({len(failures)}/{len(targets)}):")
            for f in failures:
                print(" ", f)
            return 1
        print(f"all {len(targets)} example(s) passed")
        return 0
    raise SystemExit("usage: tpurun examples [list | run [pattern] [--timeout S]]")


def cmd_docs(argv: list[str]) -> int:
    """Render the literate examples to markdown (the examples ARE the docs —
    internal/utils.py render_example_md parity)."""
    from pathlib import Path

    from ..utils.docs import get_examples, render_example_md, repo_root

    out_dir = Path(argv[0]) if argv else repo_root() / "docs"
    n = 0
    for e in get_examples():
        src = (repo_root() / e.path).read_text()
        md = render_example_md(src)
        target = out_dir / e.path.with_suffix(".md")
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(md)
        n += 1
    index = []
    # hand-written guides live next to the rendered examples; the index
    # links both so regeneration never clobbers the guide entries
    guides = sorted(
        p.name for p in out_dir.glob("*.md") if p.name != "index.md"
    )
    if guides:
        index.append("# Guides\n")
        for g in guides:
            title = g.removesuffix(".md").replace("_", " ")
            index.append(f"- [{title}]({g})")
        index.append("")
    index.append("# Examples\n")
    for e in get_examples():
        index.append(f"- [{e.module_name}]({e.path.with_suffix('.md')})")
    (out_dir / "index.md").write_text("\n".join(index) + "\n")
    print(f"rendered {n} example docs to {out_dir}")
    return 0


def cmd_snapshot(argv: list[str]) -> int:
    """Inspect the memory-snapshot store (modal_examples_tpu.snapshot).

    list     — one line per entry: key, size, age, last use, function tag
    inspect  — full meta.json for one key (manifest, rebuild markers, ...)
    clear    — delete one entry (``clear KEY``) or every entry (``clear``)

    ``--dir PATH`` overrides the store root (default: MTPU_SNAPSHOT_DIR or
    ``<state_dir>/snapshots``).
    """
    from ..snapshot.store import SnapshotStore

    argv, root = _pop_dir_flag(argv, "usage: tpurun snapshot ... --dir PATH")
    store = SnapshotStore(root=root)
    sub = argv[0] if argv else "list"
    if sub == "list":
        entries = store.entries()
        if not entries:
            print(f"no snapshots in {store.root}")
            return 0
        import time as _time

        now = _time.time()
        print(f"{'KEY':<34} {'SIZE':>9} {'AGE':>8} {'USED':>8}  FUNCTION")
        for e in entries:
            size_kb = e.get("size_bytes", 0) / 1024
            age = now - e.get("created_at", now)
            used = now - e.get("last_used", now)
            tag = (e.get("manifest") or {}).get("tag", "")
            print(
                f"{e['key']:<34} {size_kb:>7.1f}kB {age:>7.0f}s {used:>7.0f}s  {tag}"
            )
        return 0
    if sub == "inspect":
        if len(argv) < 2:
            raise SystemExit("usage: tpurun snapshot inspect KEY")
        meta = store.inspect(argv[1])
        if meta is None:
            raise SystemExit(f"no snapshot {argv[1]!r} in {store.root}")
        print(json.dumps(meta, indent=2))
        return 0
    if sub == "clear":
        if len(argv) >= 2:
            ok = store.delete(argv[1])
            print(f"{'deleted' if ok else 'no such entry'}: {argv[1]}")
            return 0 if ok else 1
        n = store.clear()
        print(f"cleared {n} snapshot(s) from {store.root}")
        return 0
    raise SystemExit("usage: tpurun snapshot [list | inspect KEY | clear [KEY]] [--dir PATH]")


def cmd_trace(argv: list[str]) -> int:
    """Render one trace as an indented span tree — either id namespace:
    executor calls (``in-...``, ``FunctionCall.call_id``) and serving
    requests (``req-...``, ``x-mtpu-request-id``) live in the same store,
    and a unique id PREFIX resolves too.

    trace ID           — the spans of one call/request
    trace ID --perfetto [-o FILE] [--profile SNAP.json] [--tsdb]
                       — emit the trace as Chrome-trace/Perfetto JSON
                         (loads in chrome://tracing and ui.perfetto.dev;
                         request traces get one track per replica).
                         ``--profile`` merges a saved hot-path profiler
                         snapshot (the gateway's ``/profile`` payload, or
                         a bare {replica: {ticks, compiles}} map) as
                         tick-phase counter tracks + compile slices on
                         the owning replica tracks; ``--tsdb`` rides the
                         on-disk flight-recorder window overlapping the
                         spans along as counter tracks
    trace list [--limit N]
                       — most recently active traces, newest first
    ``--dir PATH`` overrides the trace root (default ``<state_dir>/traces``;
    ``os.pathsep``-separated roots merge per-replica stores, like explain).
    """
    from ..observability import reqtrace as _reqtrace
    from ..observability.trace import TraceStore

    argv, root = _pop_dir_flag(argv, "usage: tpurun trace ... --dir PATH")
    stores = (
        [TraceStore(root=p) for p in root.split(os.pathsep) if p]
        if root
        else [TraceStore()]
    )
    store = stores[0]
    if not argv or argv[0] == "list":
        rest, limit_s = _pop_flag(
            argv[1:], "--limit", "usage: tpurun trace list [--limit N]"
        )
        if limit_s is None and rest:  # bare N still accepted
            limit_s, rest = rest[0], rest[1:]
        limit = int(limit_s) if limit_s is not None else 20
        ids = store.list_traces(limit=limit)
        if not ids:
            print(f"no traces in {store.root}")
            return 0
        for tid in ids:
            spans = store.read(tid)
            roots = [s for s in spans if s.get("parent_id") is None]
            head = roots[0] if roots else (spans[0] if spans else {})
            attrs = head.get("attrs") or {}
            dur = (head.get("end") or 0) - (head.get("start") or 0)
            status = head.get("status", "?")
            print(
                f"{tid}  {attrs.get('function', '?'):<24} "
                f"{dur * 1000:>9.1f}ms  {status}  ({len(spans)} spans)"
            )
        return 0
    # by-id: resolve either namespace (whitelisted token, no raw-path
    # fallback) and MERGE the given stores — a per-replica-store fleet's
    # request trace renders/exports complete, not one store's slice
    trace_id = _reqtrace.resolve(argv[0], stores=stores)
    spans = _reqtrace.read_trace(trace_id, stores=stores) if trace_id else []
    if not spans:
        raise SystemExit(f"no trace {argv[0]!r} in {store.root}")
    if "--perfetto" in argv:
        from ..observability.export import spans_to_chrome_trace

        usage_p = (
            "usage: tpurun trace ID --perfetto [-o FILE] "
            "[--profile SNAP.json] [--tsdb]"
        )
        argv, out_file = _pop_flag(argv, "-o", usage_p)
        argv, prof_file = _pop_flag(argv, "--profile", usage_p)
        with_tsdb = "--tsdb" in argv
        argv = [a for a in argv if a != "--tsdb"]
        tsdb = None
        if with_tsdb:
            # the on-disk flight-recorder window overlapping the spans
            # (±30 s) rides along as counter tracks next to the tick-phase
            # tracks (docs/observability.md#metrics-history)
            from ..observability import timeseries as _tsm

            at = [s.get("start") or 0.0 for s in spans]
            at += [s.get("end") or 0.0 for s in spans]
            at = [t for t in at if t]
            if at:
                tsdb = _tsm.read_window(min(at) - 30.0, max(at) + 30.0)
        profile = None
        if prof_file:
            from pathlib import Path as _Path

            doc_in = json.loads(_Path(prof_file).read_text())
            # accept the gateway's /profile payload or a bare
            # {replica: {ticks, compiles}} map
            nodes = doc_in.get("replicas", doc_in)
            profile = {
                name: node.get("perfetto", node)
                for name, node in nodes.items()
                if isinstance(node, dict)
            }
        doc = spans_to_chrome_trace(
            spans, trace_id, profile=profile, tsdb=tsdb
        )
        if out_file:
            from pathlib import Path as _Path

            _Path(out_file).write_text(json.dumps(doc, indent=1))
            print(
                f"wrote {len(doc['traceEvents'])} events to {out_file} "
                "(open in chrome://tracing or ui.perfetto.dev)"
            )
        else:
            print(json.dumps(doc))
        return 0
    spans.sort(key=lambda s: (s.get("start") or 0.0))
    by_parent: dict = {}
    for s in spans:
        by_parent.setdefault(s.get("parent_id"), []).append(s)
    t0 = min(s.get("start") or 0.0 for s in spans)

    def render(span: dict, depth: int) -> None:
        dur = ((span.get("end") or span["start"]) - span["start"]) * 1000
        rel = (span["start"] - t0) * 1000
        attrs = span.get("attrs") or {}
        extras = " ".join(f"{k}={v}" for k, v in attrs.items())
        mark = "" if span.get("status") == "ok" else f" [{span.get('status')}]"
        print(
            f"{'  ' * depth}{span['name']:<{24 - 2 * min(depth, 8)}} "
            f"+{rel:>8.1f}ms {dur:>9.1f}ms{mark}"
            + (f"  {extras}" if extras else "")
        )
        for child in by_parent.get(span.get("span_id"), []):
            render(child, depth + 1)

    print(f"trace {trace_id}")
    for s in by_parent.get(None, []):
        render(s, 0)
    # spans whose parent never landed (e.g. the container died before its
    # dispatch span closed) still print, flat, rather than vanishing
    known = {s.get("span_id") for s in spans}
    for s in spans:
        pid = s.get("parent_id")
        if pid is not None and pid not in known:
            render(s, 0)
    return 0


def cmd_explain(argv: list[str]) -> int:
    """Merge one request's spans across trace stores and render the
    lifecycle narrative (docs/observability.md):

        $ tpurun explain req-4f2a...
        request req-4f2a...: serving request trace — stop in 412.0ms ...
          +   0.0ms  queued 12.1ms (class=interactive, replica dec-0)
          +  12.3ms  placed: prefill=pre-0 decode=dec-0
          +  13.0ms  prefill on pre-0 340.2ms (512 prompt tokens)
          ...

    Takes either id namespace — a serving request id (``req-…``, from the
    ``x-mtpu-request-id`` response header) or an executor call id
    (``in-…``) — full or unique prefix, and says which kind it found.
    ``--dir`` accepts one or more store roots (``os.pathsep``-separated)
    for merging per-replica trace dirs; default is ``<state_dir>/traces``.
    """
    from ..observability import reqtrace as _reqtrace
    from ..observability.trace import TraceStore

    usage = "usage: tpurun explain REQUEST_ID [--dir PATH[:PATH...]]"
    argv, root = _pop_dir_flag(argv, usage)
    if not argv:
        raise SystemExit(usage)
    stores = (
        [TraceStore(root=p) for p in root.split(os.pathsep) if p]
        if root
        else None
    )
    rid = _reqtrace.resolve(argv[0], stores=stores)
    if rid is None:
        raise SystemExit(f"no trace matching {argv[0]!r}")
    spans = _reqtrace.read_trace(rid, stores=stores)
    for line in _reqtrace.explain_lines(spans, rid):
        print(line)
    return 0


def cmd_benchdiff(argv: list[str]) -> int:
    """Round-over-round bench regression diff: compare two BENCH json
    files section-by-section (tok/s, ttft/tpot p95, migration p95,
    shed_rate, per-config throughputs) and exit 1 past the threshold —
    the automatic companion of a revalidation run (ROADMAP #1);
    ``benchmarks/bench_diff.py`` is the same tool as a script."""
    from ..utils.bench_diff import run_diff

    return run_diff(argv)


def cmd_profile(argv: list[str]) -> int:
    """Hot-path time attribution (docs/observability.md#hot-path-profiling):
    the scheduler-tick phase table (p50/p95 per catalog.TICK_PHASES entry),
    the host-vs-device overhead fraction, and the compile ledger's biggest
    builds — from the pushed metrics files plus
    ``<state_dir>/compiles.jsonl``. Engines emit these series only under
    ``MTPU_PROFILE`` (bench configs opt in), so an empty table means no
    profiled engine has pushed yet. jax-free by construction.

    profile [N]        — phase table + top N ledger compiles (default 10)
    profile --json     — the machine-readable payload
    ``--dir PATH`` overrides the state-dir root (``metrics/`` +
    ``compiles.jsonl`` live under it).
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability import profiler as _prof
    from ..observability.export import pushed_jobs
    from ..utils.prometheus import merge_expositions, parse_exposition

    argv, root = _pop_dir_flag(argv, "usage: tpurun profile [N] [--json]")
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    top_n = int(argv[0]) if argv else 10

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    merged = parse_exposition(merge_expositions(jobs)) if jobs else None
    ledger = _prof.read_ledger(
        path=Path(root) / "compiles.jsonl" if root else None, n=2000
    )
    builds = [r for r in ledger if r.get("event") == "end"]
    unfinished = _prof.unfinished_builds(ledger)

    phases: dict = {}
    ratio = None
    decode_steps = None
    tokens_per_dispatch = None
    spec_gamma = None
    spec_accept = None
    spec_tpd = None
    lookups: dict = {}
    roofline: dict = {}
    if merged is not None:
        # roofline position (docs/observability.md#roofline-and-usage-
        # accounting): the usage meter's achieved-vs-peak gauges per phase
        for series, key in (
            (C.MFU, "mfu"),
            (C.HBM_BW_UTIL, "mbu"),
            (C.ACHIEVED_TFLOPS, "tflops"),
        ):
            for labels, v in merged.series(series):
                roofline.setdefault(labels.get("phase", "?"), {})[key] = v
        for phase in C.TICK_PHASES + (C.TICK_TOTAL_PHASE,):
            q = merged.histogram_quantiles(
                C.TICK_PHASE_SECONDS, quantiles=(0.5, 0.95),
                aggregate={"phase": phase},
            )
            if q:
                phases[phase] = {
                    "p50": q["p50"], "p95": q["p95"], "count": q["count"],
                }
        # a 0..1 fraction must never sum across jobs: show the worst
        ratio = merged.peak(C.HOST_OVERHEAD_RATIO) or None
        # macro-step decode (docs/multistep.md): configured N + the
        # harvested tokens-per-dispatch — gauges, so peak, never sum
        decode_steps = merged.peak(C.MULTISTEP_DECODE_STEPS) or None
        tokens_per_dispatch = (
            merged.peak(C.MULTISTEP_TOKENS_PER_DISPATCH) or None
        )
        # fused speculative rounds (docs/speculative.md#series): dispatched
        # γ p50 + acceptance — gauges, so peak, never sum
        spec_gamma = merged.peak(C.SPEC_GAMMA) or None
        spec_accept = merged.peak(C.SPEC_ACCEPTANCE_RATE) or None
        spec_tpd = merged.peak(C.SPEC_TOKENS_PER_DISPATCH) or None
        for labels, v in merged.series(C.COMPILES_TOTAL):
            entry = lookups.setdefault(
                labels.get("program", "?"), {"hit": 0, "miss": 0}
            )
            entry[labels.get("cache", "miss")] = int(v)

    top = sorted(
        builds, key=lambda r: r.get("seconds") or 0.0, reverse=True
    )[:top_n]
    if as_json:
        print(json.dumps({
            "host_overhead_ratio": ratio,
            "decode_steps": decode_steps,
            "tokens_per_dispatch": tokens_per_dispatch,
            "spec_gamma": spec_gamma,
            "spec_acceptance_rate": spec_accept,
            "spec_tokens_per_dispatch": spec_tpd,
            "roofline": roofline,
            "phases": phases,
            "compile_lookups": lookups,
            "compile_total_s": round(
                sum(r.get("seconds") or 0.0 for r in builds), 3
            ),
            "compiles_n": len(builds),
            "top_compiles": top,
            "unfinished_builds": unfinished,
        }))
        return 0

    if ratio is not None:
        print(f"host overhead ratio: {ratio:.3f} (1 - device-blocked/total)")
    if decode_steps is not None:
        tpd = (
            f"{tokens_per_dispatch:.1f}"
            if tokens_per_dispatch is not None else "-"
        )
        print(
            f"macro-step decode: N={decode_steps:.0f} configured, "
            f"{tpd} tokens/dispatch"
        )
    if spec_gamma is not None or spec_accept:
        acc = f"{spec_accept:.2f}" if spec_accept is not None else "-"
        stpd = f"{spec_tpd:.1f}" if spec_tpd is not None else "-"
        print(
            f"speculative decode: gamma p50 {spec_gamma or 0:.0f}, "
            f"acceptance {acc}, {stpd} tokens/round"
        )
    tot = roofline.get("total")
    if tot is not None:
        bound = (
            "compute-bound"
            if tot.get("mfu", 0.0) >= tot.get("mbu", 0.0)
            else "bandwidth-bound"
        )
        print(
            f"roofline: MFU {tot.get('mfu', 0.0):.4f}  "
            f"MBU {tot.get('mbu', 0.0):.4f}  "
            f"{tot.get('tflops', 0.0):.3f} TFLOP/s achieved ({bound})"
        )
    if phases:
        print(f"{'PHASE':<18} {'P50 ms':>9} {'P95 ms':>9} {'TICKS':>7}")
        for phase in list(C.TICK_PHASES) + [C.TICK_TOTAL_PHASE]:
            q = phases.get(phase)
            if q:
                print(
                    f"{phase:<18} {q['p50'] * 1000:>9.3f} "
                    f"{q['p95'] * 1000:>9.3f} {q['count']:>7}"
                )
    else:
        print(
            "no tick-phase series in pushed metrics "
            "(run a bench or an engine with MTPU_PROFILE=1 first)"
        )
    if lookups:
        print("\ncompile-cache lookups per program (miss=fresh build):")
        for program, entry in sorted(lookups.items()):
            print(
                f"  {program:<16} miss={entry['miss']:<5} hit={entry['hit']}"
            )
    if top:
        print(f"\ntop compiles ({len(builds)} ledgered builds):")
        for r in top:
            print(
                f"  {r.get('seconds', 0.0):>8.3f}s  "
                f"{r.get('program', '?'):<16} {r.get('shape_key', '?'):<14} "
                f"({r.get('replica', '?')})"
            )
    if unfinished:
        # the ≥40-slot ceiling diagnosis: a begin event with no end means
        # the build crashed or hung — name it loudly
        print("\nUNFINISHED builds (began, never completed — crash/hang?):")
        for r in unfinished:
            print(
                f"  {r.get('program', '?')} {r.get('shape_key', '?')} "
                f"on {r.get('replica', '?')}"
            )
    return 0


def cmd_usage(argv: list[str]) -> int:
    """Hardware-utilization accounting (docs/observability.md#roofline-and-
    usage-accounting): the per-tenant/per-class usage counters (prompt +
    generated tokens, device-seconds, KV page-seconds, sheds) from the
    pushed metrics files, the roofline MFU/MBU gauges, and the newest
    per-request records from ``<state_dir>/usage.jsonl``. jax-free by
    construction.

    usage [N]        — tenant table + last N journal records (default 10)
    usage --json     — the machine-readable payload
    ``--dir PATH`` overrides the state-dir root.
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability import usage as _usage
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..utils.prometheus import merge_expositions, parse_exposition

    argv, root = _pop_dir_flag(argv, "usage: tpurun usage [N] [--json]")
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    last = int(argv[0]) if argv else 10

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    merged = parse_exposition(merge_expositions(jobs)) if jobs else None

    tenants: dict = {}
    roofline: dict = {}
    if merged is not None:
        for series, field in (
            (C.USAGE_PROMPT_TOKENS_TOTAL, "prompt_tokens"),
            (C.USAGE_GENERATED_TOKENS_TOTAL, "generated_tokens"),
            (C.USAGE_DEVICE_SECONDS_TOTAL, "device_seconds"),
            (C.USAGE_KV_PAGE_SECONDS_TOTAL, "kv_page_seconds"),
            (C.USAGE_SHEDS_TOTAL, "sheds"),
        ):
            for labels, v in merged.series(series):
                key = (
                    labels.get("tenant", "?"), labels.get("class", "?")
                )
                tenants.setdefault(key, {})[field] = v
        for series, field in (
            (C.MFU, "mfu"),
            (C.HBM_BW_UTIL, "mbu"),
            (C.ACHIEVED_TFLOPS, "tflops"),
        ):
            for labels, v in merged.series(series):
                roofline.setdefault(
                    labels.get("phase", "?"), {}
                )[field] = v

    records = named_journal("usage", root).tail(last)
    journal_totals = _usage.journal_tenant_totals(records)

    if as_json:
        print(json.dumps({
            "tenants": [
                {"tenant": t, "class": k, **fields}
                for (t, k), fields in sorted(tenants.items())
            ],
            "roofline": roofline,
            "journal_totals": journal_totals,
            "records": records,
        }))
        return 0

    if tenants:
        print(
            f"{'TENANT':<14} {'CLASS':<13} {'PROMPT':>9} {'GEN':>8} "
            f"{'DEV s':>9} {'PAGE s':>11} {'SHEDS':>6}"
        )
        for (t, k), f in sorted(tenants.items()):
            print(
                f"{t:<14} {k:<13} {int(f.get('prompt_tokens', 0)):>9} "
                f"{int(f.get('generated_tokens', 0)):>8} "
                f"{f.get('device_seconds', 0.0):>9.3f} "
                f"{f.get('kv_page_seconds', 0.0):>11.3f} "
                f"{int(f.get('sheds', 0)):>6}"
            )
    else:
        print(
            "no usage series in pushed metrics "
            "(run a bench or a serving engine first)"
        )
    tot = roofline.get("total")
    if tot is not None:
        bound = (
            "compute-bound"
            if tot.get("mfu", 0.0) >= tot.get("mbu", 0.0)
            else "bandwidth-bound"
        )
        print(
            f"\nroofline: MFU {tot.get('mfu', 0.0):.4f}  "
            f"MBU {tot.get('mbu', 0.0):.4f}  "
            f"{tot.get('tflops', 0.0):.3f} TFLOP/s achieved ({bound})"
        )
    if records:
        print(f"\nlast {len(records)} usage records (usage.jsonl):")
        for r in records:
            print(
                f"  {r.get('request_id', '?'):<18} "
                f"{r.get('tenant', '?'):<12} {r.get('class', '?'):<10} "
                f"prompt={r.get('prompt_tokens', 0):<6} "
                f"gen={r.get('generated_tokens', 0):<6} "
                f"cached={r.get('cached_prompt_tokens', 0):<6} "
                f"{r.get('finish_reason', '?')}"
            )
    return 0


def cmd_canary(argv: list[str]) -> int:
    """Correctness-canary status
    (docs/observability.md#correctness-canary): per-replica golden-set
    probe counts from the pushed metrics files plus the newest probe
    rounds from ``<state_dir>/canary.jsonl``. jax-free by construction.

    canary [N]        — replica table + last N journal records (default 10)
    canary --json     — the machine-readable payload
    ``--dir PATH`` overrides the state-dir root.
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..utils.prometheus import merge_expositions, parse_exposition

    argv, root = _pop_dir_flag(argv, "usage: tpurun canary [N] [--json]")
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    last = int(argv[0]) if argv else 10

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    merged = parse_exposition(merge_expositions(jobs)) if jobs else None

    replicas: dict = {}
    if merged is not None:
        for labels, v in merged.series(C.CANARY_PROBES_TOTAL):
            rep = replicas.setdefault(labels.get("replica", "?"), {})
            rep[labels.get("result", "?")] = rep.get(
                labels.get("result", "?"), 0.0
            ) + v
        for labels, v in merged.series(C.CANARY_DRIFT_TOTAL):
            replicas.setdefault(
                labels.get("replica", "?"), {}
            )["drift_total"] = v
        for labels, v in merged.series(C.CANARY_FAILING):
            replicas.setdefault(
                labels.get("replica", "?"), {}
            )["failing_streak"] = v

    records = named_journal("canary", root).tail(last)

    if as_json:
        print(json.dumps({
            "replicas": [
                {"replica": name, **fields}
                for name, fields in sorted(replicas.items())
            ],
            "records": records,
        }))
        return 0

    if replicas:
        print(
            f"{'REPLICA':<18} {'PASS':>6} {'DRIFT':>6} {'ERROR':>6} "
            f"{'RECORDED':>9} {'STREAK':>7}"
        )
        for name, f in sorted(replicas.items()):
            print(
                f"{name:<18} {int(f.get('pass', 0)):>6} "
                f"{int(f.get('drift', 0)):>6} {int(f.get('error', 0)):>6} "
                f"{int(f.get('recorded', 0)):>9} "
                f"{int(f.get('failing_streak', 0)):>7}"
            )
    else:
        print(
            "no canary series in pushed metrics "
            "(arm the prober: MTPU_CANARY_INTERVAL, or run a bench)"
        )
    if records:
        print(f"\nlast {len(records)} canary records (canary.jsonl):")
        for r in records:
            action = r.get("action", "?")
            if action == "round":
                results = r.get("results", {})
                summary = " ".join(
                    f"{k}={v}" for k, v in sorted(results.items())
                )
                print(
                    f"  round      {r.get('replica', '?'):<16} {summary}"
                )
            else:
                print(
                    f"  {action:<10} {r.get('replica', '?'):<16} "
                    f"{r.get('reason', r.get('weight', ''))}"
                )
    return 0


def cmd_metrics(argv: list[str]) -> int:
    """Print the merged prometheus exposition of every pushed job file
    (``<state_dir>/metrics/*.prom`` — the local pushgateway) — the same text
    a scraper sees on the gateway's ``/metrics``. ``--json`` prints
    {job: path} of the sources instead.

    ``--watch S [--rate]`` switches to the flight recorder: live DELTAS
    from the on-disk tsdb (``<state_dir>/tsdb/``, written by any process
    running ``MTPU_TSDB=1``) refreshed every S seconds — each series'
    current value plus its change over the refresh window (``--rate``
    renders per-second rates instead), which a one-shot exposition dump
    structurally cannot show (docs/observability.md#metrics-history)."""
    from ..observability.export import _metrics_dir, read_pushed_metrics

    usage = "usage: tpurun metrics [--json] [--watch S [--rate]] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, watch_s = _pop_flag(argv, "--watch", usage)
    as_rate = "--rate" in argv
    argv = [a for a in argv if a != "--rate"]
    if watch_s is not None:
        return _metrics_watch(float(watch_s), root, as_rate)
    if "--json" in argv:
        d = _metrics_dir(root)
        print(json.dumps({p.stem: str(p) for p in sorted(d.glob("*.prom"))}))
        return 0
    text = read_pushed_metrics(root)
    if not text:
        print("no pushed metrics (run an app first, or scrape a live /metrics)")
        return 0
    print(text, end="")
    return 0


def _series_key(entry) -> str:
    name, labels = entry[0], entry[1]
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _metrics_watch(watch: float, root, as_rate: bool) -> int:
    """The `tpurun metrics --watch` loop: render the newest tsdb sample
    and the per-series delta (or rate) against the previous refresh."""
    import time as _time

    from ..observability import timeseries as _ts

    prev: dict | None = None
    try:
        while True:
            cur = _ts.read_latest(root=root)
            print("\033[2J\033[H", end="")
            if cur is None:
                print(
                    f"no tsdb samples under {_ts.tsdb_dir(root)} "
                    "(start an engine/bench with MTPU_TSDB=1)"
                )
                _time.sleep(watch)
                continue
            rows: list[tuple[str, float, float | None]] = []
            prev_vals = (
                {
                    _series_key(e): (e[3], e[4])
                    for e in prev.get("series", ())
                }
                if prev is not None
                else {}
            )
            dt = cur["at"] - prev["at"] if prev is not None else None
            for e in cur.get("series", ()):
                key = _series_key(e)
                value = e[3]
                delta = None
                if key in prev_vals and dt and dt > 0:
                    d = value - prev_vals[key][0]
                    delta = (d / dt) if as_rate else d
                rows.append((key, value, delta))
            moved = [r for r in rows if r[2]]
            still = [r for r in rows if not r[2]]
            when = _time.strftime(
                "%H:%M:%S", _time.localtime(cur["at"])
            )
            unit = "/s" if as_rate else f"/{dt:.1f}s" if dt else ""
            print(
                f"tsdb {_ts.tsdb_dir(root)}  sample {when}  "
                f"{len(rows)} series  (delta{unit or ': first sample'})"
            )
            print(f"{'SERIES':<56} {'VALUE':>12} {'DELTA':>12}")
            shown = 0
            for key, value, delta in (
                sorted(moved, key=lambda r: -abs(r[2])) + sorted(still)
            ):
                if shown >= 40:
                    hidden_moved = max(0, len(moved) - shown)
                    note = (
                        f"{hidden_moved} still changing"
                        if hidden_moved
                        else "unchanged"
                    )
                    print(f"… {len(rows) - shown} more ({note})")
                    break
                d = f"{delta:+.3f}" if delta is not None else "-"
                print(f"{key:<56} {value:>12.3f} {d:>12}")
                shown += 1
            prev = cur
            _time.sleep(watch)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_tsdb(argv: list[str]) -> int:
    """Metrics-history view of the on-disk tsdb segment ring
    (``<state_dir>/tsdb/``, docs/observability.md#metrics-history).

    tsdb                      — summary: segments, window covered, series
    tsdb --series NAME [--label k=v] [--window S] [--sum]
                              — (time, value) points for one series,
                                newest last; ``--sum`` reads a histogram's
                                cumulative seconds instead of its count
    tsdb --rate ...           — the per-second increase over the window
                                (counter-reset aware), instead of points
    tsdb --perfetto FILE [--window S]
                              — export the window's counter tracks as
                                Chrome-trace JSON (ui.perfetto.dev)
    tsdb --json               — machine-readable payload
    ``--dir PATH`` overrides the state-dir root.
    """
    from pathlib import Path

    from ..observability import timeseries as _ts

    usage = (
        "usage: tpurun tsdb [--series NAME [--label k=v] [--sum] [--rate]]"
        " [--window S] [--perfetto FILE] [--json] [--dir PATH]"
    )
    argv, root = _pop_dir_flag(argv, usage)
    argv, series = _pop_flag(argv, "--series", usage)
    argv, label_s = _pop_flag(argv, "--label", usage)
    argv, window_s = _pop_flag(argv, "--window", usage)
    argv, perfetto = _pop_flag(argv, "--perfetto", usage)
    as_json = "--json" in argv
    as_rate = "--rate" in argv
    as_sum = "--sum" in argv

    labels = None
    if label_s:
        k, _, v = label_s.partition("=")
        labels = {k: v}

    records = _ts.read_window(root=root)
    if window_s is not None and records:
        lo = records[-1]["at"] - float(window_s)
        records = [r for r in records if r["at"] >= lo]
    if not records:
        print(
            f"no tsdb samples under {_ts.tsdb_dir(root)} "
            "(start an engine/bench with MTPU_TSDB=1)"
        )
        return 0

    if perfetto:
        from ..observability.export import spans_to_chrome_trace

        doc = spans_to_chrome_trace([], "tsdb-window", tsdb=records)
        Path(perfetto).write_text(json.dumps(doc, indent=1))
        print(
            f"wrote {perfetto} ({len(records)} samples as counter tracks "
            "— open in chrome://tracing or ui.perfetto.dev)"
        )
        return 0

    span = records[-1]["at"] - records[0]["at"]
    if series:
        pts = _ts.series_points(
            series, records, labels=labels,
            field="sum" if as_sum else "value",
        )
        if as_rate:
            r = _ts.rate(pts)
            if as_json:
                print(json.dumps({"series": series, "rate_per_s": r}))
            elif r is None:
                print(f"not enough points for a rate ({len(pts)} in window)")
            else:
                print(f"{series}: {r:.6f}/s over {span:.1f}s")
            return 0
        if as_json:
            print(json.dumps({"series": series, "points": pts}))
            return 0
        if not pts:
            print(f"no points for {series} in the window")
            return 0
        import time as _time

        for at, v in pts:
            when = _time.strftime("%H:%M:%S", _time.localtime(at))
            print(f"{when}  {v:.6f}")
        return 0

    names = _ts.series_names(records)
    if as_json:
        print(json.dumps({
            "dir": str(_ts.tsdb_dir(root)),
            "samples": len(records),
            "window_s": round(span, 3),
            "first_at": records[0]["at"],
            "last_at": records[-1]["at"],
            "series": names,
        }))
        return 0
    segs = sorted(_ts.tsdb_dir(root).glob("seg-*.jsonl"))
    print(
        f"{_ts.tsdb_dir(root)}: {len(segs)} segments, "
        f"{len(records)} samples covering {span:.1f}s, "
        f"{len(names)} series"
    )
    for name in names:
        print(f"  {name}")
    return 0


def cmd_alerts(argv: list[str]) -> int:
    """Alert rules + fire/clear history
    (docs/observability.md#alert-rules): the declarative rule set, each
    rule's condition evaluated one-shot over the on-disk tsdb window, and
    the newest transitions from the ``alerts`` journal.

    alerts [--last N]   — rule table + last N journal records (default 20)
    alerts --json       — machine-readable payload
    ``--dir PATH`` overrides the state-dir root.
    """
    from ..observability import alerts as _alerts
    from ..observability import timeseries as _ts

    usage = "usage: tpurun alerts [--last N] [--json] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, last_s = _pop_flag(argv, "--last", usage)
    last = int(last_s) if last_s is not None else 20
    as_json = "--json" in argv

    records = _ts.read_window(root=root)
    rows = _alerts.evaluate_offline(records)
    history = _alerts.read_alert_journal(last, root)
    if as_json:
        print(json.dumps({
            "rules": rows,
            "history": history,
            "tsdb_samples": len(records),
        }))
        return 0
    if not records:
        print(
            "no tsdb window to evaluate "
            "(start an engine/bench with MTPU_TSDB=1); rule set:"
        )
    print(
        f"{'RULE':<20} {'KIND':<10} {'SERIES':<32} {'THRESH':>7} "
        f"{'NOW':<5} DESCRIPTION"
    )
    for r in rows:
        now_s = "FIRE" if r["firing"] else "ok"
        print(
            f"{r['rule']:<20} {r['kind']:<10} {r['series']:<32} "
            f"{r['threshold']:>7} {now_s:<5} {r['description']}"
        )
    if history:
        import time as _time

        print()
        print(f"{'WHEN':<20} {'EVENT':<6} {'RULE':<20} VALUE")
        for rec in history:
            when = _time.strftime(
                "%Y-%m-%d %H:%M:%S", _time.localtime(rec.get("at", 0))
            )
            print(
                f"{when:<20} {rec.get('event', '?'):<6} "
                f"{rec.get('rule', '?'):<20} {rec.get('value')}"
            )
    return 0


def cmd_incidents(argv: list[str]) -> int:
    """Incident bundles (docs/observability.md#incident-bundles).

    incidents [list] [--json]    — bundle index, newest first
    incidents show ID [--file NAME]
                                 — one bundle's manifest (or one bundled
                                   file raw); a unique id prefix resolves
    incidents capture [--reason TEXT] [--trigger T]
                                 — capture a bundle right now (trigger
                                   ``manual``; ``revalidate_chip.sh``'s
                                   stage wrapper passes ``stage_failure``)
    ``--dir PATH`` overrides the state-dir root.
    """
    from ..observability import incident as _incident

    usage = (
        "usage: tpurun incidents [list [--json] | show ID [--file NAME] "
        "| capture [--reason TEXT] [--trigger T]] [--dir PATH]"
    )
    argv, root = _pop_dir_flag(argv, usage)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    sub = argv[0] if argv else "list"

    if sub == "capture":
        argv, reason = _pop_flag(argv[1:], "--reason", usage)
        argv, trigger = _pop_flag(argv, "--trigger", usage)
        if trigger is not None and trigger not in _incident.TRIGGERS:
            raise SystemExit(
                f"unknown trigger {trigger!r}; one of {_incident.TRIGGERS}"
            )
        bundle = _incident.capture(
            trigger or "manual",
            reason=reason or "tpurun incidents capture",
            root=root, force=True,
        )
        if bundle is None:
            print("capture failed (read-only state dir?)")
            return 1
        print(bundle)
        return 0

    if sub == "show":
        argv, file_name = _pop_flag(argv, "--file", usage)
        if len(argv) < 2:
            raise SystemExit(usage)
        manifest = _incident.read_manifest(argv[1], root=root)
        if manifest is None:
            raise SystemExit(f"no incident bundle {argv[1]!r}")
        if file_name:
            body = _incident.read_bundle_file(
                manifest["id"], file_name, root=root
            )
            if body is None:
                raise SystemExit(
                    f"no file {file_name!r} in {manifest['id']} "
                    f"(files: {sorted(manifest.get('files', {}))})"
                )
            print(body, end="")
            return 0
        print(json.dumps(manifest, indent=1))
        return 0

    if sub != "list":
        raise SystemExit(usage)
    manifests = _incident.list_incidents(root=root)
    if as_json:
        print(json.dumps(manifests))
        return 0
    if not manifests:
        print(f"no incident bundles under {_incident.incidents_dir(root)}")
        return 0
    import time as _time

    print(
        f"{'ID':<34} {'TRIGGER':<20} {'WHEN':<20} {'TSDB':>5} "
        f"{'TRACES':>6}  REASON"
    )
    for m in manifests:
        when = _time.strftime(
            "%Y-%m-%d %H:%M:%S", _time.localtime(m.get("at", 0))
        )
        print(
            f"{m.get('id', '?'):<34} {m.get('trigger', '?'):<20} "
            f"{when:<20} {m.get('tsdb_records', 0):>5} "
            f"{len(m.get('open_traces', ())):>6}  {m.get('reason', '')}"
        )
    return 0


def cmd_scaler(argv: list[str]) -> int:
    """Print the autoscaler decision journal, newest last.

    scaler [N]            — last N decisions (default 20)
    scaler --function TAG — only one function's decisions
    scaler --json         — raw JSONL records
    ``--dir PATH`` overrides the journal directory (default: state dir).
    """
    from ..observability.journal import named_journal

    argv, root = _pop_dir_flag(argv, "usage: tpurun scaler ... --dir PATH")
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    argv, function = _pop_flag(
        argv, "--function", "usage: tpurun scaler [N] [--function TAG]"
    )
    n = int(argv[0]) if argv else 20

    journal = named_journal("scaler", root)
    recs = journal.tail(n, function=function)
    if not recs:
        print(f"no autoscaler decisions in {journal.path}")
        return 0
    if as_json:
        for r in recs:
            print(json.dumps(r))
        return 0
    import time as _time

    print(
        f"{'WHEN':<20} {'FUNCTION':<24} {'ACTION':<11} {'TRIGGER':<17} "
        f"{'QUEUE':>5} {'POOL':>7}  DETAIL"
    )
    for r in recs:
        when = _time.strftime(
            "%Y-%m-%d %H:%M:%S", _time.localtime(r.get("at", 0))
        )
        pool = f"{r.get('containers_before', '?')}->{r.get('containers_after', '?')}"
        detail = []
        if r.get("spawned"):
            detail.append(f"spawned={r['spawned']}")
        if r.get("idle_ages_s"):
            detail.append(f"idle={r['idle_ages_s'][0]:.1f}s")
        if r.get("container") is not None:
            detail.append(f"container={r['container']}")
        print(
            f"{when:<20} {r.get('function', '?'):<24} "
            f"{r.get('action', '?'):<11} {r.get('trigger', '?'):<17} "
            f"{r.get('queue_depth', 0):>5} {pool:>7}  {' '.join(detail)}"
        )
    return 0


def cmd_top(argv: list[str]) -> int:
    """Live serving summary: engine load, token-level latency, SLO burn
    rates, and recent autoscaler decisions — from the pushed metrics files
    plus the decision journal (the ``htop`` of the framework).

    ``--watch S`` refreshes every S seconds until interrupted;
    ``--dir PATH`` overrides the state dir roots.
    """
    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..observability.slo import evaluate
    from ..serving.health import decode_watchdog_series
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun top [--watch S] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, watch_s = _pop_flag(argv, "--watch", usage)
    watch = float(watch_s) if watch_s is not None else None

    from pathlib import Path

    metrics_root = Path(root) / "metrics" if root else None
    journal = named_journal("scaler", root)

    def render() -> None:
        jobs = pushed_jobs(metrics_root)
        if not jobs:
            print("no pushed metrics yet (run an app or bench first)")
        merged = parse_exposition(merge_expositions(jobs))

        def fmt_q(name):
            q = merged.histogram_quantiles(
                name, quantiles=(0.5, 0.95), aggregate={}
            )
            if q is None:
                return "     -/-    "
            return f"{q['p50'] * 1000:>6.1f}/{q['p95'] * 1000:<6.1f}"

        print(f"jobs: {len(jobs)} ({', '.join(sorted(jobs)) or 'none'})")
        print(
            f"tokens/s {merged.total(C.TOKENS_PER_SECOND):>8.1f}   "
            f"active slots {merged.total(C.ACTIVE_SLOTS):>4.0f}   "
            f"waiting {merged.total(C.WAITING_REQUESTS):>4.0f}   "
            # a 0..1 fraction must never sum across jobs: show the worst
            f"kv occupancy {merged.peak(C.KV_PAGE_OCCUPANCY):>5.2f}"
        )
        print(
            f"ttft p50/p95 ms {fmt_q(C.TTFT_SECONDS)}   "
            f"tpot p50/p95 ms {fmt_q(C.TPOT_SECONDS)}"
        )
        # macro-step decode (docs/multistep.md): configured N + harvested
        # tokens-per-dispatch, when a multistep engine has pushed (gauges:
        # peak, never sum across jobs)
        ms_n = merged.peak(C.MULTISTEP_DECODE_STEPS)
        if ms_n:
            print(
                f"macro-step decode: N={ms_n:.0f}   tokens/dispatch "
                f"{merged.peak(C.MULTISTEP_TOKENS_PER_DISPATCH):.1f}"
            )
        # fused speculative decode (docs/speculative.md#series): dispatched
        # γ p50 + acceptance, when a spec engine has pushed (gauges: peak)
        sp_acc = merged.peak(C.SPEC_ACCEPTANCE_RATE)
        if merged.peak(C.SPEC_GAMMA) or sp_acc:
            print(
                f"speculative decode: gamma p50 "
                f"{merged.peak(C.SPEC_GAMMA):.0f}   acceptance "
                f"{sp_acc:.2f}   tokens/round "
                f"{merged.peak(C.SPEC_TOKENS_PER_DISPATCH):.1f}"
            )
        # the resolved decode plan, incl. the tensor-parallel degree and the
        # PER-SHARD ragged variant (paged_impl_plan(mesh=...)) — so a TP
        # deployment's dashboard shows the sharded plan actually running
        for labels, _v in merged.series(C.DECODE_IMPL):
            print(
                f"decode impl: attention={labels.get('attention', '?')} "
                f"variant={labels.get('variant', '-')} "
                f"scatter={labels.get('scatter', '?')} "
                f"kv_dtype={labels.get('kv_dtype', '?')} "
                f"tp={labels.get('tp', '1')}"
            )
        # gray-failure watchdog (docs/health.md): per-replica progress
        # classification + last-progress age, when a watchdog has pushed
        wd = decode_watchdog_series(merged)
        wd_states = wd["states"]
        if wd_states:
            wd_ages = wd["ages"]
            print(
                "replica health: "
                + "  ".join(
                    f"{name}={state}"
                    + (
                        f"({wd_ages[name]:.1f}s)"
                        if wd_ages.get(name) else ""
                    )
                    for name, state in sorted(wd_states.items())
                )
            )
        print()
        print(f"{'SLO':<22} {'TARGET':>10} {'OBSERVED':>10} {'BURN':>6}  OK")
        for r in evaluate(merged, burn_rate_registry=merged):
            obs = "-" if r["observed"] is None else f"{r['observed']:.4f}"
            burn = "-" if r["burn_rate"] is None else f"{r['burn_rate']:.2f}"
            print(
                f"{r['name']:<22} {r['target']:>10.4f} {obs:>10} {burn:>6}  "
                f"{'ok' if r['ok'] else 'VIOLATING'}"
            )
        recs = journal.tail(5)
        if recs:
            print()
            print("recent autoscaler decisions:")
            for r in recs:
                print(
                    f"  {r.get('function', '?')}: {r.get('action')} "
                    f"({r.get('trigger')}) queue={r.get('queue_depth')} "
                    f"pool {r.get('containers_before')}->"
                    f"{r.get('containers_after')}"
                )

    if watch is None:
        render()
        return 0
    import time as _time

    try:
        while True:
            print("\033[2J\033[H", end="")
            render()
            _time.sleep(watch)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_sched(argv: list[str]) -> int:
    """Live scheduler view: per-class queue depth + admission wait, shed
    rates by reason, deadline misses, and router affinity — from the pushed
    metrics files (the scheduling companion of ``tpurun top``).

    ``--watch S`` refreshes every S seconds; ``--dir PATH`` overrides the
    state dir root.
    """
    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..scheduling.policy import PRIORITY_CLASSES
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun sched [--watch S] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, watch_s = _pop_flag(argv, "--watch", usage)
    watch = float(watch_s) if watch_s is not None else None

    from pathlib import Path

    metrics_root = Path(root) / "metrics" if root else None

    def render() -> None:
        jobs = pushed_jobs(metrics_root)
        if not jobs:
            print("no pushed metrics yet (run an app or bench first)")
        merged = parse_exposition(merge_expositions(jobs))
        print(f"jobs: {len(jobs)} ({', '.join(sorted(jobs)) or 'none'})")
        print(
            f"{'CLASS':<13} {'QUEUED':>6} {'ADMITTED':>9} {'SHED':>6} "
            f"{'WAIT p50/p95 ms':>18}"
        )
        for klass in PRIORITY_CLASSES:
            depth = merged.total(C.SCHED_QUEUE_DEPTH, {"class": klass})
            admitted = merged.total(
                C.REQUESTS_ADMITTED_TOTAL, {"class": klass}
            )
            shed = merged.total(C.SHEDS_TOTAL, {"class": klass})
            q = merged.histogram_quantiles(
                C.SCHED_QUEUE_WAIT_SECONDS,
                quantiles=(0.5, 0.95),
                aggregate={"class": klass},
            )
            wait = (
                f"{q['p50'] * 1000:>7.1f}/{q['p95'] * 1000:<7.1f}"
                if q
                else "      -/-     "
            )
            print(
                f"{klass:<13} {depth:>6.0f} {admitted:>9.0f} {shed:>6.0f} "
                f"{wait:>18}"
            )
        offered = merged.total(C.REQUESTS_ADMITTED_TOTAL) + merged.total(
            C.SHEDS_TOTAL
        )
        shed_rate = (
            merged.total(C.SHEDS_TOTAL) / offered if offered else 0.0
        )
        by_reason = {}
        for lbls, v in merged.series(C.SHEDS_TOTAL):
            reason = lbls.get("reason", "?")
            by_reason[reason] = by_reason.get(reason, 0.0) + v
        reasons = " ".join(
            f"{r}={int(v)}" for r, v in sorted(by_reason.items())
        )
        print(
            f"shed rate {shed_rate:.4f}"
            + (f"   by reason: {reasons}" if reasons else "")
        )
        misses = {
            lbls.get("stage", "?"): v
            for lbls, v in merged.series(C.DEADLINE_MISSES_TOTAL)
        }
        if misses:
            print(
                "deadline misses: "
                + " ".join(f"{k}={int(v)}" for k, v in sorted(misses.items()))
            )
        routed = merged.total(C.ROUTER_REQUESTS_TOTAL)
        if routed:
            print(
                f"router: {int(routed)} placed, "
                f"{int(merged.total(C.ROUTER_AFFINITY_HITS_TOTAL))} affinity "
                f"hits, "
                f"{int(merged.total(C.ROUTER_REQUESTS_TOTAL, {'route': 'fallback'}))}"
                f" fallbacks"
            )

    if watch is None:
        render()
        return 0
    import time as _time

    try:
        while True:
            print("\033[2J\033[H", end="")
            render()
            _time.sleep(watch)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_disagg(argv: list[str]) -> int:
    """Live disaggregated-serving view: replica roles, outstanding and
    completed migrations (with wire bytes + latency quantiles), and the
    tiered prefix cache's per-tier occupancy and hit rates — from the
    pushed metrics files (the disagg companion of ``tpurun sched``;
    docs/disagg.md).

    ``--watch S`` refreshes every S seconds; ``--dir PATH`` overrides the
    state dir root.
    """
    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun disagg [--watch S] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, watch_s = _pop_flag(argv, "--watch", usage)
    watch = float(watch_s) if watch_s is not None else None

    from pathlib import Path

    metrics_root = Path(root) / "metrics" if root else None

    def render() -> None:
        jobs = pushed_jobs(metrics_root)
        if not jobs:
            print("no pushed metrics yet (run an app or bench first)")
        merged = parse_exposition(merge_expositions(jobs))
        print(f"jobs: {len(jobs)} ({', '.join(sorted(jobs)) or 'none'})")
        roles = sorted(
            (lbls.get("replica", "?"), lbls.get("role", "?"))
            for lbls, v in merged.series(C.REPLICA_ROLE)
            if v
        )
        if roles:
            print(f"{'REPLICA':<24} ROLE")
            for name, role in roles:
                print(f"{name:<24} {role}")
        else:
            print("no role-tagged replicas (unified fleet)")
        by_result = {
            lbls.get("result", "?"): v
            for lbls, v in merged.series(C.DISAGG_MIGRATIONS_TOTAL)
        }
        inflight = merged.total(C.DISAGG_MIGRATIONS_INFLIGHT)
        q = merged.histogram_quantiles(
            C.DISAGG_MIGRATION_SECONDS, quantiles=(0.5, 0.95), aggregate={}
        )
        lat = (
            f"{q['p50'] * 1000:.1f}/{q['p95'] * 1000:.1f} ms"
            if q
            else "-/-"
        )
        print(
            f"migrations: {int(sum(by_result.values()))} total "
            f"({' '.join(f'{k}={int(v)}' for k, v in sorted(by_result.items())) or 'none'})"
            f"   inflight {int(inflight)}"
        )
        print(
            f"  pages {int(merged.total(C.DISAGG_PAGES_MIGRATED_TOTAL))}   "
            f"wire bytes {int(merged.total(C.DISAGG_MIGRATION_BYTES_TOTAL))}   "
            f"chunk retries "
            f"{int(merged.total(C.DISAGG_CHUNK_RETRIES_TOTAL))}   "
            f"latency p50/p95 {lat}"
        )
        hits = {
            lbls.get("tier", "?"): v
            for lbls, v in merged.series(C.PREFIX_TIER_HITS_TOTAL)
        }
        total_hits = sum(hits.values())
        print()
        print(f"{'TIER':<8} {'BLOCKS':>8} {'BYTES':>12} {'HITS':>8} {'RATE':>6}")
        for tier in ("hbm", "host", "volume"):
            pages = merged.total(C.PREFIX_TIER_PAGES, {"tier": tier})
            tier_bytes = merged.total(C.PREFIX_TIER_BYTES, {"tier": tier})
            h = hits.get(tier, 0.0)
            rate = h / total_hits if total_hits else 0.0
            occ = "-" if tier == "hbm" else f"{int(pages)}"
            occ_b = "-" if tier == "hbm" else f"{int(tier_bytes)}"
            print(
                f"{tier:<8} {occ:>8} {occ_b:>12} {int(h):>8} {rate:>6.2f}"
            )

    if watch is None:
        render()
        return 0
    import time as _time

    try:
        while True:
            print("\033[2J\033[H", end="")
            render()
            _time.sleep(watch)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_chaos(argv: list[str]) -> int:
    """Chaos-harness view: the last fault-injection episodes — faults
    injected per catalog point, recoveries, and invariant results — from
    the chaos journal (``<state_dir>/chaos.jsonl``) plus pushed metrics
    (the fault-injection companion of ``tpurun disagg``; docs/faults.md).

    ``--last N`` shows the newest N episodes (default 10); ``--dir PATH``
    overrides the state dir root.
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun chaos [--last N] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, last_s = _pop_flag(argv, "--last", usage)
    last = int(last_s) if last_s is not None else 10

    episodes = named_journal("chaos", root).tail(last)

    # per-point injected totals: pushed metrics when available (the chaos
    # runner pushes job "chaos"), else aggregated from the journal records
    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    injected: dict[str, float] = {}
    if jobs:
        merged = parse_exposition(merge_expositions(jobs))
        for lbls, v in merged.series(C.FAULTS_INJECTED_TOTAL):
            injected[lbls.get("point", "?")] = v
        readmissions = merged.total(C.ROUTER_READMISSIONS_TOTAL)
    else:
        readmissions = 0.0
    if not injected:
        for ep in episodes:
            for point, n in (ep.get("injected") or {}).items():
                injected[point] = injected.get(point, 0) + n

    if not episodes and not injected:
        print(
            "no chaos episodes recorded yet "
            "(run `python -m pytest tests/test_chaos.py` or the "
            "tiny-chaos bench config first)"
        )
        return 0
    if injected:
        print(f"{'FAULT POINT':<28} {'INJECTED':>9}")
        for point in sorted(injected):
            print(f"{point:<28} {int(injected[point]):>9}")
        print(f"{'total':<28} {int(sum(injected.values())):>9}")
    if readmissions:
        print(f"router re-admissions: {int(readmissions)}")
    if episodes:
        print()
        print(
            f"{'EPISODE':<20} {'INJ':>4} {'FINISHED':<24} {'SHED':>4} "
            f"{'WEDGED':>6} INVARIANTS"
        )
        for ep in episodes:
            finished = " ".join(
                f"{k}={v}" for k, v in sorted(
                    (ep.get("finished") or {}).items()
                )
            )
            inv = ep.get("invariants")
            print(
                f"{ep.get('episode', '?'):<20} "
                f"{sum((ep.get('injected') or {}).values()):>4} "
                f"{finished:<24} {ep.get('shed', 0):>4} "
                f"{ep.get('wedged', 0):>6} "
                f"{'ok' if inv == 'ok' else f'VIOLATED: {inv}'}"
            )
    return 0


def cmd_prefixstore(argv: list[str]) -> int:
    """Shared prefix-store view: fleet-wide dedup ratio, hit origins
    (self vs peer — peer hits are the cross-replica wins the store
    exists for), resident bytes, and the lease-takeover journal tail —
    from the pushed metrics files plus ``<state_dir>/prefix_store.jsonl``
    (the shared-KV companion of ``tpurun disagg``; docs/prefix_store.md).

    ``--last N`` shows the newest N journal records (default 10);
    ``--dir PATH`` overrides the state dir root.
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun prefixstore [--last N] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, last_s = _pop_flag(argv, "--last", usage)
    last = int(last_s) if last_s is not None else 10

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    records = named_journal("prefix_store", root).tail(last)
    if not jobs and not records:
        print(
            "no shared prefix-store activity yet "
            "(serve with tiered_prefix shared=True, or run the fleet "
            "bench config first)"
        )
        return 0

    if jobs:
        merged = parse_exposition(merge_expositions(jobs))
        hits = {
            lbls.get("origin", "?"): v
            for lbls, v in merged.series(C.PREFIX_STORE_HITS_TOTAL)
        }
        total_hits = sum(hits.values())
        misses = merged.total(C.PREFIX_STORE_MISSES_TOTAL)
        looked = total_hits + misses
        print(f"jobs: {len(jobs)} ({', '.join(sorted(jobs)) or 'none'})")
        print(
            f"hits: {int(total_hits)} "
            f"(self={int(hits.get('self', 0))} "
            f"peer={int(hits.get('peer', 0))})   "
            f"misses {int(misses)}   "
            f"hit rate {total_hits / looked if looked else 0.0:.2f}"
        )
        print(
            f"dedup ratio {merged.total(C.PREFIX_STORE_DEDUP_RATIO):.2f}   "
            f"resident bytes "
            f"{int(merged.total(C.PREFIX_STORE_BYTES))}   "
            f"owner takeovers "
            f"{int(merged.total(C.PREFIX_STORE_OWNER_TAKEOVERS_TOTAL))}"
        )
    if records:
        print()
        print(f"{'ACTION':<16} {'CHAIN':<14} {'FROM':<12} {'TO':<12} REASON")
        for rec in records:
            print(
                f"{rec.get('action', '?'):<16} "
                f"{str(rec.get('chain', '?'))[:12]:<14} "
                f"{str(rec.get('from', '-')):<12} "
                f"{str(rec.get('to', '-')):<12} "
                f"{rec.get('reason', '-')}"
            )
    return 0


def cmd_health(argv: list[str]) -> int:
    """Gray-failure watchdog view: per-replica progress classification,
    watermark ages, ladder counters, and the last N watchdog decisions from
    the journal (``<state_dir>/watchdog.jsonl``) plus the pushed watchdog
    metric series (docs/health.md).

    ``--last N`` shows the newest N journal records (default 20);
    ``--dir PATH`` overrides the state dir root.
    """
    from pathlib import Path

    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..serving.health import decode_watchdog_series
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun health [--last N] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, last_s = _pop_flag(argv, "--last", usage)
    last = int(last_s) if last_s is not None else 20

    records = named_journal("watchdog", root).tail(last)

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    merged = parse_exposition(merge_expositions(jobs)) if jobs else None

    wd = (
        decode_watchdog_series(merged)
        if merged is not None
        else {"states": {}, "ages": {}, "transitions": {}, "recoveries": {}}
    )
    states, ages = wd["states"], wd["ages"]
    transitions, recoveries = wd["transitions"], wd["recoveries"]

    if not records and not states:
        print(
            "no watchdog activity recorded yet "
            "(run a FleetWatchdog — tests/test_chaos.py or the "
            "tiny-recovery bench config exercise it)"
        )
        return 0
    if states:
        print(f"{'REPLICA':<16} {'STATE':<12} {'PROGRESS AGE':>12}")
        for name in sorted(states):
            age = ages.get(name)
            print(
                f"{name:<16} {states[name]:<12} "
                f"{('%.2fs' % age) if age is not None else '-':>12}"
            )
    if transitions:
        print(
            "transitions: "
            + "  ".join(
                f"{k}={int(v)}" for k, v in sorted(transitions.items())
            )
        )
    if recoveries:
        print(
            "ladder actions: "
            + "  ".join(
                f"{k}={int(v)}" for k, v in sorted(recoveries.items())
            )
        )
    if records:
        print()
        print(f"{'ACTION':<16} {'REPLICA':<16} DETAIL")
        for rec in records:
            action = rec.get("action", "?")
            who = rec.get("replica") or rec.get("transfer_id") or "?"
            if action == "transition":
                detail = (
                    f"-> {rec.get('state')} (raw={rec.get('raw')}, "
                    f"age={rec.get('progress_age_s')}s, "
                    f"outstanding={rec.get('outstanding')})"
                )
            elif action == "down_weight":
                detail = f"weight={rec.get('weight')}"
            elif action == "quarantine":
                detail = f"for {rec.get('quarantine_s')}s"
            elif action == "abort_transfer":
                detail = f"stalled > {rec.get('stall_s')}s"
            else:
                detail = ""
            print(f"{action:<16} {who:<16} {detail}")
    return 0


def cmd_fleet(argv: list[str]) -> int:
    """Fleet-autoscaler view: replica counts by role, scale decisions by
    action/trigger, boot latency (warm snapshot-restore vs cold init), and
    the newest decision-journal records (``<state_dir>/fleet.jsonl``) —
    the replica-fleet companion of ``tpurun scaler`` (docs/fleet.md).

    ``--last N`` shows the newest N journal records (default 20);
    ``--dir PATH`` overrides the state dir root.
    """
    from pathlib import Path

    from ..observability import catalog as C
    from ..observability.export import pushed_jobs
    from ..observability.journal import named_journal
    from ..utils.prometheus import merge_expositions, parse_exposition

    usage = "usage: tpurun fleet [--last N] [--dir PATH]"
    argv, root = _pop_dir_flag(argv, usage)
    argv, last_s = _pop_flag(argv, "--last", usage)
    last = int(last_s) if last_s is not None else 20

    journal = named_journal("fleet", root)
    records = journal.tail(last)

    jobs = pushed_jobs(Path(root) / "metrics" if root else None)
    merged = parse_exposition(merge_expositions(jobs)) if jobs else None

    replicas: dict[str, float] = {}
    decisions: dict[tuple[str, str], float] = {}
    if merged is not None:
        for lbls, v in merged.series(C.FLEET_REPLICAS):
            replicas[lbls.get("role", "?")] = v
        for lbls, v in merged.series(C.FLEET_DECISIONS_TOTAL):
            decisions[(lbls.get("action", "?"), lbls.get("trigger", "?"))] = v
    if not decisions:
        # no pushed metrics: aggregate over the WHOLE journal (its own
        # file bound), not the --last display window — the counts table
        # prints as totals and must not be silently capped at N
        for rec in journal.tail(1 << 20):
            key = (rec.get("action", "?"), rec.get("trigger", "?"))
            decisions[key] = decisions.get(key, 0) + 1

    if not records and not decisions:
        print(
            "no fleet decisions recorded yet "
            "(run the tiny-fleet bench config or a FleetAutoscaler first)"
        )
        return 0
    if replicas:
        print("replicas: " + "  ".join(
            f"{role}={int(n)}" for role, n in sorted(replicas.items()) if n
        ))
    if decisions:
        print(f"{'ACTION':<12} {'TRIGGER':<16} {'COUNT':>6}")
        for (action, trigger), n in sorted(decisions.items()):
            print(f"{action:<12} {trigger:<16} {int(n):>6}")
    if merged is not None:
        for boot in ("warm", "cold"):
            q = merged.histogram_quantiles(
                C.FLEET_BOOT_SECONDS, quantiles=(0.5, 0.95),
                aggregate={"boot": boot},
            )
            if q:
                print(
                    f"{boot} boots: p50 {q['p50'] * 1000:.0f} ms   "
                    f"p95 {q['p95'] * 1000:.0f} ms"
                )
    if records:
        print()
        print(
            f"{'ACTION':<12} {'ROLE':<8} {'REPLICA':<14} {'TRIGGER':<16} "
            f"{'BOOT':<6} {'N->N':>7}"
        )
        for rec in records:
            boot = rec.get("boot") or "-"
            before = rec.get("replicas_before")
            after = rec.get("replicas_after")
            sizes = f"{before}->{after}" if before is not None else "-"
            print(
                f"{rec.get('action', '?'):<12} {rec.get('role', '?'):<8} "
                f"{rec.get('replica', '?'):<14} {rec.get('trigger', '?'):<16} "
                f"{boot:<6} {sizes:>7}"
            )
    return 0


def cmd_app(argv: list[str]) -> int:
    if argv and argv[0] == "list":
        reg = _config.state_dir() / "apps.json"
        try:
            registry = json.loads(reg.read_text())
        except (FileNotFoundError, json.JSONDecodeError):
            registry = {}
        for name, entry in sorted(registry.items()):
            print(f"{name}\t{entry.get('source_file')}")
        return 0
    raise SystemExit("usage: tpurun app list")


COMMANDS = {
    "run": cmd_run,
    "deploy": cmd_deploy,
    "serve": cmd_serve,
    "secret": cmd_secret,
    "app": cmd_app,
    "snapshot": cmd_snapshot,
    "trace": cmd_trace,
    "explain": cmd_explain,
    "benchdiff": cmd_benchdiff,
    "metrics": cmd_metrics,
    "profile": cmd_profile,
    "usage": cmd_usage,
    "canary": cmd_canary,
    "tsdb": cmd_tsdb,
    "alerts": cmd_alerts,
    "incidents": cmd_incidents,
    "incident": cmd_incidents,  # `tpurun incident capture` reads naturally
    "scaler": cmd_scaler,
    "sched": cmd_sched,
    "disagg": cmd_disagg,
    "prefixstore": cmd_prefixstore,
    "chaos": cmd_chaos,
    "fleet": cmd_fleet,
    "health": cmd_health,
    "top": cmd_top,
    "examples": cmd_examples,
    "docs": cmd_docs,
}


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    handler = COMMANDS.get(cmd)
    if handler is None:
        raise SystemExit(f"unknown command {cmd!r}; one of {sorted(COMMANDS)}")
    try:
        return handler(rest)
    except BrokenPipeError:
        # `tpurun trace list | head` is a supported workflow: the reader
        # closing early is success, not a traceback
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
