"""TPU resource specs — the TPU-native replacement for ``gpu=``.

The reference requests accelerators with typed strings and fallback lists:
``gpu="H200:8"`` (vllm_inference.py:133), ``gpu=["h100", "a100", "any"]``
(gpu_fallbacks.py:20-23). Our equivalent is topology-aware: ``tpu="v5e-8"``
names a generation *and* a slice size, from which chips-per-host, host count,
and the default device mesh all derive. This module is pure parsing — no jax
import — so the client SDK stays light; mesh construction from a spec lives in
``modal_examples_tpu.parallel.mesh``.
"""

from __future__ import annotations

import dataclasses
import re

# generation -> (chips per host, HBM GiB per chip, bf16 peak TFLOP/s per chip)
# Used for host-count derivation and for back-of-envelope perf accounting in
# the profiler/bench tooling.
TPU_GENERATIONS: dict[str, tuple[int, int, float]] = {
    "v4": (4, 32, 137.5),
    "v5e": (8, 16, 98.5),  # v5 lite
    "v5p": (4, 95, 229.5),
    "v6e": (8, 32, 459.0),
}

# generation -> HBM bandwidth GB/s per chip: the MBU denominator the
# roofline meter (observability/usage.py) normalizes decode byte traffic
# against. v5e matches bench.py's V5E_HBM_GBPS ceiling.
TPU_HBM_GBPS: dict[str, float] = {
    "v4": 1228.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6e": 1638.0,
}

_SPEC_RE = re.compile(r"^(?P<gen>v\d+[a-z]*)(?:-(?P<chips>\d+))?$", re.IGNORECASE)


class InvalidTPUSpec(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class TPUSpec:
    """A parsed TPU slice request.

    ``tpu="v5e-8"`` -> generation v5e, 8 chips, 1 host.
    ``tpu="v5p-128"`` -> 128 chips, 32 hosts (4 chips/host).
    A bare generation (``tpu="v5e"``) means one chip.
    """

    generation: str
    chips: int

    @property
    def chips_per_host(self) -> int:
        return TPU_GENERATIONS[self.generation][0]

    @property
    def hosts(self) -> int:
        cph = self.chips_per_host
        return max(1, (self.chips + cph - 1) // cph)

    @property
    def hbm_gib_per_chip(self) -> int:
        return TPU_GENERATIONS[self.generation][1]

    @property
    def bf16_tflops_per_chip(self) -> float:
        return TPU_GENERATIONS[self.generation][2]

    @property
    def hbm_gbps_per_chip(self) -> float:
        return TPU_HBM_GBPS[self.generation]

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1

    def __str__(self) -> str:
        return f"{self.generation}-{self.chips}"


def parse_tpu_spec(spec: str) -> TPUSpec:
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise InvalidTPUSpec(
            f"invalid tpu spec {spec!r}; expected e.g. 'v5e-8', 'v4-16', 'v5e'"
        )
    gen = m.group("gen").lower()
    if gen not in TPU_GENERATIONS:
        raise InvalidTPUSpec(
            f"unknown TPU generation {gen!r}; known: {sorted(TPU_GENERATIONS)}"
        )
    chips = int(m.group("chips") or 1)
    if chips < 1:
        raise InvalidTPUSpec("chip count must be >= 1")
    return TPUSpec(generation=gen, chips=chips)


def parse_tpu_request(
    tpu: str | list[str] | tuple[str, ...] | None,
) -> list[TPUSpec]:
    """Parse a ``tpu=`` argument into an ordered preference list.

    Mirrors the reference's ordered GPU fallback lists
    (gpu_fallbacks.py:20-23): the scheduler tries each spec in order until
    capacity is found.
    """
    if tpu is None:
        return []
    if isinstance(tpu, str):
        return [parse_tpu_spec(tpu)]
    specs = [parse_tpu_spec(s) for s in tpu]
    if not specs:
        raise InvalidTPUSpec("empty tpu fallback list")
    return specs
