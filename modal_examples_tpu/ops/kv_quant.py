"""int8 quantized paged-KV storage: the dtype half of the paged cache.

Why: at the headline shape (llama2-7b, ctx 128, 32 slots) decode-step KV
reads are ~4.3 GB — already comparable to the int8 *weight* floor — and at
ctx 1024 they grow to ~34 GB/step and dominate the step entirely (NOTES.md
round-5 design note). int8 KV halves that bandwidth AND halves cache
residency, so the same HBM holds ~2x the slots/context. This mirrors what
TPU-native serving kernels assume (Ragged Paged Attention) and what vLLM
ships as fp8 KV — the accuracy contract is tolerance-based (quantization
legitimately changes logits), never token-exact.

Scheme (NOTES.md round 5, "int8 KV cache — design note"):
- pages keep the ``[L, P, page_size, Hkv, D]`` layout but store int8, with a
  per-token-head f32 scale array ``[L, P, page_size, Hkv]`` riding alongside
  (~3% overhead at D=128) — together a 2-leaf :class:`QuantizedKV` pytree,
  which makes the full :class:`~..serving.kv_cache.PagedKVCache` a 4-leaf
  pytree (k data+scale, v data+scale);
- **quantize at write**: per token-head symmetric ``amax/127`` over D, fused
  into the producing program (prefill page scatter, the post-scan decode
  scatter, the verify-chain writes);
- **dequantize at read**: one bf16 multiply fused into the XLA page gather,
  or into the ragged kernels' VMEM loads (they DMA the int8 page plus its
  scale row — int8 packs legal (32, 128) Mosaic tiles).

Every helper below is a no-op pass-through for plain (bf16/f32) page
arrays, so the default ``kv_dtype`` path stays bit-identical: no
QuantizedKV object is ever constructed unless the cache was created int8.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

#: scale granularity: one f32 per (token, kv-head) over the D axis
_QMAX = 127.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedKV:
    """int8 KV pages + per-token-head f32 scales, as one pytree node.

    ``data`` is ``[..., D]`` int8; ``scale`` is ``data.shape[:-1]`` f32 with
    ``dequant = data * scale[..., None]``. Shape/dtype properties delegate
    to ``data`` so shape-probing call sites (``k_pages.shape[2]`` etc.)
    work unchanged; consumers that touch VALUES must branch (the static
    guard in tests/test_static.py enforces that every cache consumer does).
    """

    data: jax.Array  # int8 [..., D]
    scale: jax.Array  # f32  [...] == data.shape[:-1]

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def __getitem__(self, idx) -> "QuantizedKV":
        """Index data and scale together — valid for indices into the
        leading (non-D) axes only (a layer view ``pages[li]``, a page
        gather ``pages[tables]``); indexing the trailing D axis would
        desynchronize the pair and is the caller's bug."""
        return QuantizedKV(data=self.data[idx], scale=self.scale[idx])

    @property
    def nbytes(self) -> int:
        """Total device bytes (int8 payload + f32 scales). A property to
        match ``jax.Array.nbytes``, so byte accounting needs no
        is_quantized branch."""
        return (
            self.data.size * self.data.dtype.itemsize
            + self.scale.size * self.scale.dtype.itemsize
        )


def is_quantized(pages) -> bool:
    return isinstance(pages, QuantizedKV)


def resolve_kv_dtype(kv_dtype):
    """Normalize an engine/env kv_dtype spec: returns the string ``"int8"``
    for the quantized cache, else a jnp dtype. Accepts jnp dtypes, numpy
    dtypes, and the ``MTPU_KV_DTYPE`` spellings."""
    if isinstance(kv_dtype, str):
        name = kv_dtype.lower()
        if name in ("int8", "i8"):
            return "int8"
        aliases = {"bf16": "bfloat16", "f32": "float32", "fp32": "float32"}
        return jnp.dtype(aliases.get(name, name))
    if kv_dtype == jnp.int8:
        return "int8"
    return jnp.dtype(kv_dtype)


def kv_dtype_name(pages) -> str:
    """Reporting name for a cache leaf: "int8" or the array dtype name."""
    if is_quantized(pages):
        return "int8"
    return str(jnp.dtype(pages.dtype))


def quantize_kv(x: jax.Array) -> QuantizedKV:
    """Per-token-head symmetric int8 over the last (D) axis.

    ``scale = amax/127`` (1.0 where the row is all zero, so dequant of a
    zero row is exactly zero), ``data = round(x / scale)``. Deterministic:
    the prefix cache relies on same-tokens + same-weights => same quantized
    page bytes when concurrent prefills rewrite a shared page."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.round(xf / scale[..., None])
    q = jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)
    return QuantizedKV(data=q, scale=scale)


def dequantize_kv(pages, dtype=jnp.bfloat16):
    """One multiply at ``dtype`` (bf16 on the serving path); pass-through
    for plain arrays."""
    if not is_quantized(pages):
        return pages
    return pages.data.astype(dtype) * pages.scale[..., None].astype(dtype)


def kv_empty(shape: tuple, kv_dtype) -> jax.Array | QuantizedKV:
    """A zeroed cache-page array of ``shape`` = [..., D] at ``kv_dtype``
    ("int8" => QuantizedKV with unit scales; dequant of the empty cache is
    exactly zero either way)."""
    kv_dtype = resolve_kv_dtype(kv_dtype)
    if kv_dtype == "int8":
        return QuantizedKV(
            data=jnp.zeros(shape, jnp.int8),
            scale=jnp.ones(shape[:-1], jnp.float32),
        )
    return jnp.zeros(shape, kv_dtype)


def kv_gather(pages, tables, layer=None, *, dtype=jnp.bfloat16):
    """``pages[(layer,) tables]`` with the dequant multiply fused into the
    gather (XLA fuses gather -> convert -> multiply into one bandwidth-bound
    loop, so the HBM reads stay int8). Plain arrays gather untouched —
    bit-identical to direct indexing."""
    if is_quantized(pages):
        if layer is None:
            d, s = pages.data[tables], pages.scale[tables]
        else:
            d, s = pages.data[layer, tables], pages.scale[layer, tables]
        return d.astype(dtype) * s[..., None].astype(dtype)
    return pages[tables] if layer is None else pages[layer, tables]


def shard_kv(pages, data_sharding, scale_sharding):
    """Place cache pages on a mesh: plain arrays take ``data_sharding``;
    QuantizedKV shards its f32 scale array WITH the int8 data on the same
    kv-head axis (``scale_sharding`` = the data spec minus the D axis), so
    dequant never crosses chips. The one helper behind both the paged
    (engine._shard_cache) and dense (DenseKVCache.create) TP caches."""
    if is_quantized(pages):
        return QuantizedKV(
            data=jax.device_put(pages.data, data_sharding),
            scale=jax.device_put(pages.scale, scale_sharding),
        )
    return jax.device_put(pages, data_sharding)


def kv_scatter(pages, update, page_idx, slot, *, leading_layer: bool = True):
    """``pages.at[(:,) page_idx, slot].set(update)`` with quantize-at-write
    fused in for int8 caches (per token-head amax/127 computed on the
    full-precision update, then one int8 scatter + one f32 scale scatter).
    Plain arrays take the identical ``.at[].set`` as before."""
    if is_quantized(pages):
        q = quantize_kv(update)
        if leading_layer:
            return QuantizedKV(
                data=pages.data.at[:, page_idx, slot].set(q.data),
                scale=pages.scale.at[:, page_idx, slot].set(q.scale),
            )
        return QuantizedKV(
            data=pages.data.at[page_idx, slot].set(q.data),
            scale=pages.scale.at[page_idx, slot].set(q.scale),
        )
    # cast to the page dtype explicitly (no-op when they already match):
    # jax deprecates implicit down-cast in scatter, and a f32-model +
    # bf16-cache engine would otherwise warn (then error) on every write
    if leading_layer:
        return pages.at[:, page_idx, slot].set(update.astype(pages.dtype))
    return pages.at[page_idx, slot].set(update.astype(pages.dtype))
