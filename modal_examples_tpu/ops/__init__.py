"""ops — Pallas TPU kernels + XLA references.

The native-kernel surface replacing the reference's CUDA dependencies
(SURVEY.md §2.4): flash attention (flash-attn), ragged paged decode attention
(vLLM PagedAttention), int8 quantized matmul (bitsandbytes/unsloth), ring
attention (sequence parallelism the reference lacks).
"""

from .flash_attention import (
    flash_attention,
    flash_attention_chunked,
    flash_attention_with_lse,
)
from .kv_quant import (
    QuantizedKV,
    dequantize_kv,
    is_quantized,
    kv_empty,
    kv_gather,
    kv_scatter,
    quantize_kv,
)
from .paged_attention import (
    paged_decode_attention,
    paged_decode_attention_inflight,
    paged_decode_attention_ragged,
    scatter_kv_pages,
)
from .quantized_matmul import dequantize_int8, quantize_int8, quantized_matmul
from .ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
from . import reference

__all__ = [
    "QuantizedKV",
    "dequantize_int8",
    "dequantize_kv",
    "flash_attention",
    "flash_attention_chunked",
    "flash_attention_with_lse",
    "paged_decode_attention",
    "paged_decode_attention_inflight",
    "paged_decode_attention_ragged",
    "is_quantized",
    "kv_empty",
    "kv_gather",
    "kv_scatter",
    "scatter_kv_pages",
    "quantize_int8",
    "quantize_kv",
    "quantized_matmul",
    "reference",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
