"""ops — Pallas TPU kernels + XLA references.

The native-kernel surface replacing the reference's CUDA dependencies
(SURVEY.md §2.4): flash attention (flash-attn), ragged paged decode attention
(vLLM PagedAttention), int8 quantized matmul (bitsandbytes/unsloth), ring
attention (sequence parallelism the reference lacks).
"""

from .flash_attention import (
    flash_attention,
    flash_attention_chunked,
    flash_attention_with_lse,
)
from .kv_quant import (
    QuantizedKV,
    dequantize_kv,
    is_quantized,
    kv_empty,
    kv_gather,
    kv_scatter,
    quantize_kv,
)
from .paged_attention import (
    paged_decode_attention,
    paged_decode_attention_inflight,
    paged_decode_attention_ragged,
    scatter_kv_pages,
)
from .quantized_matmul import dequantize_int8, quantize_int8, quantized_matmul
from .scan_loop import masked_scan
from .sharded import (
    mesh_tp_degree,
    shard_cache_pages,
    sharded_flash_attention,
    sharded_flash_attention_chunked,
    sharded_paged_decode_attention,
    sharded_ragged_decode,
    sharded_scatter_kv_pages,
)
from .ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
from . import reference

__all__ = [
    "QuantizedKV",
    "dequantize_int8",
    "dequantize_kv",
    "flash_attention",
    "flash_attention_chunked",
    "flash_attention_with_lse",
    "paged_decode_attention",
    "paged_decode_attention_inflight",
    "paged_decode_attention_ragged",
    "is_quantized",
    "kv_empty",
    "kv_gather",
    "kv_scatter",
    "masked_scan",
    "mesh_tp_degree",
    "scatter_kv_pages",
    "shard_cache_pages",
    "sharded_flash_attention",
    "sharded_flash_attention_chunked",
    "sharded_paged_decode_attention",
    "sharded_ragged_decode",
    "sharded_scatter_kv_pages",
    "quantize_int8",
    "quantize_kv",
    "quantized_matmul",
    "reference",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
