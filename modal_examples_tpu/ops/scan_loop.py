"""Masked early-exit scan: the macro-step decode loop's control-flow core.

:func:`masked_scan` runs a per-step body over a leading axis of inputs
while any lane of a boolean ``live`` mask is still set, and skips the body
entirely — one ``lax.cond`` per step, no transformer math — once every
lane is dead. It is the shared shape under two loops:

- the multi-step decode runtime (``serving/multistep``): N decode+sample
  steps fused into one jitted program, lanes dying at stop-token or
  length-budget boundaries (docs/multistep.md);
- a gamma-step speculative *verify* loop (ROADMAP #4): lanes die at the
  first rejected draft token, and the tail steps skip.

The contract mirrors ``jax.lax.scan`` with a mask threaded through:

- ``step(live, state, x) -> (live', state', out)`` runs when any lane is
  live. It must keep dead lanes inert itself (``jnp.where(live, ...)``) —
  the mask only short-circuits *whole* steps, not single lanes.
- ``hold(live, state, x) -> out`` produces the stacked output for a
  skipped step (typically the held tokens plus an all-false validity
  row). It must return the same pytree structure/dtypes as ``step``'s
  ``out`` — ``lax.cond`` requires matching branch signatures.

Both branches trace at compile time; the runtime cost of a skipped step
is the cond predicate plus a copy-through of the carry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_scan(step, hold, live0, state0, xs):
    """Scan ``step`` over ``xs`` carrying ``(live, state)``; skip steps via
    ``lax.cond`` once no lane is live. Returns ``(live, state, outs)`` with
    ``outs`` stacked along the leading axis like ``lax.scan``."""

    def body(carry, x):
        live, state = carry

        def run(operand):
            live_, state_ = operand
            return step(live_, state_, x)

        def skip(operand):
            live_, state_ = operand
            return live_, state_, hold(live_, state_, x)

        live, state, out = jax.lax.cond(
            jnp.any(live), run, skip, (live, state)
        )
        return (live, state), out

    (live, state), outs = jax.lax.scan(body, (live0, state0), xs)
    return live, state, outs
