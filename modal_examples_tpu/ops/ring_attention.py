"""Ring attention: context parallelism over a sequence-sharded mesh axis.

The reference has NO sequence-parallel/long-context machinery — its longest
contexts are engine flags (max_seq_length=32768, unsloth_finetune.py:386) and
vLLM/SGLang internals (SURVEY.md §5.7 calls this out as our value-add). This
module provides it TPU-natively:

- the sequence dimension is sharded over a mesh axis (``seq``);
- each shard computes blockwise attention between its local queries and a
  rotating K/V shard, passed around the ring with ``ppermute`` — on a TPU
  torus each hop is a neighbor ICI transfer, so K/V transit overlaps compute
  and no device ever holds the full sequence;
- partial results merge with the standard online-softmax rule using each
  block's logsumexp (from the flash kernel), so the result is exactly dense
  attention.

Causal masking: shard i attends to shard j's K/V only when j <= i (block
granularity), with the diagonal block using the in-kernel causal mask. The
per-hop `kv_index` bookkeeping makes that exact.

Usage: wrap in shard_map over a mesh with a "seq" axis — see
ring_attention_sharded() and tests/test_ops.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import flash_attention_with_lse
from ..parallel.collectives import axis_size as _axis_size
from ..parallel.mesh import shard_map_compat



def _merge(o1, lse1, o2, lse2):
    """Combine two attention partials over disjoint K/V sets."""
    m = jnp.maximum(lse1, lse2)
    # guard -inf (a block that saw nothing)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(lse1 - m_safe), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(lse2 - m_safe), 0.0)
    denom = w1 + w2
    denom_safe = jnp.where(denom > 0, denom, 1.0)
    o = (
        o1.astype(jnp.float32) * (w1 / denom_safe)[..., None]
        + o2.astype(jnp.float32) * (w2 / denom_safe)[..., None]
    )
    lse = jnp.where(denom > 0, m_safe + jnp.log(denom_safe), -jnp.inf)
    return o.astype(o1.dtype), lse


def ring_attention(
    q: jax.Array,  # [B, H, S_local, D] — this shard's queries
    k: jax.Array,  # [B, Hkv, S_local, D] — this shard's keys (hop 0)
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Call INSIDE shard_map with the sequence dim sharded over ``axis_name``."""
    n = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, S, D = q.shape

    o_acc = jnp.zeros_like(q)
    lse_acc = jnp.full((B, H, S), -jnp.inf, jnp.float32)

    def hop(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        kv_index = (my_idx - step) % n  # whose K/V we hold this hop

        # contribution of this K/V shard to our queries
        if causal:
            # diagonal shard: in-kernel causal mask; earlier shards: full;
            # later shards: masked out entirely. cond executes one branch.
            o_blk, lse_blk = lax.cond(
                kv_index == my_idx,
                lambda: flash_attention_with_lse(
                    q, k_cur, v_cur, causal=True, sm_scale=sm_scale
                ),
                lambda: flash_attention_with_lse(
                    q, k_cur, v_cur, causal=False, sm_scale=sm_scale
                ),
            )
            visible = kv_index <= my_idx
            o_blk = jnp.where(visible, o_blk, 0.0)
            lse_blk = jnp.where(visible, lse_blk, -jnp.inf)
        else:
            o_blk, lse_blk = flash_attention_with_lse(
                q, k_cur, v_cur, causal=False, sm_scale=sm_scale
            )
        o_new, lse_new = _merge(o_acc, lse_acc, o_blk, lse_blk)

        # rotate K/V one hop around the ring (neighbor ICI transfer)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, lse_new, k_nxt, v_nxt), None

    # scan (not fori_loop) so the ring is differentiable end to end:
    # ppermute transposes to the reverse ring in the backward pass
    (o_acc, lse_acc, _, _), _ = lax.scan(
        hop, (o_acc, lse_acc, k, v), jnp.arange(n)
    )
    return o_acc


def ring_attention_sharded(
    q, k, v, mesh, *, seq_axis: str = "seq", causal: bool = True,
    sm_scale: float | None = None,
):
    """Convenience wrapper: shard q/k/v over ``seq_axis`` and run the ring."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, seq_axis, None)
    fn = functools.partial(
        ring_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
    )
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,  # [B, H, S_local, D] — this shard's sequence slice
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Ulysses (DeepSpeed-style) sequence parallelism: two all_to_alls swap
    the sharded dimension from SEQUENCE to HEADS, so each shard runs plain
    full-sequence attention on H/n heads — exact, and a good fit when
    H >= shards and the interconnect is all-to-all friendly. The reference
    has no equivalent (SURVEY §2.3 row 'Ulysses: absent'); on a TPU torus
    the ring variant is usually preferred, but both are exact — pick by
    profile. Call inside shard_map with the seq dim sharded over
    ``axis_name``."""
    n = _axis_size(axis_name)
    B, H, S_loc, D = q.shape
    if H % n:
        raise ValueError(f"heads {H} must be divisible by seq shards {n}")

    def seq_to_heads(x):
        # [B, H, S_loc, D] -> [B, H/n, S_global, D]: give away head blocks,
        # receive every shard's tokens for our head block. concat_axis indexes
        # the shape AFTER the split dim is removed: [B, H/n, S_loc, D] with
        # the shard dim inserted at 2 -> [B, H/n, n, S_loc, D] (shard-major
        # global sequence).
        x = x.reshape(B, n, H // n, S_loc, D)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=False)
        return x.reshape(B, H // n, n * S_loc, D)

    def heads_to_seq(x):
        # inverse: [B, H/n, S_global, D] -> [B, H, S_loc, D]
        x = x.reshape(B, H // n, n, S_loc, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
        # [B, n, H/n, S_loc, D] -> [B, H, S_loc, D]
        return x.reshape(B, H, S_loc, D)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    og, _ = flash_attention_with_lse(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(og)


def ulysses_attention_sharded(
    q, k, v, mesh, *, seq_axis: str = "seq", causal: bool = True,
    sm_scale: float | None = None,
):
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, seq_axis, None)
    fn = functools.partial(
        ulysses_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
    )
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
