"""Blockwise flash attention for TPU (Pallas → Mosaic).

The training-side replacement for the reference's flash-attn CUDA wheel
(02_building_containers/install_flash_attn.py:19-33, learn_math.py:29-32) and
the SDPA inside its torch models (hp_sweep src/model.py:14-30).

Design (TPU-first, not a CUDA translation):
- grid = (batch*kv_heads*group, q_blocks, k_blocks); the LAST grid axis is
  sequential on TPU, so the online-softmax state (m, l, acc) lives in VMEM
  scratch carried across k-block steps — no atomics, no cross-block sync.
- blocks default to 128x128: MXU-shaped, and the f32 scratch tiles align to
  (8, 128).
- causal masking skips fully-masked k blocks via a zero-work early exit
  (the index map still walks them, but no FLOPs issue), and applies an
  elementwise triangle mask only on the one diagonal block.
- GQA folds the query-head group into the batch dimension; K/V blocks are
  indexed by kv head so grouped queries share the same K/V traffic.
- backward: dedicated Pallas kernels (dq with sequential k-blocks, dk/dv
  with sequential q-blocks) sharing per-block dS math, including the lse
  output's cotangent so ring/ulysses merges differentiate through the
  kernels; MTPU_FLASH_BWD=recompute switches to an XLA-recompute fallback.

Runs in interpreter mode off-TPU so CPU CI exercises the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import reference

_LANES = 128  # f32 scratch tile: (8, 128); m/l are broadcast across lanes


def _fwd_kernel(
    q_ref,  # (1, block_q, D)
    k_ref,  # (1, block_k, D)
    v_ref,  # (1, block_k, D)
    o_ref,  # (1, block_q, D)
    lse_ref,  # (1, block_q, LANES) — row stats ride a 128-lane dim: Mosaic
    #           requires output tiles shaped (8k, 128m); a bare (1, block_q)
    #           block fails lowering (the official TPU flash kernel pads the
    #           same way)
    m_scr,  # (block_q, LANES) f32
    l_scr,  # (block_q, LANES) f32
    acc_scr,  # (block_q, D) f32
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int = 0,
):
    del block_k  # derivable from refs; kept for signature symmetry
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: k blocks strictly above the diagonal contribute nothing.
    # q_offset shifts query GLOBAL positions (chunked prefill: this q chunk
    # starts at q_offset within the full sequence the K/V cover).
    block_k = k_ref.shape[1]
    q_start = qi * block_q + q_offset
    k_start = ki * block_k
    run = jnp.logical_or(not causal, k_start <= q_start + block_q - 1)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + k_start
            s = jnp.where(rows >= cols, s, -jnp.inf)

        m_prev = m_scr[:, :1]  # (block_q, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (m_new == -inf) from producing NaNs
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # finalize on the last k block this q block ever sees
    last_k = (
        jnp.minimum((q_start + block_q - 1) // block_k, nk - 1) if causal else nk - 1
    )

    @pl.when(ki == last_k)
    def _finalize():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l_safe), -jnp.inf)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_forward(
    q, k, v, *, causal: bool, sm_scale: float, block_q: int, block_k: int,
    interpret: bool, q_offset: int = 0,
):
    B, Hq, S, D = q.shape  # S = query length
    Hkv, Skv = k.shape[1], k.shape[2]
    if S % block_q or Skv % block_k:
        raise ValueError(
            f"lengths (q={S}, kv={Skv}) must be multiples of block sizes "
            f"({block_q}, {block_k}); pad sequences at the model layer"
        )
    if Hq % Hkv:
        raise ValueError(f"query heads {Hq} not a multiple of kv heads {Hkv}")
    if causal and q_offset + S > Skv:
        raise ValueError(
            f"q_offset {q_offset} + q len {S} exceeds kv len {Skv}"
        )
    group = Hq // Hkv
    # fold (B, Hkv, group) into one leading grid axis; kv index drops `group`
    qf = q.reshape(B * Hkv * group, S, D)
    kf = k.reshape(B * Hkv, Skv, D)
    vf = v.reshape(B * Hkv, Skv, D)

    grid = (B * Hkv * group, pl.cdiv(S, block_q), pl.cdiv(Skv, block_k))
    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, D), lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, D),
                lambda bh, qi, ki, g=group: (bh // g, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, D),
                lambda bh, qi, ki, g=group: (bh // g, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_q, D), lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct((B * Hkv * group, S, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * S * S * D * (0.5 if causal else 1.0)),
            bytes_accessed=(qf.size + kf.size + vf.size + qf.size) * q.dtype.itemsize,
            transcendentals=B * Hq * S * S,
        ),
        interpret=interpret,
    )(qf, kf, vf)
    return o.reshape(B, Hq, S, D), lse[:, :, 0].reshape(B, Hq, S)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Backward kernels. With S_scaled = scale*Q@K^T, P = exp(S_scaled - lse):
#   dV = P^T dO
#   dP = dO V^T
#   dS = P * (dP - D),  D_i = rowsum(dO_i * O_i)
#   dQ = scale * dS K          (accumulated over k blocks)
#   dK = scale * dS^T Q        (accumulated over q blocks)
# Two kernels: dq (grid bh, qi, ki — ki sequential into scratch) and dkv
# (grid bh, ki, qi — qi sequential into scratch). lse/delta ride along as
# per-row statistics; causal blocks above the diagonal are skipped.
# ---------------------------------------------------------------------------


def _bwd_block_ds(q, k, lse_row, delta_row, dlse_row, do, v, *, sm_scale,
                  causal, q_start, k_start):
    """Shared per-block math: returns (p, ds) both (block_q, block_k) f32.

    dS has two sources: the output path p*(dP - D), and the lse output's own
    cotangent (d lse/dS = p), so dS = p * (dP - D + dLSE) — the latter is
    what makes ring/ulysses merges (which consume lse) kernel-differentiable.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_start
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + k_start
        s = jnp.where(rows >= cols, s, -jnp.inf)
    finite = jnp.isfinite(lse_row)
    p = jnp.where(finite, jnp.exp(s - jnp.where(finite, lse_row, 0.0)), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta_row + dlse_row)
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dlse_ref,
               dq_ref, dq_scr, *, sm_scale, causal, block_q):
    qi, ki, nk = pl.program_id(1), pl.program_id(2), pl.num_programs(2)
    block_k = k_ref.shape[1]
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = jnp.logical_or(not causal, k_start <= q_start + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse_row = lse_ref[0][:, :1]
        delta_row = delta_ref[0][:, :1]
        dlse_row = dlse_ref[0][:, :1]
        _, ds = _bwd_block_ds(
            q, k, lse_row, delta_row, dlse_row, do, v, sm_scale=sm_scale,
            causal=causal, q_start=q_start, k_start=k_start,
        )
        dq_scr[:] += sm_scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dlse_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal, block_k):
    ki, qi, nq = pl.program_id(1), pl.program_id(2), pl.num_programs(2)
    block_q = q_ref.shape[1]
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = jnp.logical_or(not causal, q_start + block_q - 1 >= k_start)

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse_row = lse_ref[0][:, :1]
        delta_row = delta_ref[0][:, :1]
        dlse_row = dlse_ref[0][:, :1]
        p, ds = _bwd_block_ds(
            q, k, lse_row, delta_row, dlse_row, do, v, sm_scale=sm_scale,
            causal=causal, q_start=q_start, k_start=k_start,
        )
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[:] += sm_scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, *, causal, sm_scale, block_q, block_k,
                    interpret, g_lse=None):
    """Pallas backward: returns (dq, dk, dv) with GQA group reduction.
    ``g_lse`` carries the lse output's cotangent (ring/ulysses merges)."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    BHq = B * Hq
    qf = q.reshape(BHq, S, D)
    kf = k.reshape(B * Hkv, S, D)
    vf = v.reshape(B * Hkv, S, D)
    dof = g.reshape(BHq, S, D)
    # per-row stats ride a 128-lane dim (same Mosaic tiling constraint as the
    # forward's lse output; the kernels read lane 0)
    lsef = jnp.broadcast_to(lse.reshape(BHq, S)[:, :, None], (BHq, S, _LANES))
    dlsef = (
        jnp.zeros((BHq, S, _LANES), jnp.float32)
        if g_lse is None
        else jnp.broadcast_to(
            g_lse.astype(jnp.float32).reshape(BHq, S)[:, :, None],
            (BHq, S, _LANES),
        )
    )
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
        .reshape(BHq, S)[:, :, None],
        (BHq, S, _LANES),
    )

    kv_index = lambda bh, g=group: bh // g

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q
        ),
        grid=(BHq, pl.cdiv(S, block_q), pl.cdiv(S, block_k)),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (kv_index(bh), ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (kv_index(bh), ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta, dlsef)

    # dk/dv per QUERY head (kv blocks replicated across the group), then
    # group-summed outside the kernel
    dkv_grid = (BHq, pl.cdiv(S, block_k), pl.cdiv(S, block_q))
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k
        ),
        grid=dkv_grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (kv_index(bh), ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (kv_index(bh), ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHq, S, D), k.dtype),
            jax.ShapeDtypeStruct((BHq, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta, dlsef)

    dq = dq.reshape(B, Hq, S, D)
    dk = dk_h.reshape(B, Hkv, group, S, D).sum(axis=2).astype(k.dtype)
    dv = dv_h.reshape(B, Hkv, group, S, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Fused attention: q [B,Hq,S,D], k/v [B,Hkv,S,D] (GQA when Hkv < Hq)."""
    o, _ = _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _resolve_scale(q, sm_scale):
    return q.shape[-1] ** -0.5 if sm_scale is None else sm_scale


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    scale = _resolve_scale(q, sm_scale)
    S = q.shape[2]
    bq, bk = min(block_q, S), min(block_k, S)
    o, lse = _flash_forward(
        q, k, v, causal=causal, sm_scale=scale,
        block_q=bq, block_k=bk, interpret=_use_interpret(),
    )
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    scale = _resolve_scale(q, sm_scale)
    S = q.shape[2]
    bq, bk = min(block_q, S), min(block_k, S)
    import os as _os

    if _os.environ.get("MTPU_FLASH_BWD", "kernel") == "recompute":
        # XLA-recompute fallback (numerically identical; debugging aid)
        def ref(q, k, v):
            return reference.attention(q, k, v, causal=causal, sm_scale=scale)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    return _flash_backward(
        q, k, v, o, lse, g, causal=causal, sm_scale=scale,
        block_q=bq, block_k=bk, interpret=_use_interpret(),
    )


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_with_lse(q, k, v, causal, sm_scale):
    S = q.shape[2]
    return _flash_forward(
        q, k, v, causal=causal, sm_scale=sm_scale,
        block_q=min(128, S), block_k=min(128, S),
        interpret=_use_interpret(),
    )


def _flash_with_lse_fwd(q, k, v, causal, sm_scale):
    out = _flash_with_lse(q, k, v, causal, sm_scale)
    return out, (q, k, v, *out)


def _flash_with_lse_fwd_res(q, k, v, causal, sm_scale):
    o, lse = _flash_with_lse(q, k, v, causal, sm_scale)
    return (o, lse), (q, k, v, o, lse)


def _flash_with_lse_bwd(causal, sm_scale, res, cots):
    # dedicated Pallas backward; the lse output's cotangent (nonzero inside
    # ring/ulysses softmax merges) feeds the kernels' dS term directly
    q, k, v, o, lse = res
    g_o, g_lse = cots
    S = q.shape[2]
    import os as _os

    if _os.environ.get("MTPU_FLASH_BWD", "kernel") == "recompute":
        _, vjp = jax.vjp(
            lambda q, k, v: reference.attention_with_lse(
                q, k, v, causal=causal, sm_scale=sm_scale
            ),
            q, k, v,
        )
        return vjp(cots)
    return _flash_backward(
        q, k, v, o, lse, g_o, causal=causal, sm_scale=sm_scale,
        block_q=min(128, S), block_k=min(128, S),
        interpret=_use_interpret(), g_lse=g_lse,
    )


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def flash_attention_with_lse(
    q, k, v, *, causal=True, sm_scale=None, block_q=128, block_k=128
):
    """Variant also returning the per-row logsumexp (used by ring attention
    to combine partial results across shards). Differentiable: backward
    recomputes through the XLA reference (same pattern as flash_attention)."""
    del block_q, block_k  # fixed at 128 (clamped to S) on this path
    return _flash_with_lse(q, k, v, causal, _resolve_scale(q, sm_scale))


def flash_attention_chunked(
    q: jax.Array,  # [B, Hq, S_chunk, D] — queries at positions
                   # [q_offset, q_offset + S_chunk) of the full sequence
    k: jax.Array,  # [B, Hkv, S_kv, D] — the full (or so-far) K
    v: jax.Array,
    *,
    q_offset: int,
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Rectangular attention for chunked prefill: one query chunk against a
    longer K/V prefix (the engine processes long prompts chunk by chunk with
    bounded VMEM; also the building block for prefix-cache reuse). Forward
    only — prefill needs no gradients."""
    scale = _resolve_scale(q, sm_scale)
    Sq, Skv = q.shape[2], k.shape[2]

    def pick_block(S: int) -> int:
        for b in (128, 64, 32, 16, 8):
            if S % b == 0:
                return b
        return S

    o, _ = _flash_forward(
        q, k, v, causal=causal, sm_scale=scale,
        block_q=pick_block(Sq), block_k=pick_block(Skv),
        interpret=_use_interpret(), q_offset=q_offset,
    )
    return o
