"""Per-kernel bring-up probes for the wedge-proof compile harness.

Each probe compiles ONE Pallas kernel on the smallest Mosaic-legal shapes
(D=128 lanes, page_size%16 sublanes, Hkv%16 for the flattened page
matmuls), checks numerics against the pure-XLA references, and returns a
small dict of floats. Probes are run by
``modal_examples_tpu.utils.kernel_probe`` in a killable subprocess — see
that module for why first compiles are treated as hostile (two rounds of
chip-claim wedges). On CPU the same probes run in Pallas interpreter mode,
so the fast test tier exercises probe plumbing end to end.

Keep this registry in sync with the kernels: a test
(tests/test_kernel_probe.py) asserts every ops/ module that calls
``pl.pallas_call`` has at least one probe here.
"""

from __future__ import annotations

# probe name -> "module:function", in bring-up order: known-good kernels
# first, the riskiest (in-place DMA scatter, the round-4 wedge suspect)
# last so a wedge doesn't block validating everything else.
KERNEL_PROBES: dict[str, str] = {
    "flash_fwd": "modal_examples_tpu.ops.probes:probe_flash_fwd",
    "flash_bwd": "modal_examples_tpu.ops.probes:probe_flash_bwd",
    "flash_chunked": "modal_examples_tpu.ops.probes:probe_flash_chunked",
    "int8_matmul": "modal_examples_tpu.ops.probes:probe_int8_matmul",
    "paged_decode": "modal_examples_tpu.ops.probes:probe_paged_decode",
    "ragged_decode": "modal_examples_tpu.ops.probes:probe_ragged_decode",
    "ragged_decode_gqa": "modal_examples_tpu.ops.probes:probe_ragged_decode_gqa",
    # int8-KV bring-ups (the quantized-cache Mosaic paths: int8 page +
    # f32 scale-row DMAs, in-VMEM dequant). New DMA shapes => new
    # first-compile risk => probe-harness territory, per the wedge rule.
    "ragged_decode_int8kv":
        "modal_examples_tpu.ops.probes:probe_ragged_decode_int8kv",
    "ragged_decode_gqa_int8kv":
        "modal_examples_tpu.ops.probes:probe_ragged_decode_gqa_int8kv",
    # the TP=2 shard of the 7B int8 head geometry (Hq=Hkv=16, G=1): what
    # each device compiles inside the shard_map dispatch (ops.sharded) —
    # int8 flat needs Hkv%32, so the 16-head shard runs grouped
    "ragged_decode_tp_shard_int8kv":
        "modal_examples_tpu.ops.probes:probe_ragged_decode_tp_shard_int8kv",
    "scatter_kv": "modal_examples_tpu.ops.probes:probe_scatter_kv",
    "scatter_kv_int8": "modal_examples_tpu.ops.probes:probe_scatter_kv_int8",
}

# which probes cover which pallas_call-bearing module; a test asserts this
# stays in sync with the set of modules that actually call pl.pallas_call,
# so a new kernel module cannot land without a bring-up probe.
PROBED_MODULES: dict[str, list[str]] = {
    "modal_examples_tpu.ops.flash_attention": [
        "flash_fwd", "flash_bwd", "flash_chunked",
    ],
    "modal_examples_tpu.ops.paged_attention": [
        "paged_decode", "ragged_decode", "ragged_decode_gqa",
        "ragged_decode_int8kv", "ragged_decode_gqa_int8kv",
        "ragged_decode_tp_shard_int8kv", "scatter_kv", "scatter_kv_int8",
    ],
    "modal_examples_tpu.ops.quantized_matmul": ["int8_matmul"],
}


def _err(a, b) -> float:
    import jax.numpy as jnp

    return float(
        jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
    )


def probe_flash_fwd() -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops
    from modal_examples_tpu.ops import reference

    B, Hq, Hkv, S, D = 1, 8, 4, 256, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, Hq, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.bfloat16)
    o = jax.jit(ops.flash_attention)(q, k, v)
    ref = jax.jit(reference.attention)(q, k, v)
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def probe_flash_bwd() -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops
    from modal_examples_tpu.ops import reference

    B, Hq, Hkv, S, D = 1, 8, 4, 256, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, Hq, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: jax.numpy.sum(fn(q, k, v).astype(jnp.float32))

    g1 = jax.jit(jax.grad(loss(ops.flash_attention), argnums=(0, 1, 2)))(
        q, k, v
    )
    g2 = jax.jit(jax.grad(loss(reference.attention), argnums=(0, 1, 2)))(
        q, k, v
    )
    errs = [_err(a, b) for a, b in zip(g1, g2)]
    assert max(errs) < 0.5, errs  # sum-of-S grad scale
    return {"max_err": round(max(errs), 4)}


def probe_flash_chunked() -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops
    from modal_examples_tpu.ops import reference

    B, Hq, Hkv, S, D, C, off = 1, 8, 4, 256, 128, 128, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, Hq, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.bfloat16)
    qc = q[:, :, :C, :]
    o = jax.jit(
        lambda qc, k, v: ops.flash_attention_chunked(qc, k, v, q_offset=off)
    )(qc, k, v)
    qfull = q.at[:, :, off : off + C, :].set(qc)
    ref = jax.jit(reference.attention)(qfull, k, v)[:, :, off : off + C, :]
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def probe_int8_matmul() -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops

    M, K, N = 256, 512, 512
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.float32)
    w_q, w_scale = ops.quantize_int8(w)
    o = jax.jit(ops.quantized_matmul)(x, w_q, w_scale)
    ref = jnp.dot(
        x.astype(jnp.float32), ops.dequantize_int8(w_q, w_scale)
    )
    err = _err(o, ref)
    rel = err / (float(jnp.max(jnp.abs(ref))) + 1e-6)
    assert rel < 0.05, (err, rel)
    return {"rel_err": round(rel, 4)}


def probe_paged_decode() -> dict:
    import functools

    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops
    from modal_examples_tpu.ops import reference

    B, Hq, Hkv, D, ps, pp = 2, 16, 16, 128, 16, 4
    n_pages = B * pp + 2
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (n_pages, ps, Hkv, D), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.PRNGKey(1), (n_pages, ps, Hkv, D), jnp.bfloat16
    )
    pt = jax.random.permutation(jax.random.PRNGKey(2), n_pages)[
        : B * pp
    ].reshape(B, pp).astype(jnp.int32)
    lens = jnp.array([30, 57], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(3), (B, Hq, D), jnp.bfloat16)
    o = jax.jit(functools.partial(ops.paged_decode_attention, impl="pallas"))(
        q, kp, vp, pt, lens
    )
    ref = jax.jit(reference.paged_decode_attention)(q, kp, vp, pt, lens)
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def probe_ragged_decode() -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops

    L, B, Hq, Hkv, D, ps, pp = 2, 2, 16, 16, 128, 16, 4
    n_pages = B * pp + 1
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (L, n_pages, ps, Hkv, D), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.PRNGKey(1), (L, n_pages, ps, Hkv, D), jnp.bfloat16
    )
    pt = (1 + jnp.arange(B * pp, dtype=jnp.int32)).reshape(B, pp)
    prefix = jnp.array([19, 44], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), jnp.bfloat16)
    k_new = jax.random.normal(jax.random.PRNGKey(3), (B, Hkv, D), jnp.bfloat16)
    v_new = jax.random.normal(jax.random.PRNGKey(4), (B, Hkv, D), jnp.bfloat16)
    layer = jnp.int32(1)
    o = jax.jit(ops.paged_decode_attention_ragged)(
        q, kp, vp, layer, pt, prefix, k_new, v_new
    )
    ks = kp[1][pt]  # [B, pp, ps, Hkv, D]
    vs = vp[1][pt]
    ref = jax.jit(ops.paged_decode_attention_inflight)(
        q, ks, vs, prefix, k_new, v_new
    )
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def probe_ragged_decode_gqa() -> dict:
    """The v4 "grouped" per-kv-head formulation at a GQA shape (Hkv=8,
    G=4 — the llama-3.1 head geometry): no (ps*Hkv) flatten, so Hkv%16
    doesn't apply. First-compile risk: the per-head strided VMEM slices."""
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops

    L, B, Hq, Hkv, D, ps, pp = 2, 2, 32, 8, 128, 16, 4
    n_pages = B * pp + 1
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (L, n_pages, ps, Hkv, D), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.PRNGKey(1), (L, n_pages, ps, Hkv, D), jnp.bfloat16
    )
    pt = (1 + jnp.arange(B * pp, dtype=jnp.int32)).reshape(B, pp)
    prefix = jnp.array([23, 61], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), jnp.bfloat16)
    k_new = jax.random.normal(jax.random.PRNGKey(3), (B, Hkv, D), jnp.bfloat16)
    v_new = jax.random.normal(jax.random.PRNGKey(4), (B, Hkv, D), jnp.bfloat16)
    import functools

    o = jax.jit(functools.partial(
        ops.paged_decode_attention_ragged, variant="grouped"
    ))(q, kp, vp, jnp.int32(1), pt, prefix, k_new, v_new)
    ref = jax.jit(ops.paged_decode_attention_inflight)(
        q, kp[1][pt], vp[1][pt], prefix, k_new, v_new
    )
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def _int8kv_ragged_probe(Hq: int, Hkv: int, variant: str) -> dict:
    """Shared body for the int8-KV ragged bring-ups: quantized cache into
    the kernel vs the XLA inflight reference over the DEQUANTIZED pages —
    isolates kernel correctness from quantization noise, so the bound is
    the same 0.06 the bf16 probes use."""
    import functools

    import jax
    import jax.numpy as jnp

    from modal_examples_tpu import ops

    L, B, D, ps, pp = 2, 2, 128, 16, 4
    n_pages = B * pp + 1
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (L, n_pages, ps, Hkv, D), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.PRNGKey(1), kp.shape, jnp.bfloat16
    )
    qkp, qvp = ops.quantize_kv(kp), ops.quantize_kv(vp)
    pt = (1 + jnp.arange(B * pp, dtype=jnp.int32)).reshape(B, pp)
    prefix = jnp.array([19, 44], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (B, Hq, D), jnp.bfloat16)
    k_new = jax.random.normal(jax.random.PRNGKey(3), (B, Hkv, D), jnp.bfloat16)
    v_new = jax.random.normal(jax.random.PRNGKey(4), (B, Hkv, D), jnp.bfloat16)
    o = jax.jit(functools.partial(
        ops.paged_decode_attention_ragged, variant=variant
    ))(q, qkp, qvp, jnp.int32(1), pt, prefix, k_new, v_new)
    dk = ops.dequantize_kv(qkp)[1][pt]
    dv = ops.dequantize_kv(qvp)[1][pt]
    ref = jax.jit(ops.paged_decode_attention_inflight)(
        q, dk, dv, prefix, k_new, v_new
    )
    err = _err(o, ref)
    assert err < 0.06, err
    return {"max_err": round(err, 4)}


def probe_ragged_decode_int8kv() -> dict:
    """int8-KV flat variant (Hkv=32: the int8 page flatten needs Hkv%32 —
    (32, 128) tiles). First-compile risk: the f32 scale-row DMAs + the
    in-VMEM int8 dequant multiply."""
    return _int8kv_ragged_probe(Hq=32, Hkv=32, variant="flat")


def probe_ragged_decode_gqa_int8kv() -> dict:
    """int8-KV grouped variant at the GQA shape (Hkv=8, G=4): per-head
    strided int8 slices + their (chunk, ps) scale slices."""
    return _int8kv_ragged_probe(Hq=32, Hkv=8, variant="grouped")


def probe_ragged_decode_tp_shard_int8kv() -> dict:
    """int8-KV grouped variant at the TP=2 shard of the 7B head geometry
    (Hq=Hkv=16, G=1): the per-device compile shape of the shard_map'd
    decode under tensor parallelism (ops.sharded, round 7). MHA-as-grouped
    is a distinct Mosaic shape family — 16 single-row head matmuls — so
    its first compile goes through the harness like every other."""
    return _int8kv_ragged_probe(Hq=16, Hkv=16, variant="grouped")


def probe_scatter_kv_int8() -> dict:
    """int8-KV scatter: four-array DMA pipeline (int8 K/V columns + f32
    scale columns). Same in-place-DMA risk class as scatter_kv; runs after
    it so a bf16 scatter wedge is attributed first."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu import ops

    L, P, ps, Hkv, D, B = 2, 6, 16, 32, 128, 3
    kp = ops.quantize_kv(jax.random.normal(
        jax.random.PRNGKey(0), (L, P, ps, Hkv, D), jnp.float32
    ))
    vp = ops.quantize_kv(jax.random.normal(
        jax.random.PRNGKey(1), (L, P, ps, Hkv, D), jnp.float32
    ))
    k_all = jax.random.normal(
        jax.random.PRNGKey(2), (L, B, Hkv, D), jnp.bfloat16
    )
    v_all = jax.random.normal(jax.random.PRNGKey(3), k_all.shape, jnp.bfloat16)
    page_idx = jnp.array([1, 3, 5], jnp.int32)
    slot = jnp.array([0, 7, 15], jnp.int32)
    qk, qv = ops.quantize_kv(k_all), ops.quantize_kv(v_all)
    # references BEFORE the call: kp/vp are donated through the jit. All
    # FOUR arrays are checked — v's scale column rides the 4th sem column,
    # the one DMA no other probe exercises.
    ref_kd = kp.data.at[:, page_idx, slot].set(qk.data)
    ref_ks = kp.scale.at[:, page_idx, slot].set(qk.scale)
    ref_vd = vp.data.at[:, page_idx, slot].set(qv.data)
    ref_vs = vp.scale.at[:, page_idx, slot].set(qv.scale)
    ok, ov = jax.jit(ops.scatter_kv_pages, donate_argnums=(0, 1))(
        kp, vp, k_all, v_all, page_idx, slot
    )
    err = max(_err(ok.data, ref_kd), _err(ok.scale, ref_ks))
    err = max(err, _err(ov.data, ref_vd), _err(ov.scale, ref_vs))
    assert err == 0.0, err
    # every non-target entry untouched (data AND scale)
    assert bool(np.asarray(jnp.all(ok.data[:, 0] == ref_kd[:, 0])))
    assert bool(np.asarray(jnp.all(ok.scale[:, 0] == ref_ks[:, 0])))
    return {"max_err": err}


def probe_scatter_kv() -> dict:
    """The round-4 wedge suspect: in-place strided HBM->HBM DMA scatter.
    Runs LAST in the registry; always bring this up through the probe
    harness, never in-process."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu import ops

    L, P, ps, Hkv, D, B = 2, 6, 16, 16, 128, 3
    kp = jax.random.normal(
        jax.random.PRNGKey(0), (L, P, ps, Hkv, D), jnp.bfloat16
    )
    vp = jax.random.normal(jax.random.PRNGKey(1), kp.shape, jnp.bfloat16)
    k_all = jax.random.normal(
        jax.random.PRNGKey(2), (L, B, Hkv, D), jnp.bfloat16
    )
    v_all = jax.random.normal(jax.random.PRNGKey(3), k_all.shape, jnp.bfloat16)
    page_idx = jnp.array([1, 3, 5], jnp.int32)
    slot = jnp.array([0, 7, 15], jnp.int32)
    ref_k = kp.at[:, page_idx, slot].set(k_all)
    ref_v = vp.at[:, page_idx, slot].set(v_all)
    ok, ov = jax.jit(ops.scatter_kv_pages, donate_argnums=(0, 1))(
        kp, vp, k_all, v_all, page_idx, slot
    )
    err = max(_err(ok, ref_k), _err(ov, ref_v))
    assert err == 0.0, err
    # every non-target entry untouched
    assert bool(np.asarray(jnp.all(ok[:, 0] == ref_k[:, 0])))
    return {"max_err": err}
