"""Pure-XLA reference implementations of the framework's custom kernels.

Three jobs (SURVEY.md §4's "fake backend" tier):
1. numerical ground truth for Pallas kernel tests;
2. CPU fallback so every model runs (slowly) without a TPU;
3. the recompute path for backward passes until dedicated bwd kernels land.

These replace the reference repo's dependence on flash-attn / vLLM CUDA
kernels (install_flash_attn.py:19-33, vllm_inference.py engine internals) —
the semantics live here, the speed lives in the Pallas siblings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def attention(
    q: jax.Array,  # [B, Hq, S, D]
    k: jax.Array,  # [B, Hkv, S, D]
    v: jax.Array,  # [B, Hkv, S, D]
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    logit_cap: float | None = None,
) -> jax.Array:
    """Dense softmax attention with GQA (Hq a multiple of Hkv)."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, S, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if logit_cap is not None:
        s = logit_cap * jnp.tanh(s / logit_cap)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), v)
    return o.reshape(B, Hq, S, D)


def attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Dense attention also returning per-row logsumexp [B, Hq, S] — the
    differentiable ground truth for flash_attention_with_lse (ring attention's
    backward recomputes through this)."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, S, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B,Hkv,g,S]
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), v)
    return o.reshape(B, Hq, S, D), lse.reshape(B, Hq, S)


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] — one new token per sequence
    k_pages: jax.Array,  # [Hkv, n_pages, page_size, D]
    v_pages: jax.Array,  # [Hkv, n_pages, page_size, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32 — physical page ids
    context_lens: jax.Array,  # [B] int32 — tokens already in cache (incl. new)
    *,
    sm_scale: float | None = None,
) -> jax.Array:
    """Decode-step attention over a paged KV cache (vLLM-semantics ground
    truth for the Pallas ragged kernel)."""
    B, Hq, D = q.shape
    Hkv, _, page_size, _ = k_pages.shape
    group = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    S = pages_per_seq * page_size
    if sm_scale is None:
        sm_scale = D**-0.5

    # gather each sequence's logical KV [B, Hkv, S, D]
    ks = k_pages[:, page_tables]  # [Hkv, B, pages, page_size, D]
    vs = v_pages[:, page_tables]
    ks = ks.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, S, D)
    vs = vs.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, S, D)

    qg = q.reshape(B, Hkv, group, D)
    s = jnp.einsum("bhgd,bhkd->bhgk", qg, ks, preferred_element_type=jnp.float32)
    s = s * sm_scale
    positions = jnp.arange(S)[None, :]  # [1, S]
    valid = positions < context_lens[:, None]  # [B, S]
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgk,bhkd->bhgd", p.astype(vs.dtype), vs)
    return o.reshape(B, Hq, D)
