"""Pure-XLA reference implementations of the framework's custom kernels.

Three jobs (SURVEY.md §4's "fake backend" tier):
1. numerical ground truth for Pallas kernel tests;
2. CPU fallback so every model runs (slowly) without a TPU;
3. the recompute path for backward passes until dedicated bwd kernels land.

These replace the reference repo's dependence on flash-attn / vLLM CUDA
kernels (install_flash_attn.py:19-33, vllm_inference.py engine internals) —
the semantics live here, the speed lives in the Pallas siblings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .kv_quant import kv_gather


def attention(
    q: jax.Array,  # [B, Hq, S, D]
    k: jax.Array,  # [B, Hkv, S, D]
    v: jax.Array,  # [B, Hkv, S, D]
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    logit_cap: float | None = None,
) -> jax.Array:
    """Dense softmax attention with GQA (Hq a multiple of Hkv)."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, S, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if logit_cap is not None:
        s = logit_cap * jnp.tanh(s / logit_cap)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), v)
    return o.reshape(B, Hq, S, D)


def attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Dense attention also returning per-row logsumexp [B, Hq, S] — the
    differentiable ground truth for flash_attention_with_lse (ring attention's
    backward recomputes through this)."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, S, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B,Hkv,g,S]
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), v)
    return o.reshape(B, Hq, S, D), lse.reshape(B, Hq, S)


def attention_chunked(
    q: jax.Array,  # [B, Hq, Sq, D] — queries at positions q_offset..q_offset+Sq
    k: jax.Array,  # [B, Hkv, Skv, D] — full (or so-far) K
    v: jax.Array,
    *,
    q_offset: int,
    sm_scale: float | None = None,
) -> jax.Array:
    """Rectangular causal attention: the XLA ground truth for
    ops.flash_attention_chunked (TP prefill now keeps the flash kernel via
    ops.sharded's shard_map dispatch; this reference stays the
    auto-partitionable fallback and the exactness oracle)."""
    B, Hq, Sq, D = q.shape
    Hkv, Skv = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = D**-0.5
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, Sq, D)
    s = jnp.einsum(
        "bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * sm_scale
    rows = q_offset + jnp.arange(Sq)[:, None]
    cols = jnp.arange(Skv)[None, :]
    s = jnp.where(rows >= cols, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), v)
    return o.reshape(B, Hq, Sq, D)


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] — one new token per sequence
    k_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    v_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32 — physical page ids
    context_lens: jax.Array,  # [B] int32 — tokens already in cache (incl. new)
    *,
    sm_scale: float | None = None,
) -> jax.Array:
    """Decode-step attention over a paged KV cache (vLLM-semantics ground
    truth for the Pallas ragged kernel). int8 (QuantizedKV) page caches
    dequantize in the gather."""
    B, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    group = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    S = pages_per_seq * page_size
    if sm_scale is None:
        sm_scale = D**-0.5

    # gather each sequence's logical KV [B, Hkv, S, D]; int8 caches
    # dequantize at the query's dtype (same as the kernels' VMEM dequant)
    ks = kv_gather(k_pages, page_tables, dtype=q.dtype)
    vs = kv_gather(v_pages, page_tables, dtype=q.dtype)
    ks = ks.transpose(0, 3, 1, 2, 4).reshape(B, Hkv, S, D)
    vs = vs.transpose(0, 3, 1, 2, 4).reshape(B, Hkv, S, D)

    qg = q.reshape(B, Hkv, group, D)
    s = jnp.einsum("bhgd,bhkd->bhgk", qg, ks, preferred_element_type=jnp.float32)
    s = s * sm_scale
    positions = jnp.arange(S)[None, :]  # [1, S]
    valid = positions < context_lens[:, None]  # [B, S]
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgk,bhkd->bhgd", p.astype(vs.dtype), vs)
    return o.reshape(B, Hq, D)


def paged_verify_attention(
    q: jax.Array,  # [B, T, Hq, D] — a short chain of new tokens per sequence
    k_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    v_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32
    positions: jax.Array,  # [B, T] int32 — global position of each query
    *,
    sm_scale: float | None = None,
) -> jax.Array:  # [B, T, Hq, D]
    """Teacher-forced attention of a T-token chain against the paged cache
    (the chain's own KV must already be written). Query t attends to cache
    positions <= positions[b, t] — the multi-token generalization of
    ``paged_decode_attention`` used by speculative-decoding verification
    (the reference ships spec decode engine-side, vllm_inference.py:196-205).
    int8 (QuantizedKV) page caches dequantize in the gather, so the verify
    pass scores proposals against exactly the KV values decode will read.
    """
    B, T, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    group = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    S = pages_per_seq * page_size
    if sm_scale is None:
        sm_scale = D**-0.5

    # int8 caches dequantize in the gather at the query's dtype
    ks = kv_gather(k_pages, page_tables, dtype=q.dtype)
    vs = kv_gather(v_pages, page_tables, dtype=q.dtype)
    ks = ks.transpose(0, 3, 1, 2, 4).reshape(B, Hkv, S, D)
    vs = vs.transpose(0, 3, 1, 2, 4).reshape(B, Hkv, S, D)

    qg = q.transpose(0, 2, 1, 3).reshape(B, Hkv, group, T, D)
    s = jnp.einsum(
        "bhgtd,bhkd->bhgtk", qg, ks, preferred_element_type=jnp.float32
    )
    s = s * sm_scale
    cols = jnp.arange(S)[None, None, :]  # [1, 1, S]
    valid = cols <= positions[:, :, None]  # [B, T, S]
    s = jnp.where(valid[:, None, None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgtk,bhkd->bhgtd", p.astype(vs.dtype), vs)
    return o.reshape(B, Hq, T, D).transpose(0, 2, 1, 3)
