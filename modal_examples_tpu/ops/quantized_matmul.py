"""Quantized matmul: int8 weights x bf16 activations (Pallas → Mosaic).

The TPU-native replacement for the reference's bitsandbytes / unsloth 4-bit
paths (unsloth_finetune.py:58,187-197 loads models "in 4bit"): weights are
stored int8 with per-output-channel f32 scales (AQT-style symmetric
quantization), halving HBM traffic for bandwidth-bound decode matmuls; the
MXU natively consumes int8.

Kernel: grid over (M_tiles, N_tiles, K_tiles); K is the sequential axis, an
f32 accumulator lives in scratch across K steps; dequantization by the
per-channel scale happens once at the final K step (not per-tile), so the
inner loop is pure int8xbf16 MXU work.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def quantize_int8(w: jax.Array, axis: int = 0) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8 quantization along ``axis`` (the contraction
    axis of the later matmul stays unscaled)."""
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.round(w.astype(jnp.float32) / scale).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, n_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[:].astype(jnp.bfloat16)
    w = w_ref[:].astype(jnp.bfloat16)  # int8 -> bf16 on the way into the MXU
    acc_scr[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] * s_ref[0]).astype(o_ref.dtype)


def quantized_matmul(
    x: jax.Array,  # [M, K] bf16/f32
    w_q: jax.Array,  # [K, N] int8
    w_scale: jax.Array,  # [1, N] f32 per-output-channel
    *,
    block_m: int = 256,
    block_n: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    M, K = x.shape
    K2, N = w_q.shape
    assert K == K2, (K, K2)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bm, bn, bk = min(block_m, M), min(block_n, N), min(block_k, K)
    if M % bm or N % bn or K % bk:
        # shapes that don't tile cleanly fall back to XLA (still fast there)
        return (
            jnp.dot(x.astype(jnp.float32), dequantize_int8(w_q, w_scale))
        ).astype(x.dtype)
    n_k = K // bk
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, n_k=n_k),
        grid=(M // bm, N // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=M * K * 2 + K * N + M * N * 2,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, w_q, w_scale)
    return out
