"""shard_map dispatch for the Pallas fast paths under tensor parallelism.

A ``pallas_call`` cannot be auto-partitioned: under a sharded jit, GSPMD
either fails to compile the kernel or forces a full-cache gather onto every
device. Until this round the serving engine therefore refused
``mesh= + paged_impl="pallas"`` and silently downgraded prefill to the XLA
attention path — the moment serving went multi-chip, every decode-kernel win
from rounds 3–5 was lost (ROADMAP open item #2).

This module is the ONE dispatch layer that fixes that: each wrapper takes
the mesh alongside the kernel operands and

- with no mesh (or a 1-wide ``tensor`` axis) falls straight through to the
  plain kernel — the single-chip path is byte-for-byte what it always was;
- with a real ``tensor`` axis wraps the kernel in ``shard_map`` over the
  kv-head dimension, so every device runs the unmodified Mosaic kernel on
  its local head shard.

Why the kv-head axis: the Ragged Paged Attention kernel is explicitly
designed to shard there (PAPERS.md, arxiv 2604.15464) — decode attention is
fully head-local (query head ``h`` reads only kv head ``h // group``), so a
head-sharded cache means every page byte, its f32 scale row (int8 caches),
and all of its attention math stay on the chip that owns the head. There is
**no kernel-level collective**: outputs come back sharded on the head axis
(the concat over shards IS the epilogue), and the one reduction TP needs —
summing per-head partial outputs through the row-parallel ``wo`` — happens
in the surrounding auto-partitioned matmul exactly as on the XLA path.
The scatter is head-local for the same reason (pages shard on ``Hkv``; page
ids are global and un-sharded), and quantize-at-write stays bit-exact under
sharding because int8 scales are per (token, head).

Per-shard legality: inside ``shard_map`` the kernels see ``Hkv // tp`` and
``Hq // tp`` heads, so Mosaic shape legality — the flat variant's
``Hkv % 16`` (bf16) / ``% 32`` (int8) page flatten, GQA grouping — must be
evaluated against the LOCAL shard shapes. The wrappers do this implicitly
(the kernel sees local shapes); ``llama.paged_impl_plan(mesh=...)`` is the
reporting mirror, so a plan and the kernels can't drift.

Serving code (``models/llama.py``, ``serving/``) must reach Pallas ONLY
through these wrappers — a raw kernel call under the engine's
auto-partitioned jits is the exact bug class the old engine guard errored
on, and a static guard (tests/test_static.py) now makes it unrepresentable
instead.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..parallel.mesh import TENSOR, shard_map_compat
from .flash_attention import flash_attention, flash_attention_chunked
from .kv_quant import QuantizedKV, is_quantized
from .paged_attention import (
    paged_decode_attention,
    paged_decode_attention_ragged,
    scatter_kv_pages,
)


def mesh_tp_degree(mesh, axis: str = TENSOR) -> int:
    """Size of the mesh's tensor axis (1 when mesh is None or the axis is
    absent) — the single helper every mesh-aware dispatch + plan uses."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(axis, 1))


def shard_cache_pages(mesh, k_pages, v_pages, *, axis: str = TENSOR):
    """Place a full [L, P, ps, Hkv, D] paged cache on the mesh with the
    canonical kv-head sharding (int8 caches: f32 scale rows ride the SAME
    head axis as their data) — the ONE placement rule behind the engine's
    ``_shard_cache`` and the TP microbench, so the two cannot drift.
    Returns the (k_pages, v_pages) pair; no-op placement when mesh is
    None."""
    from jax.sharding import NamedSharding

    from .kv_quant import shard_kv

    if mesh is None:
        return k_pages, v_pages
    data_sh = NamedSharding(mesh, P(None, None, None, axis, None))
    scale_sh = NamedSharding(mesh, P(None, None, None, axis))
    return (
        shard_kv(k_pages, data_sh, scale_sh),
        shard_kv(v_pages, data_sh, scale_sh),
    )


def _check_heads(tp: int, name_shapes: list[tuple[str, int]]) -> None:
    for name, n in name_shapes:
        if n % tp:
            raise ValueError(
                f"{name}={n} is not divisible by the tensor-parallel degree "
                f"{tp}: head-sharded kernels need whole heads per shard"
            )


def _pages_specs(quantized: bool, axis: str, head_dim: int = 3):
    """(in_specs, operand-flatten, rebuild) for one page operand whose
    kv-head axis sits at ``head_dim`` ([L, P, ps, Hkv, D] → 3; the
    writeback path's per-layer [P, ps, Hkv, D] → 2): plain arrays are one
    head-sharded leaf; QuantizedKV flattens to (int8 data, f32 scale) with
    the scale sharded on the SAME head axis so in-kernel dequant never
    crosses chips — the one place that data/scale pairing rule lives."""
    lead = (None,) * head_dim
    data = P(*lead, axis, None)
    if not quantized:
        return [data], lambda pg: [pg], lambda leaves: leaves[0]
    scale = P(*lead, axis)
    return (
        [data, scale],
        lambda pg: [pg.data, pg.scale],
        lambda leaves: QuantizedKV(data=leaves[0], scale=leaves[1]),
    )


def sharded_ragged_decode(
    mesh,
    q,  # [B, Hq, D]
    k_pages,  # [L, P, ps, Hkv, D] array or QuantizedKV
    v_pages,
    layer,  # scalar int32
    page_tables,  # [B, pages_per_seq] int32 — GLOBAL page ids (P not sharded)
    prefix_lens,  # [B] int32
    k_new,  # [B, Hkv, D]
    v_new,
    *,
    sm_scale: float | None = None,
    variant: str | None = None,
    interpret: bool | None = None,
    axis: str = TENSOR,
):
    """Ragged paged decode attention (flat v3 / grouped v4, incl. int8-KV)
    under tensor parallelism: every device runs the kernel on its local
    kv-head shard of the cache; output comes back sharded on the query-head
    axis (no psum — attention is head-local; ``wo`` reduces outside).

    ``variant=None`` resolves per SHARD: inside ``shard_map`` the kernel
    sees ``Hkv // tp`` heads, so e.g. a 32-head bf16 cache runs "flat" on
    one chip but its 16-head TP=2 shard still runs "flat", while its int8
    form (Hkv%32 flatten) drops to "grouped" — exactly what
    ``llama.paged_impl_plan(mesh=...)`` reports.
    """
    tp = mesh_tp_degree(mesh, axis)
    if tp <= 1:
        return paged_decode_attention_ragged(
            q, k_pages, v_pages, layer, page_tables, prefix_lens, k_new,
            v_new, sm_scale=sm_scale, variant=variant, interpret=interpret,
        )
    _check_heads(
        tp, [("n_heads", q.shape[1]), ("n_kv_heads", k_new.shape[1])]
    )
    quantized = is_quantized(k_pages)
    pg_specs, flatten, rebuild = _pages_specs(quantized, axis)
    heads = P(None, axis, None)
    n_pg = len(pg_specs)

    def local(q, *rest):
        kp = rebuild(rest[:n_pg])
        vp = rebuild(rest[n_pg : 2 * n_pg])
        layer, tables, lens, k_new, v_new = rest[2 * n_pg :]
        return paged_decode_attention_ragged(
            q, kp, vp, layer, tables, lens, k_new, v_new,
            sm_scale=sm_scale, variant=variant, interpret=interpret,
        )

    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(
            heads, *pg_specs, *pg_specs, P(), P(None, None), P(None),
            heads, heads,
        ),
        out_specs=heads,
    )
    return fn(
        q, *flatten(k_pages), *flatten(v_pages), layer, page_tables,
        prefix_lens, k_new, v_new,
    )


def sharded_scatter_kv_pages(
    mesh,
    k_pages,  # [L, P, ps, Hkv, D] array or QuantizedKV
    v_pages,
    k_all,  # [L, B, Hkv, D]
    v_all,
    page_idx,  # [B] int32 — global page ids
    slot,  # [B] int32
    *,
    interpret: bool | None = None,
    axis: str = TENSOR,
):
    """Post-scan KV scatter under tensor parallelism: each device DMAs its
    own head columns into its local page shard (page ids are global; the
    page axis is replicated). int8 caches quantize INSIDE the shard — exact
    under sharding, because scales are per (token, head) over the local D
    row. Falls through to the plain kernel when there is no tensor axis."""
    tp = mesh_tp_degree(mesh, axis)
    if tp <= 1:
        return scatter_kv_pages(
            k_pages, v_pages, k_all, v_all, page_idx, slot,
            interpret=interpret,
        )
    _check_heads(tp, [("n_kv_heads", k_all.shape[2])])
    quantized = is_quantized(k_pages)
    pg_specs, flatten, rebuild = _pages_specs(quantized, axis)
    new_kv = P(None, None, axis, None)
    n_pg = len(pg_specs)

    def local(*args):
        kp = rebuild(args[:n_pg])
        vp = rebuild(args[n_pg : 2 * n_pg])
        k_all, v_all, page_idx, slot = args[2 * n_pg :]
        ok, ov = scatter_kv_pages(
            kp, vp, k_all, v_all, page_idx, slot, interpret=interpret
        )
        return tuple(flatten(ok)) + tuple(flatten(ov))

    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(
            *pg_specs, *pg_specs, new_kv, new_kv, P(None), P(None),
        ),
        out_specs=tuple(pg_specs) + tuple(pg_specs),
    )
    out = fn(
        *flatten(k_pages), *flatten(v_pages), k_all, v_all, page_idx, slot
    )
    return rebuild(list(out[:n_pg])), rebuild(list(out[n_pg:]))


def sharded_flash_attention(
    mesh,
    q,  # [B, Hq, S, D]
    k,  # [B, Hkv, S, D]
    v,
    causal: bool = True,
    *,
    axis: str = TENSOR,
):
    """Flash prefill attention under tensor parallelism: heads shard over
    the tensor axis (GQA groups stay whole per shard), each device runs the
    unmodified Pallas kernel on its local heads — per-head math is
    IDENTICAL to the single-chip kernel, so sharded prefill is bit-exact
    per head, not merely close. Forward-only on the serving path."""
    tp = mesh_tp_degree(mesh, axis)
    if tp <= 1:
        return flash_attention(q, k, v, causal)
    _check_heads(tp, [("n_heads", q.shape[1]), ("n_kv_heads", k.shape[1])])
    heads = P(None, axis, None, None)
    return shard_map_compat(
        lambda q, k, v: flash_attention(q, k, v, causal),
        mesh=mesh,
        in_specs=(heads, heads, heads),
        out_specs=heads,
    )(q, k, v)


def sharded_flash_attention_chunked(
    mesh,
    q,  # [B, Hq, C, D]
    k,  # [B, Hkv, S_kv, D]
    v,
    *,
    q_offset: int,
    axis: str = TENSOR,
):
    """Chunked-prefill flash (rectangular q chunk vs the full prefix) under
    tensor parallelism — same head sharding as ``sharded_flash_attention``,
    with the chunk's global ``q_offset`` passed through unchanged."""
    tp = mesh_tp_degree(mesh, axis)
    if tp <= 1:
        return flash_attention_chunked(q, k, v, q_offset=q_offset)
    _check_heads(tp, [("n_heads", q.shape[1]), ("n_kv_heads", k.shape[1])])
    heads = P(None, axis, None, None)
    return shard_map_compat(
        lambda q, k, v: flash_attention_chunked(q, k, v, q_offset=q_offset),
        mesh=mesh,
        in_specs=(heads, heads, heads),
        out_specs=heads,
    )(q, k, v)


def sharded_paged_decode_attention(
    mesh,
    q,  # [B, Hq, D]
    k_pages,  # [P, ps, Hkv, D] — per-layer pages (the writeback structure)
    v_pages,
    page_tables,  # [B, pages_per_seq] int32
    context_lens,  # [B] int32
    *,
    impl: str | None = None,
    axis: str = TENSOR,
):
    """The legacy write-then-attend decode kernel under tensor parallelism
    (the ``pallas-writeback`` A/B lever): same head sharding, per-layer
    [P, ps, Hkv, D] page views. Inside the shard the wrapper's own shape
    legality applies to the LOCAL head count (an Hkv//tp below 16 silently
    takes the XLA gather per shard, exactly like single-chip sub-16)."""
    tp = mesh_tp_degree(mesh, axis)
    if tp <= 1:
        return paged_decode_attention(
            q, k_pages, v_pages, page_tables, context_lens, impl=impl
        )
    _check_heads(
        tp, [("n_heads", q.shape[1]), ("n_kv_heads", k_pages.shape[2])]
    )
    quantized = is_quantized(k_pages)
    # per-layer [P, ps, Hkv, D] pages: the head axis sits one dim earlier
    pg_specs, flatten, rebuild = _pages_specs(quantized, axis, head_dim=2)
    heads = P(None, axis, None)
    n_pg = len(pg_specs)

    def local(q, *rest):
        kp = rebuild(rest[:n_pg])
        vp = rebuild(rest[n_pg : 2 * n_pg])
        tables, lens = rest[2 * n_pg :]
        return paged_decode_attention(q, kp, vp, tables, lens, impl=impl)

    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(heads, *pg_specs, *pg_specs, P(None, None), P(None)),
        out_specs=heads,
    )(q, *flatten(k_pages), *flatten(v_pages), page_tables, context_lens)
