"""Ragged paged decode attention for TPU (Pallas → Mosaic).

The TPU-native replacement for vLLM's PagedAttention CUDA kernels — the core
of the reference's north-star serving path (vllm_inference.py; SURVEY.md §7
hard part #1: "Ragged paged attention kernel + continuous batching in JAX").

Memory layout (TPU-first, v2):
- KV cache pages live in **HBM** as ``[n_pages, Hkv, page_size, D]`` — one
  page holds ALL kv heads contiguously, so a single DMA moves
  ``Hkv * page_size * D`` elements (128KB at 7B shapes) instead of one tiny
  (page_size, D) tile per head. v1's per-(seq, head) grid issued 4KB DMAs
  and was ~50x off the HBM bandwidth floor on a real v5e chip.
- Each sequence owns a list of physical page ids (its *page table*); pages
  are allocated/freed by the serving engine's block allocator.

Kernel design:
- grid = (batch,): decode attention is HBM-bandwidth-bound; fewer, fatter
  programs keep the DMA engine streaming instead of paying per-program and
  per-DMA latency. Page tables + context lengths arrive via scalar prefetch
  (SMEM) so the kernel computes its own DMA addresses — the "ragged" part:
  each sequence reads exactly ceil(ctx/page_size) pages.
- pages stream HBM→VMEM with double buffering, overlapped with the
  online-softmax update of the previous page.
- all heads in ONE MXU matmul per page: q rows (all Hq query heads) against
  the page's (Hkv*page_size, D) keys with a block-diagonal head mask —
  off-head logits are -inf so the p·V matmul accumulates per-head results
  exactly. The off-diagonal FLOPs are free (the MXU is idle in a
  bandwidth-bound kernel); what matters is that both contractions are
  single dense (Hq, Hkv*ps, D) matmuls instead of Hkv tiny ones.

Runs in interpreter mode off-TPU (CPU CI), with a dense XLA reference in
ops.reference for ground truth.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    page_tables_ref,  # (B * pages_per_seq,) int32, SMEM
    ctx_lens_ref,  # (B,) int32, SMEM
    # inputs
    q_ref,  # (1, Hq, D) VMEM
    k_hbm,  # (n_pages, Hkv, page_size, D) ANY/HBM
    v_hbm,  # (n_pages, Hkv, page_size, D) ANY/HBM
    # outputs
    o_ref,  # (1, Hq, D) VMEM
    # scratch
    k_scr,  # (2, Hkv, page_size, D) VMEM
    v_scr,  # (2, Hkv, page_size, D) VMEM
    acc_scr,  # (Hq, D) f32
    sems,  # DMA sems (2, 2)
    *,
    page_size: int,
    pages_per_seq: int,
    group: int,  # Hq // Hkv
    sm_scale: float,
):
    b = pl.program_id(0)
    ctx = ctx_lens_ref[b]
    n_pages = pl.cdiv(ctx, page_size)

    def page_id(i):
        return page_tables_ref[b * pages_per_seq + i]

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[page_id(i)], k_scr.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[page_id(i)], v_scr.at[slot], sems.at[slot, 1]
        )

    @pl.when(n_pages > 0)
    def _():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    acc_scr[:] = jnp.zeros_like(acc_scr)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (Hq, D)
    Hq, D = q.shape
    Hkv = k_scr.shape[1]
    W = Hkv * page_size  # page width in the flattened-heads layout

    # static (Hq, W) head-alignment mask: query row r (kv head r // group)
    # may only see columns of its own kv head (column c // page_size)
    row_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 0) // group
    col_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) // page_size
    head_ok = row_head == col_head
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) % page_size

    def body(i, carry):
        m_prev, l_prev = carry  # (Hq, 1) each
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _prefetch():
            nxt = jax.lax.rem(i + 1, 2)
            k_dma(nxt, i + 1).start()
            v_dma(nxt, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()
        k = k_scr[slot].reshape(W, D).astype(jnp.float32)
        v = v_scr[slot].reshape(W, D).astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (Hq, W)
        valid = head_ok & (i * page_size + col_tok < ctx)
        s = jnp.where(valid, s, -jnp.inf)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (Hq, D) — off-head columns of p are 0, so per-head rows are exact
        acc_scr[:] = acc_scr[:] * alpha + pv
        return m_new, l_new

    init = (
        jnp.full((Hq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((Hq, 1), jnp.float32),
    )
    _, l_final = jax.lax.fori_loop(0, n_pages, body, init)
    l_safe = jnp.where(l_final > 0, l_final, 1.0)
    o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _paged_decode_xla(
    q, k_pages, v_pages, page_tables, context_lens, sm_scale
):
    """Gather + layout-preserving einsums — the default decode path.

    Measured on a v5e chip at 7B decode shapes (B=8, 32 heads, D=128,
    ctx 256): ~0.05 ms vs 1.5 ms for the hand-written Pallas kernel and
    1.7 ms for a transpose-then-einsum formulation. The trick is that no
    operand is ever relaid out: the einsums contract directly over the
    gathered ``[B, pages, Hkv, page_size, D]`` page layout, so XLA fuses
    gather → QK → softmax → PV into bandwidth-bound loops. Also (unlike a
    pallas_call) this is auto-partitionable under a sharded jit, which is
    what lets tensor-parallel serving shard the page cache by kv head.
    """
    B, Hq, D = q.shape
    _, Hkv, page_size, _ = k_pages.shape
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]

    ks = k_pages[page_tables]  # [B, pp, Hkv, ps, D]
    vs = v_pages[page_tables]
    qg = q.reshape(B, Hkv, G, D)
    s = jnp.einsum(
        "bhgd,bphtd->bhgpt", qg.astype(jnp.float32), ks.astype(jnp.float32)
    ) * sm_scale  # [B, Hkv, G, pp, ps]
    pos = (
        jnp.arange(pages_per_seq)[:, None] * page_size
        + jnp.arange(page_size)[None, :]
    )  # [pp, ps]
    valid = pos[None] < context_lens[:, None, None]  # [B, pp, ps]
    s = jnp.where(valid[:, None, None], s, -jnp.inf)
    flat = s.reshape(B, Hkv, G, pages_per_seq * page_size)
    p = jax.nn.softmax(flat, axis=-1).reshape(s.shape)
    o = jnp.einsum("bhgpt,bphtd->bhgd", p, vs.astype(jnp.float32))
    return o.reshape(B, Hq, D).astype(q.dtype)


def paged_decode_attention_inflight(
    q: jax.Array,  # [B, Hq, D]
    ks: jax.Array,  # [B, pages_per_seq, Hkv, page_size, D] — gathered pages
    vs: jax.Array,
    prefix_lens: jax.Array,  # [B] int32 — tokens already IN the cache
    k_new: jax.Array,  # [B, Hkv, D] — current token's K (not yet written)
    v_new: jax.Array,
    *,
    sm_scale: float | None = None,
) -> jax.Array:  # [B, Hq, D]
    """Decode attention over the cached prefix PLUS the in-flight token.

    The round-2 decode step wrote each token's K/V into the page arrays
    *inside* the layer scan and returned the full caches as stacked scan
    ys — a structure XLA materializes as full cache-slice traffic every
    layer of every step (measured: the single biggest gap between the 28 ms
    step and the weight-streaming floor). Keeping the current token's K/V in
    registers lets the model scatter ALL layers' KV once per step, outside
    the scan, so the pages are read-only here: prefix scores come from the
    gathered pages, the current token contributes one extra logit column,
    and both share one softmax. Exact same math as write-then-attend with
    ``ctx_lens = prefix_lens + 1``.
    """
    B, Hq, D = q.shape
    _, pages_per_seq, Hkv, page_size, _ = ks.shape
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = D**-0.5
    qg = q.reshape(B, Hkv, G, D).astype(jnp.float32)
    s = jnp.einsum("bhgd,bphtd->bhgpt", qg, ks.astype(jnp.float32)) * sm_scale
    pos = (
        jnp.arange(pages_per_seq)[:, None] * page_size
        + jnp.arange(page_size)[None, :]
    )  # [pp, ps]
    valid = pos[None] < prefix_lens[:, None, None]  # [B, pp, ps]
    s = jnp.where(valid[:, None, None], s, -jnp.inf)
    flat = s.reshape(B, Hkv, G, pages_per_seq * page_size)
    # match the numerics of the write-then-attend path bit-for-bit: the old
    # path read the current token back from the cache, i.e. at cache dtype
    s_new = jnp.einsum(
        "bhgd,bhd->bhg", qg, k_new.astype(ks.dtype).astype(jnp.float32)
    )[..., None] * sm_scale  # [B, Hkv, G, 1]
    all_s = jnp.concatenate([flat, s_new], axis=-1)
    p = jax.nn.softmax(all_s, axis=-1)
    p_prefix = p[..., :-1].reshape(s.shape)
    p_new = p[..., -1]  # [B, Hkv, G]
    o = jnp.einsum("bhgpt,bphtd->bhgd", p_prefix, vs.astype(jnp.float32))
    o = o + p_new[..., None] * (
        v_new.astype(vs.dtype).astype(jnp.float32)[:, :, None, :]
    )
    return o.reshape(B, Hq, D).astype(q.dtype)


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D]
    k_pages: jax.Array,  # [n_pages, Hkv, page_size, D]
    v_pages: jax.Array,  # [n_pages, Hkv, page_size, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32
    context_lens: jax.Array,  # [B] int32
    *,
    sm_scale: float | None = None,
    interpret: bool | None = None,
    impl: str | None = None,  # None/env: "xla" (default) or "pallas"
) -> jax.Array:  # [B, Hq, D]
    """One decode step of attention against the paged KV cache.

    Default impl is the fused-gather XLA formulation (see
    ``_paged_decode_xla`` for on-chip measurements); the Pallas kernel is
    kept selectable (``MTPU_PAGED_IMPL=pallas``) as the base for future
    tuning where its exact-ctx page reads matter (very long, very ragged
    contexts where the gather's pages_per_seq padding dominates).
    """
    import os

    B, Hq, D = q.shape
    n_pages, Hkv, page_size, _ = k_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if impl is None:
        impl = os.environ.get("MTPU_PAGED_IMPL", "xla")

    # Mosaic DMA units are (sublane, lane) tiles — a page must be a whole
    # number of (16, 128) bf16 tiles or the HBM→VMEM copies fail to lower
    # (observed on-chip with head_dim 32). Sub-tile shapes (tiny/test models)
    # take the XLA path regardless of impl.
    if impl != "pallas" or (not interpret and (D % 128 or page_size % 16)):
        return _paged_decode_xla(
            q, k_pages, v_pages, page_tables, context_lens, sm_scale
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec(
                (1, Hq, D), lambda b, *_refs: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, Hq, D), lambda b, *_refs: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, Hkv, page_size, D), k_pages.dtype),
            pltpu.VMEM((2, Hkv, page_size, D), v_pages.dtype),
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        pages_per_seq=pages_per_seq,
        group=G,
        sm_scale=sm_scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            # each sequence reads shared pages but writes a distinct output
            # block: the grid is safely parallel
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * pages_per_seq * page_size * Hkv * D),
            bytes_accessed=int(
                2 * B * pages_per_seq * Hkv * page_size * D
                * k_pages.dtype.itemsize
            ),
            transcendentals=int(B * Hq * pages_per_seq * page_size * Hkv),
        ),
        interpret=interpret,
    )(page_tables.reshape(-1).astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pages, v_pages)
    return out
