"""Ragged paged decode attention for TPU (Pallas → Mosaic).

The TPU-native replacement for vLLM's PagedAttention CUDA kernels — the core
of the reference's north-star serving path (vllm_inference.py; SURVEY.md §7
hard part #1: "Ragged paged attention kernel + continuous batching in JAX").

Memory layout (TPU-first, v2):
- KV cache pages live in **HBM** as ``[n_pages, page_size, Hkv, D]`` — one
  page holds ALL kv heads contiguously (token-major, heads innermost), so a
  single DMA moves ``page_size * Hkv * D`` elements (128KB at 7B shapes)
  instead of one tiny (page_size, D) tile per head. v1's per-(seq, head)
  grid issued 4KB DMAs and was ~50x off the HBM bandwidth floor on a real
  v5e chip. Heads-innermost (round 4) keeps the token dim OUT of the
  packed minor tile dims, so single-token scatter writes are legal strided
  DMAs (bf16 HBM memrefs pack sublane pairs — slicing a token row of the
  old [.., Hkv, ps, D] layout cannot lower; Hkv < 16 pages pay sublane
  padding instead, acceptable because GQA caches are Hkv/Hq-fraction
  sized).
- Each sequence owns a list of physical page ids (its *page table*); pages
  are allocated/freed by the serving engine's block allocator.

Kernel design:
- grid = (batch,): decode attention is HBM-bandwidth-bound; fewer, fatter
  programs keep the DMA engine streaming instead of paying per-program and
  per-DMA latency. Page tables + context lengths arrive via scalar prefetch
  (SMEM) so the kernel computes its own DMA addresses — the "ragged" part:
  each sequence reads exactly ceil(ctx/page_size) pages.
- pages stream HBM→VMEM with double buffering, overlapped with the
  online-softmax update of the previous page.
- all heads in ONE MXU matmul per page: q rows (all Hq query heads) against
  the page's (Hkv*page_size, D) keys with a block-diagonal head mask —
  off-head logits are -inf so the p·V matmul accumulates per-head results
  exactly. The off-diagonal FLOPs are free (the MXU is idle in a
  bandwidth-bound kernel); what matters is that both contractions are
  single dense (Hq, Hkv*ps, D) matmuls instead of Hkv tiny ones.

Runs in interpreter mode off-TPU (CPU CI), with a dense XLA reference in
ops.reference for ground truth.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kv_quant import QuantizedKV, is_quantized, kv_gather, quantize_kv

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept
# whichever this jax ships so the ragged kernels work on both
_CompilerParamsCls = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _CompilerParams(**kw):
    if _CompilerParamsCls is None:
        # lazy so a further-renamed class breaks the kernel call with an
        # actionable message, not package import (the XLA decode path
        # doesn't need pallas at all)
        raise RuntimeError(
            "this jax exposes neither pltpu.CompilerParams nor "
            "pltpu.TPUCompilerParams; the pallas paged-attention kernels "
            "cannot compile — use the XLA impls (MTPU_PAGED_IMPL=xla)"
        )
    return _CompilerParamsCls(**kw)


def _decode_kernel(
    # scalar prefetch
    page_tables_ref,  # (B * pages_per_seq,) int32, SMEM
    ctx_lens_ref,  # (B,) int32, SMEM
    # inputs
    q_ref,  # (1, Hq, D) VMEM
    k_hbm,  # (n_pages, page_size, Hkv, D) ANY/HBM
    v_hbm,  # (n_pages, page_size, Hkv, D) ANY/HBM
    # outputs
    o_ref,  # (1, Hq, D) VMEM
    # scratch
    k_scr,  # (2, page_size, Hkv, D) VMEM
    v_scr,  # (2, page_size, Hkv, D) VMEM
    acc_scr,  # (Hq, D) f32
    sems,  # DMA sems (2, 2)
    *,
    page_size: int,
    pages_per_seq: int,
    group: int,  # Hq // Hkv
    sm_scale: float,
):
    b = pl.program_id(0)
    ctx = ctx_lens_ref[b]
    n_pages = pl.cdiv(ctx, page_size)

    def page_id(i):
        return page_tables_ref[b * pages_per_seq + i]

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[page_id(i)], k_scr.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[page_id(i)], v_scr.at[slot], sems.at[slot, 1]
        )

    @pl.when(n_pages > 0)
    def _():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    acc_scr[:] = jnp.zeros_like(acc_scr)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (Hq, D)
    Hq, D = q.shape
    Hkv = k_scr.shape[2]
    W = page_size * Hkv  # page width, token-major flatten (tok, head)

    # static (Hq, W) head-alignment mask: query row r (kv head r // group)
    # may only see columns of its own kv head (column c % Hkv)
    row_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 0) // group
    col_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) % Hkv
    head_ok = row_head == col_head
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) // Hkv

    def body(i, carry):
        m_prev, l_prev = carry  # (Hq, 1) each
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _prefetch():
            nxt = jax.lax.rem(i + 1, 2)
            k_dma(nxt, i + 1).start()
            v_dma(nxt, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()
        k = k_scr[slot].reshape(W, D).astype(jnp.float32)
        v = v_scr[slot].reshape(W, D).astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (Hq, W)
        valid = head_ok & (i * page_size + col_tok < ctx)
        s = jnp.where(valid, s, -jnp.inf)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (Hq, D) — off-head columns of p are 0, so per-head rows are exact
        acc_scr[:] = acc_scr[:] * alpha + pv
        return m_new, l_new

    init = (
        jnp.full((Hq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((Hq, 1), jnp.float32),
    )
    _, l_final = jax.lax.fori_loop(0, n_pages, body, init)
    l_safe = jnp.where(l_final > 0, l_final, 1.0)
    o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _paged_decode_xla(
    q, k_pages, v_pages, page_tables, context_lens, sm_scale
):
    """Gather + layout-preserving einsums — the default decode path.

    Measured on a v5e chip at 7B decode shapes (B=8, 32 heads, D=128,
    ctx 256): ~0.05 ms vs 1.5 ms for the hand-written Pallas kernel and
    1.7 ms for a transpose-then-einsum formulation. The trick is that no
    operand is ever relaid out: the einsums contract directly over the
    gathered ``[B, pages, page_size, Hkv, D]`` page layout, so XLA fuses
    gather → QK → softmax → PV into bandwidth-bound loops. Also (unlike a
    pallas_call) this is auto-partitionable under a sharded jit, which is
    what lets tensor-parallel serving shard the page cache by kv head.

    int8 caches (:class:`~.kv_quant.QuantizedKV`) dequantize HERE: one
    multiply at the query's dtype fused into the gather (bf16 on the
    serving path, matching the ragged kernels' in-VMEM dequant exactly),
    so the HBM page reads stay int8.
    """
    B, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]

    ks = kv_gather(k_pages, page_tables, dtype=q.dtype)  # [B, pp, ps, Hkv, D]
    vs = kv_gather(v_pages, page_tables, dtype=q.dtype)
    qg = q.reshape(B, Hkv, G, D)
    # operands stay in cache dtype INTO the MXU (f32 accumulation via
    # preferred_element_type): an `.astype(f32)` on the gathered pages
    # materializes an f32 copy of the whole gathered cache in HBM —
    # measured round 4 (benchmarks/decode_ablate.py) as the dominant,
    # superlinear-in-slots decode cost (44 of 57 ms/step at 7B, 32 slots)
    s = jnp.einsum(
        "bhgd,bpthd->bhgpt", qg, ks, preferred_element_type=jnp.float32
    ) * sm_scale  # [B, Hkv, G, pp, ps] f32
    pos = (
        jnp.arange(pages_per_seq)[:, None] * page_size
        + jnp.arange(page_size)[None, :]
    )  # [pp, ps]
    valid = pos[None] < context_lens[:, None, None]  # [B, pp, ps]
    s = jnp.where(valid[:, None, None], s, -jnp.inf)
    flat = s.reshape(B, Hkv, G, pages_per_seq * page_size)
    p = jax.nn.softmax(flat, axis=-1).reshape(s.shape)
    # probabilities at cache dtype for the PV contraction (flash-attention
    # numerics: f32 softmax, bf16 PV operands, f32 accumulation)
    o = jnp.einsum(
        "bhgpt,bpthd->bhgd", p.astype(vs.dtype), vs,
        preferred_element_type=jnp.float32,
    )
    return o.reshape(B, Hq, D).astype(q.dtype)


def paged_decode_attention_inflight(
    q: jax.Array,  # [B, Hq, D]
    ks: jax.Array,  # [B, pages_per_seq, page_size, Hkv, D] — gathered pages
    vs: jax.Array,
    prefix_lens: jax.Array,  # [B] int32 — tokens already IN the cache
    k_new: jax.Array,  # [B, Hkv, D] — current token's K (not yet written)
    v_new: jax.Array,
    *,
    sm_scale: float | None = None,
) -> jax.Array:  # [B, Hq, D]
    """Decode attention over the cached prefix PLUS the in-flight token.

    The round-2 decode step wrote each token's K/V into the page arrays
    *inside* the layer scan and returned the full caches as stacked scan
    ys — a structure XLA materializes as full cache-slice traffic every
    layer of every step (measured: the single biggest gap between the 28 ms
    step and the weight-streaming floor). Keeping the current token's K/V in
    registers lets the model scatter ALL layers' KV once per step, outside
    the scan, so the pages are read-only here: prefix scores come from the
    gathered pages, the current token contributes one extra logit column,
    and both share one softmax. Exact same math as write-then-attend with
    ``ctx_lens = prefix_lens + 1``.
    """
    B, Hq, D = q.shape
    _, pages_per_seq, page_size, Hkv, _ = ks.shape
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = D**-0.5
    qg = q.reshape(B, Hkv, G, D)
    # cache-dtype operands into the MXU, f32 accumulation — an astype(f32)
    # on the gathered pages materializes an f32 cache copy per layer per
    # step; measured as the dominant decode cost (benchmarks/decode_ablate)
    s = jnp.einsum(
        "bhgd,bpthd->bhgpt", qg, ks, preferred_element_type=jnp.float32
    ) * sm_scale
    pos = (
        jnp.arange(pages_per_seq)[:, None] * page_size
        + jnp.arange(page_size)[None, :]
    )  # [pp, ps]
    valid = pos[None] < prefix_lens[:, None, None]  # [B, pp, ps]
    s = jnp.where(valid[:, None, None], s, -jnp.inf)
    flat = s.reshape(B, Hkv, G, pages_per_seq * page_size)
    # match the numerics of the write-then-attend path: the old path read
    # the current token back from the cache, i.e. at cache dtype
    s_new = jnp.einsum(
        "bhgd,bhd->bhg", qg, k_new.astype(ks.dtype),
        preferred_element_type=jnp.float32,
    )[..., None] * sm_scale  # [B, Hkv, G, 1]
    all_s = jnp.concatenate([flat, s_new], axis=-1)
    p = jax.nn.softmax(all_s, axis=-1)
    p_prefix = p[..., :-1].reshape(s.shape).astype(vs.dtype)
    p_new = p[..., -1]  # [B, Hkv, G] f32
    o = jnp.einsum(
        "bhgpt,bpthd->bhgd", p_prefix, vs,
        preferred_element_type=jnp.float32,
    )
    o = o + p_new[..., None] * (
        v_new.astype(vs.dtype).astype(jnp.float32)[:, :, None, :]
    )
    return o.reshape(B, Hq, D).astype(q.dtype)


def _decode_kernel_ragged(
    # scalar prefetch
    layer_ref,  # (1,) int32, SMEM — which layer of the [L, P, ...] cache
    page_tables_ref,  # (B * pages_per_seq,) int32, SMEM
    prefix_lens_ref,  # (B,) int32, SMEM — tokens already IN the cache
    # inputs — FULL arrays as single constant-index blocks: Mosaic skips the
    # re-fetch when a block's index map is unchanged between grid steps, so
    # q/k_new/v_new stream into VMEM once per pallas_call instead of paying
    # 4 small block DMAs per program (measured ~18 us/program of pure
    # overhead at 7B shapes with per-program (1, H, D) blocks)
    q_ref,  # (B, Hq, D) VMEM
    k_new_ref,  # (B, Hkv, D) VMEM — current token's K (not yet written)
    v_new_ref,  # (B, Hkv, D) VMEM
    k_hbm,  # (L, n_pages, page_size, Hkv, D) ANY/HBM
    v_hbm,
    # quantized=True adds ks_hbm/vs_hbm (L, n_pages, page_size, Hkv) f32
    # scale inputs and ks_scr/vs_scr (depth, page_size, Hkv) scratch rings;
    # sems widen to (depth, 4). `*rest` keeps ONE kernel for both layouts.
    *rest,  # [ks_hbm, vs_hbm,] o_ref, k_scr, v_scr, [ks_scr, vs_scr,]
    # acc_scr, sems
    page_size: int,
    pages_per_seq: int,
    group: int,  # Hq // Hkv
    sm_scale: float,
    quantized: bool = False,
):
    """Ragged decode attention v3: prefix pages + ONE in-flight column.

    v2 (write-then-attend, `_decode_kernel`) forced the model to scatter each
    layer's KV into the cache *before* attention — the scan-threaded cache
    structure XLA materializes as full cache copies (round-3 NOTES). v3 keeps
    the pages READ-ONLY (the fast decode structure: one scatter per step,
    after the layer scan) by folding the current token's K/V — still in
    registers — into the online softmax as one extra logit column, exactly
    like ops.paged_decode_attention_inflight does in XLA. It also indexes the
    full [L, P, ...] cache via a prefetched layer scalar, so the layer scan
    never slices (= copies) a per-layer cache view. Reads exactly
    ceil(prefix/page_size) pages per sequence — the XLA gather formulation
    reads (and materializes) all pages_per_seq pages regardless of context,
    measured round 4 as the dominant, superlinear-in-slots decode cost
    (benchmarks/decode_ablate.py: 44 of 57 ms/step at 7B int8, 32 slots).

    With ``quantized=True`` the pages stream as int8 plus a per-token-head
    f32 scale row, and the dequant (one bf16 multiply) happens on the VMEM
    copy right before the MXU — KV HBM traffic is halved, the online
    softmax math is unchanged.
    """
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_scr, v_scr, ks_scr, vs_scr, acc_scr,
         sems) = rest
    else:
        o_ref, k_scr, v_scr, acc_scr, sems = rest
        ks_hbm = vs_hbm = ks_scr = vs_scr = None
    b = pl.program_id(0)
    li = layer_ref[0]
    prefix, n_pages, depth, k_dma, v_dma = _ragged_ring_setup(
        li, page_tables_ref, prefix_lens_ref, b, k_hbm, v_hbm, k_scr, v_scr,
        sems, pages_per_seq, ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_scr=ks_scr,
        vs_scr=vs_scr,
    )

    acc_scr[:] = jnp.zeros_like(acc_scr)
    q = q_ref[b]  # (Hq, D) — stays in model dtype INTO the MXU (native
    # mixed-precision, f32 accumulate); sm_scale is applied to the f32
    # scores. Explicit astype(f32) on the page operands forced a Mosaic
    # retile of every page (measured ~0.6 us of the ~2.3 us/page cost).
    Hq, D = q.shape
    Hkv = k_scr.shape[2]
    W = page_size * Hkv  # token-major flatten: column c = (tok, head)

    # static (Hq, W) head-alignment mask: query row r (kv head r // group)
    # may only see columns of its own kv head (column c % Hkv). The
    # off-head MXU FLOPs are the price of one dense matmul per page; at
    # MHA (group=1, the 7B shape) that is Hkv x more logits than exist —
    # the measured per-page cost is ~2 us compute-bound (a VPU
    # mul+lane-reduce formulation measured the same, round 4).
    row_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 0) // group
    col_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) % Hkv
    head_ok = row_head == col_head
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1) // Hkv

    def body(i, carry):
        m_prev, l_prev = carry  # (Hq, 1) each
        slot = jax.lax.rem(i, depth)

        # refill the slot consumed LAST iteration (its loads are done:
        # sequential loop order) with the page depth-1 ahead — keeps
        # depth-1 transfers in flight so the DMA engine streams
        # back-to-back instead of paying issue latency per page
        @pl.when(i + depth - 1 < n_pages)
        def _prefetch():
            nxt = jax.lax.rem(i + depth - 1, depth)
            for c in k_dma(nxt, i + depth - 1) + v_dma(nxt, i + depth - 1):
                c.start()

        for c in k_dma(slot, i) + v_dma(slot, i):
            c.wait()
        if quantized:
            # dequant at the VMEM load: int8 page * its f32 scale row, one
            # multiply per element at the query's compute dtype (bf16 on
            # the serving path — matches the XLA gather fallback)
            k = (
                k_scr[slot].astype(q.dtype)
                * ks_scr[slot][..., None].astype(q.dtype)
            ).reshape(W, D)
            v = (
                v_scr[slot].astype(q.dtype)
                * vs_scr[slot][..., None].astype(q.dtype)
            ).reshape(W, D)
        else:
            k = k_scr[slot].reshape(W, D)  # cache dtype, no retile
            v = v_scr[slot].reshape(W, D)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (Hq, W) f32
        valid = head_ok & (i * page_size + col_tok < prefix)
        s = jnp.where(valid, s, -jnp.inf)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(
            jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0
        )
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # flash-attention numerics: f32 softmax, cache-dtype PV operands
        acc_scr[:] = acc_scr[:] * alpha + pv
        return m_new, l_new

    init = (
        jnp.full((Hq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((Hq, 1), jnp.float32),
    )
    m_prev, l_prev = jax.lax.fori_loop(0, n_pages, body, init)

    _inflight_epilogue(
        q, k_new_ref, v_new_ref, b, o_ref, acc_scr, m_prev, l_prev, group,
        sm_scale,
    )


def ragged_shapes_ok(head_dim: int, page_size: int) -> bool:
    """Mosaic legality for the ragged decode kernels on TPU: pages must be
    whole (16, 128) bf16 tiles for the HBM→VMEM DMAs. Single source of
    truth shared by the kernel wrappers (hard error) and
    ``llama.paged_impl_plan`` (soft downgrade to the XLA gather)."""
    return head_dim % 128 == 0 and page_size % 16 == 0


def flat_variant_hkv_multiple(kv_dtype: str = "bfloat16") -> int:
    """The Hkv multiple the "flat" variant's (ps, Hkv, D) -> (ps*Hkv, D)
    page flatten needs: the sublane count of one packed Mosaic tile —
    16 for bf16, 32 for int8 ((32, 128) tiles)."""
    return 32 if str(kv_dtype) == "int8" else 16


def ragged_variant_for(n_kv_heads: int, kv_dtype: str = "bfloat16") -> str:
    """Default kernel formulation: "flat" (one all-heads matmul) needs the
    (ps, Hkv, D) -> (ps*Hkv, D) flatten, legal only at Hkv % tile-sublanes
    (16 bf16, 32 int8); everything else (GQA) takes "grouped" (per-kv-head
    contractions)."""
    return (
        "flat"
        if n_kv_heads % flat_variant_hkv_multiple(kv_dtype) == 0
        else "grouped"
    )


def scatter_shapes_ok(head_dim: int) -> bool:
    """Mosaic legality for scatter_kv_pages' strided (Hkv, D) DMAs."""
    return head_dim % 128 == 0


def _ragged_ring_setup(
    li, page_tables_ref, prefix_lens_ref, b, k_hbm, v_hbm, k_scr, v_scr,
    sems, pages_per_seq, *, ks_hbm=None, vs_hbm=None, ks_scr=None,
    vs_scr=None,
):
    """v3 (flat) DMA-ring prologue: page-id lookup, K/V copy factories,
    and the warm-up that puts depth-1 page transfers in flight. The copy
    factories return a LIST of copies: just the page for plain caches, the
    page plus its f32 scale row for int8 caches (sems columns 2/3). The
    grouped kernel streams at CHUNK granularity with clamped page ids and
    owns its own inlined version."""
    prefix = prefix_lens_ref[b]
    page_size = k_scr.shape[1]
    n_pages = pl.cdiv(prefix, page_size)

    def page_id(i):
        return page_tables_ref[b * pages_per_seq + i]

    def k_dma(slot, i):
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[li, page_id(i)], k_scr.at[slot], sems.at[slot, 0]
            )
        ]
        if ks_hbm is not None:
            copies.append(
                pltpu.make_async_copy(
                    ks_hbm.at[li, page_id(i)], ks_scr.at[slot],
                    sems.at[slot, 2],
                )
            )
        return copies

    def v_dma(slot, i):
        copies = [
            pltpu.make_async_copy(
                v_hbm.at[li, page_id(i)], v_scr.at[slot], sems.at[slot, 1]
            )
        ]
        if vs_hbm is not None:
            copies.append(
                pltpu.make_async_copy(
                    vs_hbm.at[li, page_id(i)], vs_scr.at[slot],
                    sems.at[slot, 3],
                )
            )
        return copies

    depth = k_scr.shape[0]
    for j in range(depth - 1):
        @pl.when(j < n_pages)
        def _(j=j):
            for c in k_dma(j, j) + v_dma(j, j):
                c.start()

    return prefix, n_pages, depth, k_dma, v_dma


def _inflight_epilogue(
    q, k_new_ref, v_new_ref, b, o_ref, acc_scr, m_prev, l_prev, group,
    sm_scale,
):
    """Shared v3/v4 epilogue: fold the current token's K/V (still in
    registers, not yet written to the cache) into the online softmax as one
    extra column, normalize, and write the output row. Per q row r the only
    valid kv head is r // group — selected via a (Hq, Hkv) mask so both
    contractions stay dense MXU matmuls (the waste is one column)."""
    Hq = q.shape[0]
    k_new = k_new_ref[b]  # (Hkv, D) cache dtype
    v_new = v_new_ref[b].astype(jnp.float32)
    Hkv = k_new.shape[0]
    s_all = jax.lax.dot_general(
        q, k_new, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale  # (Hq, Hkv)
    rh = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv), 0) // group
    ch = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv), 1)
    own = rh == ch
    s_new = jnp.sum(jnp.where(own, s_all, 0.0), axis=-1, keepdims=True)

    m_new = jnp.maximum(m_prev, s_new)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_new), 0.0)
    p_new = jnp.exp(s_new - m_new)  # (Hq, 1)
    l_final = l_prev * alpha + p_new
    p_mat = jnp.where(own, p_new, 0.0)  # (Hq, Hkv)
    pv_new = jax.lax.dot_general(
        p_mat, v_new, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Hq, D)
    acc = acc_scr[:] * alpha + pv_new
    l_safe = jnp.where(l_final > 0, l_final, 1.0)
    o_ref[b] = (acc / l_safe).astype(o_ref.dtype)


def _decode_kernel_ragged_grouped(
    # scalar prefetch
    layer_ref,  # (1,) int32, SMEM
    page_tables_ref,  # (B * pages_per_seq,) int32, SMEM
    prefix_lens_ref,  # (B,) int32, SMEM
    # inputs (same constant-index full-array blocks as v3)
    q_ref,  # (B, Hq, D) VMEM
    k_new_ref,  # (B, Hkv, D) VMEM
    v_new_ref,  # (B, Hkv, D) VMEM
    k_hbm,  # (L, n_pages, page_size, Hkv, D) ANY/HBM
    v_hbm,
    # quantized=True adds ks_hbm/vs_hbm scale inputs and ks_scr/vs_scr
    # scratch (see _decode_kernel_ragged); sems widen to (depth, 4)
    *rest,  # [ks_hbm, vs_hbm,] o_ref, k_scr, v_scr, [ks_scr, vs_scr,]
    # acc_scr, sems
    page_size: int,
    pages_per_seq: int,
    group: int,
    sm_scale: float,
    chunk: int,
    quantized: bool = False,
):
    """Ragged decode attention v4 ("grouped"): per-kv-head contractions
    over CHUNKS of pages.

    Differences from v3 (`_decode_kernel_ragged`), same online-softmax
    math:
    - logits come from Hkv unrolled (G, D) x (D, chunk*page_size) matmuls
      — one per kv head — instead of one (Hq, page_size*Hkv, D)
      block-diagonal matmul. Computes EXACTLY the real logits: v3 computes
      Hkv x more than exist at MHA, and the per-page cost evidence says
      the masked logits' `exp`s are what the ~2 us/page buys (NOTES r5
      "attention cost analysis").
    - no (ps, Hkv, D) -> (ps*Hkv, D) flatten, so the Hkv % 16 Mosaic
      relayout constraint disappears: GQA models (llama-3.1's Hkv=8) run
      the kernel instead of falling back to the XLA gather (the
      reference's serving targets are GQA-era, vllm_inference.py:54-58).
    - `chunk` pages per softmax update: the logits tile is
      (Hq, chunk*ps) — chunk=8 at ps=16 fills all 128 VPU lanes (a
      single-page (Hq, 16) tile wastes 7/8 of each vreg) and amortizes
      the per-iteration sem-wait/loop overhead by chunk x. The DMA ring
      is two half-buffers of `chunk` pages (scratch depth = 2*chunk):
      the next chunk streams while the current one computes.
    The trade: Hkv small matmuls per chunk at G-row MXU utilization.
    On-chip A/B vs flat: benchmarks/decode_micro.py --variant.

    ``quantized=True`` streams int8 pages + f32 scale rows and dequantizes
    per-head slices at the VMEM load (one bf16 multiply) — same online
    softmax, half the KV HBM traffic.
    """
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_scr, v_scr, ks_scr, vs_scr, acc_scr,
         sems) = rest
    else:
        o_ref, k_scr, v_scr, acc_scr, sems = rest
        ks_hbm = vs_hbm = ks_scr = vs_scr = None
    b = pl.program_id(0)
    li = layer_ref[0]
    prefix = prefix_lens_ref[b]
    C = chunk
    # chunk-granular streaming: a processed chunk loads ALL C of its page
    # slots — trailing lanes past the context clamp to a real table entry
    # (a duplicate page), so scratch never holds uninitialized data. The
    # duplicate's logits are masked to -inf, which matters in the p.V
    # matmul: 0 x finite = 0, whereas a garbage (NaN) page would poison
    # the contraction despite the mask.
    n_chunks = pl.cdiv(prefix, C * page_size)
    n_pages = pl.cdiv(prefix, page_size)

    def page_id(i):
        # clamp into the sequence's ALLOCATED pages (n_pages >= 1 whenever
        # any DMA is issued, since n_chunks > 0 implies prefix > 0): table
        # entries beyond the allocation may be caller padding
        return page_tables_ref[
            b * pages_per_seq + jax.lax.min(i, n_pages - 1)
        ]

    def k_dma(slot, i):
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[li, page_id(i)], k_scr.at[slot], sems.at[slot, 0]
            )
        ]
        if quantized:
            copies.append(
                pltpu.make_async_copy(
                    ks_hbm.at[li, page_id(i)], ks_scr.at[slot],
                    sems.at[slot, 2],
                )
            )
        return copies

    def v_dma(slot, i):
        copies = [
            pltpu.make_async_copy(
                v_hbm.at[li, page_id(i)], v_scr.at[slot], sems.at[slot, 1]
            )
        ]
        if quantized:
            copies.append(
                pltpu.make_async_copy(
                    vs_hbm.at[li, page_id(i)], vs_scr.at[slot],
                    sems.at[slot, 3],
                )
            )
        return copies

    # warm-up: chunk 0 into half 0 (every chunk's start has exactly one
    # matching wait in the body: warmup pairs with iteration 0)
    @pl.when(n_chunks > 0)
    def _():
        for j in range(C):
            for c in k_dma(j, j) + v_dma(j, j):
                c.start()

    acc_scr[:] = jnp.zeros_like(acc_scr)
    q = q_ref[b]  # (Hq, D) model dtype into the MXU, f32 accumulate
    Hq, D = q.shape
    Hkv = k_scr.shape[2]
    G = group
    ps = page_size
    W = C * ps  # chunk row = (page_in_chunk, token_in_page), row-major
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (Hq, W), 1)

    def body(i, carry):
        m_prev, l_prev = carry  # (Hq, 1) each
        base = jax.lax.rem(i, 2) * C
        nxt_base = jax.lax.rem(i + 1, 2) * C

        # stream the NEXT chunk into the other half while this one computes
        @pl.when(i + 1 < n_chunks)
        def _():
            for j in range(C):
                for c in (
                    k_dma(nxt_base + j, (i + 1) * C + j)
                    + v_dma(nxt_base + j, (i + 1) * C + j)
                ):
                    c.start()
        # wait this chunk's pages (all C were started: warmup or prefetch)
        for j in range(C):
            for c in k_dma(base + j, i * C + j) + v_dma(base + j, i * C + j):
                c.wait()

        def head_slice(scr, scale_scr, h):
            """The head's (chunk*ps, D) keys/values, dequantized for int8
            caches (int8 slice * its (C, ps) scale slice, one multiply at
            the query's compute dtype)."""
            x = scr[pl.ds(base, C), :, h, :]
            if quantized:
                x = x.astype(q.dtype) * (
                    scale_scr[pl.ds(base, C), :, h][..., None]
                ).astype(q.dtype)
            return x.reshape(W, D)

        # per-kv-head: query rows h*G:(h+1)*G against the head's
        # (chunk*ps, D) keys — static head slices, unrolled over Hkv
        s_parts = []
        for h in range(Hkv):
            k_h = head_slice(k_scr, ks_scr, h)
            s_parts.append(
                jax.lax.dot_general(
                    q[h * G : (h + 1) * G], k_h, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        s = jnp.concatenate(s_parts, axis=0) * sm_scale  # (Hq, W) f32
        s = jnp.where(i * W + col_tok < prefix, s, -jnp.inf)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(
            jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0
        )
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv_parts = []
        for h in range(Hkv):
            v_h = head_slice(v_scr, vs_scr, h)
            pv_parts.append(
                jax.lax.dot_general(
                    p[h * G : (h + 1) * G].astype(v_h.dtype), v_h,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        acc_scr[:] = acc_scr[:] * alpha + jnp.concatenate(pv_parts, axis=0)
        return m_new, l_new

    init = (
        jnp.full((Hq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((Hq, 1), jnp.float32),
    )
    m_prev, l_prev = jax.lax.fori_loop(0, n_chunks, body, init)
    _inflight_epilogue(
        q, k_new_ref, v_new_ref, b, o_ref, acc_scr, m_prev, l_prev, group,
        sm_scale,
    )


def paged_decode_attention_ragged(
    q: jax.Array,  # [B, Hq, D]
    k_pages: jax.Array,  # [L, n_pages, page_size, Hkv, D] — the FULL cache
    v_pages: jax.Array,
    layer: jax.Array,  # scalar int32 — which layer to attend against
    page_tables: jax.Array,  # [B, pages_per_seq] int32
    prefix_lens: jax.Array,  # [B] int32 — tokens already in the cache
    k_new: jax.Array,  # [B, Hkv, D] — current token's K (cache dtype)
    v_new: jax.Array,
    *,
    sm_scale: float | None = None,
    interpret: bool | None = None,
    variant: str | None = None,  # None: "flat" if Hkv%16==0 else "grouped"
) -> jax.Array:  # [B, Hq, D]
    """Pallas ragged decode attention over prefix pages + the in-flight
    token. Drop-in exact match for ``paged_decode_attention_inflight``
    given ``ks = k_pages[layer, page_tables]``.

    Two kernel formulations share the DMA/online-softmax structure:
    - ``"flat"`` (v3, `_decode_kernel_ragged`): one block-diagonal
      all-heads matmul per page; needs Hkv%16 for the page flatten.
    - ``"grouped"`` (v4, `_decode_kernel_ragged_grouped`): Hkv per-kv-head
      matmuls — only real logits, any Hkv (GQA's Hkv=8 included).
    Default picks flat where legal (the round-4 measured configuration)
    and grouped otherwise; pass ``variant=`` explicitly to A/B.

    ``k_pages``/``v_pages`` may be int8 :class:`~.kv_quant.QuantizedKV`
    caches: both variants then DMA the int8 page plus its f32 scale row and
    dequantize in VMEM — tolerance-accurate vs the f32 cache (the accuracy
    contract in docs/kv_cache.md), half the KV HBM traffic.
    """
    B, Hq, D = q.shape
    quantized = is_quantized(k_pages)
    L, n_pages, page_size, Hkv, _ = k_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kv_dtype = "int8" if quantized else "bfloat16"
    if variant is None:
        variant = ragged_variant_for(Hkv, kv_dtype)
    if variant not in ("flat", "grouped"):
        raise ValueError(f"unknown variant {variant!r}: flat | grouped")
    if not interpret and not ragged_shapes_ok(D, page_size):
        # fail with the constraint instead of an opaque Mosaic lowering
        # error: pages must be whole (16, 128) bf16 / (32, 128) int8 tiles
        raise ValueError(
            f"paged_decode_attention_ragged needs head_dim%128==0 and "
            f"page_size%16==0 on TPU; got D={D}, page_size={page_size}"
        )
    flat_mult = flat_variant_hkv_multiple(kv_dtype)
    if not interpret and variant == "flat" and Hkv % flat_mult:
        raise ValueError(
            f"variant='flat' needs n_kv_heads%{flat_mult}==0 on TPU for "
            f"{kv_dtype} pages (the (ps, Hkv, D) -> (ps*Hkv, D) flatten); "
            f"got Hkv={Hkv} — use variant='grouped' (the default for this "
            "shape)"
        )

    # int8 caches dequantize to (and fold the in-flight token at) the
    # query's compute dtype; plain caches keep their own dtype into the
    # MXU exactly as before (no retile, bit-identical default path)
    compute_dtype = q.dtype if quantized else k_pages.dtype
    # DMA ring depth: enough in-flight pages to hide issue latency (measured
    # ~2.3 us/page at depth 2), capped so K+V scratch stays ~<=4 MB of VMEM.
    # int8 pages are half the bytes, so the same budget holds twice the ring
    page_bytes = page_size * Hkv * D * k_pages.dtype.itemsize
    depth = max(2, min(pages_per_seq, (2 * 1024 * 1024) // max(page_bytes, 1)))
    chunk = 1
    if variant == "grouped":
        # chunked updates: up to 8 pages per softmax step (8*ps=128 lanes
        # at ps=16 — a full vreg row), double-buffered halves
        chunk = max(1, min(8, pages_per_seq, depth // 2))
        depth = 2 * chunk

    def _const3(shape):
        return pl.BlockSpec(
            shape, lambda b, *_refs: (0, 0, 0), memory_space=pltpu.VMEM
        )

    # full arrays, constant index maps: fetched into VMEM once per call,
    # not once per program (see _decode_kernel_ragged docstring)
    in_specs = [
        _const3((B, Hq, D)),
        _const3((B, Hkv, D)),
        _const3((B, Hkv, D)),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    scratch = [
        pltpu.VMEM((depth, page_size, Hkv, D), k_pages.dtype),
        pltpu.VMEM((depth, page_size, Hkv, D), v_pages.dtype),
    ]
    operands = [
        q,
        k_new.astype(compute_dtype),
        v_new.astype(compute_dtype),
    ]
    if quantized:
        # int8 data + f32 scale-row inputs; scale scratch rides the same
        # ring (sems columns 2/3)
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ]
        scratch += [
            pltpu.VMEM((depth, page_size, Hkv), jnp.float32),
            pltpu.VMEM((depth, page_size, Hkv), jnp.float32),
        ]
        operands += [
            k_pages.data, v_pages.data, k_pages.scale, v_pages.scale,
        ]
    else:
        operands += [k_pages, v_pages]
    scratch += [
        pltpu.VMEM((Hq, D), jnp.float32),
        pltpu.SemaphoreType.DMA((depth, 4 if quantized else 2)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (B, Hq, D), lambda b, *_refs: (0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=scratch,
    )
    kernel_kw = dict(
        page_size=page_size,
        pages_per_seq=pages_per_seq,
        group=G,
        sm_scale=sm_scale,
        quantized=quantized,
    )
    if variant == "flat":
        kernel = functools.partial(_decode_kernel_ragged, **kernel_kw)
    else:
        kernel = functools.partial(
            _decode_kernel_ragged_grouped, chunk=chunk, **kernel_kw
        )
    scale_bytes = 4 * page_size * Hkv if quantized else 0
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * pages_per_seq * page_size * D),
            bytes_accessed=int(
                2 * B * pages_per_seq
                * (Hkv * page_size * D * k_pages.dtype.itemsize + scale_bytes)
            ),
            transcendentals=int(B * Hq * pages_per_seq * page_size),
        ),
        interpret=interpret,
    )(
        jnp.reshape(layer, (1,)).astype(jnp.int32),
        page_tables.reshape(-1).astype(jnp.int32),
        prefix_lens.astype(jnp.int32),
        *operands,
    )
    return out


def _kv_scatter_kernel(
    # scalar prefetch
    page_idx_ref,  # (B,) int32
    slot_ref,  # (B,) int32
    # `*refs` (n_arrays is static): n_arrays sources (L, B, ...) ANY, then
    # n_arrays aliased page inputs, then n_arrays outputs, then DMA sems
    # (2, n_arrays). n_arrays=2 is the plain k/v cache; int8 caches run
    # n_arrays=4 with the f32 scale rows as arrays 2/3 ((L, B, Hkv) ->
    # (L, Hkv) at (page, slot) — the scale travels with its page).
    *refs,
    n_arrays: int,
):
    """One strided HBM->HBM DMA per (slot, array): copies the [L, Hkv, D]
    column of new KV (and, for int8 caches, its [L, Hkv] scale column) into
    (page_idx[b], slot[b]) of every layer's pages.

    XLA's scatter for the same update measured 4.8 ms/step at 7B/32 slots
    (benchmarks/decode_ablate.py) — it rewrites far more than the 33 MB it
    touches. Dead slots all target trash page 0 slot 0; those writes race
    harmlessly (the trash page's content is never attended).
    """
    srcs = refs[:n_arrays]
    outs = refs[2 * n_arrays : 3 * n_arrays]
    sems = refs[3 * n_arrays]
    b = pl.program_id(0)
    nb = pl.num_programs(0)

    def copies(bb):
        pid = page_idx_ref[bb]
        sl = slot_ref[bb]
        buf = jax.lax.rem(bb, 2)
        return [
            pltpu.make_async_copy(
                srcs[a].at[:, bb], outs[a].at[:, pid, sl], sems.at[buf, a]
            )
            for a in range(n_arrays)
        ]

    # two-deep pipeline: start this program's copies, wait the previous
    # program's (issued last grid step) so issue latency overlaps transfer
    for c in copies(b):
        c.start()

    @pl.when(b > 0)
    def _():
        for c in copies(b - 1):
            c.wait()

    @pl.when(b == nb - 1)
    def _():
        for c in copies(b):
            c.wait()


def scatter_kv_pages(
    k_pages: jax.Array,  # [L, P, ps, Hkv, D]
    v_pages: jax.Array,
    k_all: jax.Array,  # [L, B, Hkv, D] — new KV per layer per slot
    v_all: jax.Array,
    page_idx: jax.Array,  # [B] int32 — target page per slot
    slot: jax.Array,  # [B] int32 — position within the page
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Write every layer's new KV into the paged cache in place (one strided
    DMA per slot per array) — the Pallas replacement for the post-scan XLA
    scatter in llama.decode_step. Exact same semantics as
    ``pages.at[:, page_idx, slot].set(...)`` for distinct targets; dead
    slots (all pointed at trash page 0) may race, which is harmless.

    int8 caches quantize HERE (per token-head amax/127, fused by XLA into
    the producing program) and scatter four arrays — int8 K/V columns plus
    their f32 scale columns — through the same DMA pipeline."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = is_quantized(k_pages)
    L, B, Hkv, D = k_all.shape
    if not interpret and not scatter_shapes_ok(D):
        raise ValueError(
            f"scatter_kv_pages needs head_dim%128==0 on TPU for the "
            f"strided (Hkv, D) minor-dim DMAs; got D={D}. Use "
            f"llama.decode_step / paged_impl_plan for automatic fallback "
            "to the XLA scatter."
        )
    if quantized:
        qk, qv = quantize_kv(k_all), quantize_kv(v_all)
        srcs = [qk.data, qv.data, qk.scale, qv.scale]
        pages = [k_pages.data, v_pages.data, k_pages.scale, v_pages.scale]
    else:
        srcs = [k_all.astype(k_pages.dtype), v_all.astype(v_pages.dtype)]
        pages = [k_pages, v_pages]
    if interpret:
        # interpreter-mode DMAs of doubly-indexed HBM views are flaky; the
        # XLA scatter is exact and CPU tests only check semantics. Adjacent
        # advanced indices (dims 1, 2) keep their position: result [L, B,
        # Hkv, D] lines up with k_all directly.
        outs = [p.at[:, page_idx, slot].set(s) for p, s in zip(pages, srcs)]
    else:
        n = len(pages)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * (2 * n),
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * n,
            scratch_shapes=[pltpu.SemaphoreType.DMA((2, n))],
        )
        outs = pl.pallas_call(
            functools.partial(_kv_scatter_kernel, n_arrays=n),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct(p.shape, p.dtype) for p in pages
            ],
            # +2 for the two scalar-prefetch operands, +n for the sources:
            # alias the page arrays through so the update is in place
            input_output_aliases={2 + n + a: a for a in range(n)},
            compiler_params=_CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(
            page_idx.astype(jnp.int32),
            slot.astype(jnp.int32),
            *srcs,
            *pages,
        )
    if quantized:
        return (
            QuantizedKV(data=outs[0], scale=outs[2]),
            QuantizedKV(data=outs[1], scale=outs[3]),
        )
    return outs[0], outs[1]


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D]
    k_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    v_pages: jax.Array,  # [n_pages, page_size, Hkv, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32
    context_lens: jax.Array,  # [B] int32
    *,
    sm_scale: float | None = None,
    interpret: bool | None = None,
    impl: str | None = None,  # None/env: "xla" (default) or "pallas"
) -> jax.Array:  # [B, Hq, D]
    """One decode step of attention against the paged KV cache.

    Default impl is the fused-gather XLA formulation (see
    ``_paged_decode_xla`` for on-chip measurements); the Pallas kernel is
    kept selectable (``MTPU_PAGED_IMPL=pallas``) as the base for future
    tuning where its exact-ctx page reads matter (very long, very ragged
    contexts where the gather's pages_per_seq padding dominates).
    """
    import os

    B, Hq, D = q.shape
    n_pages, page_size, Hkv, _ = k_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if impl is None:
        impl = os.environ.get("MTPU_PAGED_IMPL", "xla")

    # Mosaic DMA units are (sublane, lane) tiles — a page must be a whole
    # number of (16, 128) bf16 tiles or the HBM→VMEM copies fail to lower
    # (observed on-chip with head_dim 32), and the kernel's (ps, Hkv, D) ->
    # (ps*Hkv, D) flatten needs Hkv % 16 (sub-16 head counts pad sublanes;
    # merging padded tiles relayouts). Sub-tile shapes (tiny/test models,
    # GQA) take the XLA path regardless of impl. int8 (QuantizedKV) caches
    # also take the XLA path here — _paged_decode_xla dequantizes in its
    # gather; only the v3/v4 ragged kernels have the int8 Mosaic bring-up
    # (this legacy write-then-attend kernel is the decode_micro A/B lever).
    if (
        impl != "pallas"
        or is_quantized(k_pages)
        or (not interpret and (D % 128 or page_size % 16 or Hkv % 16))
    ):
        return _paged_decode_xla(
            q, k_pages, v_pages, page_tables, context_lens, sm_scale
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec(
                (1, Hq, D), lambda b, *_refs: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, Hq, D), lambda b, *_refs: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, page_size, Hkv, D), v_pages.dtype),
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        pages_per_seq=pages_per_seq,
        group=G,
        sm_scale=sm_scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        compiler_params=_CompilerParams(
            # each sequence reads shared pages but writes a distinct output
            # block: the grid is safely parallel
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * pages_per_seq * page_size * Hkv * D),
            bytes_accessed=int(
                2 * B * pages_per_seq * Hkv * page_size * D
                * k_pages.dtype.itemsize
            ),
            transcendentals=int(B * Hq * pages_per_seq * page_size * Hkv),
        ),
        interpret=interpret,
    )(page_tables.reshape(-1).astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pages, v_pages)
    return out
