"""Ragged paged decode attention for TPU (Pallas → Mosaic).

The TPU-native replacement for vLLM's PagedAttention CUDA kernels — the core
of the reference's north-star serving path (vllm_inference.py; SURVEY.md §7
hard part #1: "Ragged paged attention kernel + continuous batching in JAX").

Memory layout (TPU-first):
- KV cache pages live in **HBM** as ``[Hkv, n_pages, page_size, D]`` — the
  last two dims form hardware tiles (page_size sublanes x 128 lanes), so a
  page is a contiguous DMA unit.
- Each sequence owns a list of physical page ids (its *page table*); pages
  are allocated/freed by the serving engine's block allocator.

Kernel design:
- grid = (batch, kv_heads): decode attention is HBM-bandwidth-bound (every
  live KV byte is read once per step); the job is to keep DMA saturated, not
  the MXU.
- page tables + context lengths arrive via **scalar prefetch** (SMEM), so the
  kernel computes its own DMA addresses — the "ragged" part: each sequence
  reads exactly ceil(ctx/page_size) pages, not max_pages.
- pages stream HBM→VMEM with **double buffering** (guide pattern), overlapped
  with the online-softmax update of the previous page.
- GQA: the q-head group for one kv head forms the row block, sharing the
  page traffic.

Runs in interpreter mode off-TPU (CPU CI), with a dense XLA reference in
ops.reference for ground truth.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    page_tables_ref,  # (B * pages_per_seq,) int32, SMEM
    ctx_lens_ref,  # (B,) int32, SMEM
    # inputs
    q_ref,  # (1, G, D) VMEM
    k_hbm,  # (Hkv, n_pages, page_size, D) ANY/HBM
    v_hbm,  # (Hkv, n_pages, page_size, D) ANY/HBM
    # outputs
    o_ref,  # (1, G, D) VMEM
    # scratch
    k_scr,  # (2, page_size, D) VMEM
    v_scr,  # (2, page_size, D) VMEM
    acc_scr,  # (G, D) f32
    sems,  # DMA sems (2, 2)
    *,
    page_size: int,
    pages_per_seq: int,
    sm_scale: float,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    ctx = ctx_lens_ref[b]
    n_pages = pl.cdiv(ctx, page_size)

    def page_id(i):
        return page_tables_ref[b * pages_per_seq + i]

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[h, page_id(i)], k_scr.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[h, page_id(i)], v_scr.at[slot], sems.at[slot, 1]
        )

    @pl.when(n_pages > 0)
    def _():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    acc_scr[:] = jnp.zeros_like(acc_scr)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (G, D)
    G = q.shape[0]

    def body(i, carry):
        m_prev, l_prev = carry  # (G, 1) each
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _prefetch():
            nxt = jax.lax.rem(i + 1, 2)
            k_dma(nxt, i + 1).start()
            v_dma(nxt, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()
        k = k_scr[slot].astype(jnp.float32)  # (page_size, D)
        v = v_scr[slot].astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (G, page_size)
        token_pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (G, page_size), 1
        )
        s = jnp.where(token_pos < ctx, s, -jnp.inf)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        return m_new, l_new

    init = (
        jnp.full((G, 1), -jnp.inf, jnp.float32),
        jnp.zeros((G, 1), jnp.float32),
    )
    _, l_final = jax.lax.fori_loop(0, n_pages, body, init)
    l_safe = jnp.where(l_final > 0, l_final, 1.0)
    o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D]
    k_pages: jax.Array,  # [Hkv, n_pages, page_size, D]
    v_pages: jax.Array,  # [Hkv, n_pages, page_size, D]
    page_tables: jax.Array,  # [B, pages_per_seq] int32
    context_lens: jax.Array,  # [B] int32
    *,
    sm_scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:  # [B, Hq, D]
    """One decode step of attention against the paged KV cache."""
    B, Hq, D = q.shape
    Hkv, n_pages, page_size, _ = k_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    G = Hq // Hkv
    pages_per_seq = page_tables.shape[1]
    if sm_scale is None:
        sm_scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Mosaic DMA units are (sublane, lane) tiles — a page must be a whole
    # number of (16, 128) bf16 tiles or the HBM→VMEM copies fail to lower
    # (observed on-chip with head_dim 32). Sub-tile shapes (tiny/test models)
    # take the dense XLA path instead; every production config (D=128,
    # page_size>=16) stays on the kernel.
    if not interpret and (D % 128 or page_size % 16):
        from .reference import paged_decode_attention as _ref

        return _ref(
            q, k_pages, v_pages, page_tables, context_lens, sm_scale=sm_scale
        )

    qg = q.reshape(B * Hkv, G, D)  # block (b, h) lives at row b * Hkv + h

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv),
        in_specs=[
            pl.BlockSpec(
                (1, G, D), lambda b, h, *_refs: (b * pl.num_programs(1) + h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, G, D), lambda b, h, *_refs: (b * pl.num_programs(1) + h, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, D), k_pages.dtype),
            pltpu.VMEM((2, page_size, D), v_pages.dtype),
            pltpu.VMEM((G, D), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        pages_per_seq=pages_per_seq,
        sm_scale=sm_scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * Hkv, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            # every (b, h) cell reads shared pages but writes a distinct
            # output block: both grid dims are safely parallel (lets Mosaic
            # split the grid across cores where the part has them)
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * pages_per_seq * page_size * D),
            bytes_accessed=int(
                2 * Hkv * B * pages_per_seq * page_size * D * k_pages.dtype.itemsize
            ),
            transcendentals=int(B * Hq * pages_per_seq * page_size),
        ),
        interpret=interpret,
    )(page_tables.reshape(-1).astype(jnp.int32), context_lens.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(B, Hq, D)
