"""Framework-wide configuration: state dir, backend selection, env knobs.

The reference platform keeps all durable state (volumes, deployed apps,
dicts/queues) in a closed-source control plane reached over gRPC. Our local
control plane is a state directory on disk (cheap, inspectable, works in CI);
the layout is designed so a networked metadata service can replace it later
without changing any caller. (Spec: reference examples treat these objects as
named, durable, cross-process — e.g. ``modal.Volume.from_name`` in
``06_gpu_and_ml/llm-serving/vllm_inference.py:77-81``.)
"""

from __future__ import annotations

import os
from pathlib import Path

#: Execution backend for ``.remote``-family calls.
#:   "process" — containers are supervised worker processes (default; the
#:               local analog of Modal's per-container runners).
#:   "inline"  — run in the caller's process with a serialization round-trip
#:               (used for single-chip benches so the TPU stays owned by the
#:               caller, and for debugging).
BACKEND_ENV = "MTPU_BACKEND"

#: Root of the local control plane (volumes, deployments, dicts, queues).
STATE_DIR_ENV = "MTPU_STATE_DIR"

#: Set inside containers so user code can detect remote execution
#: (reference analog: ``MODAL_TASK_ID``, simple_torch_cluster.py:111).
TASK_ID_ENV = "MTPU_TASK_ID"

#: Comma-separated ``key=value`` telling a container which TPU chips it owns.
TPU_VISIBLE_ENV = "TPU_VISIBLE_CHIPS"


def backend() -> str:
    return os.environ.get(BACKEND_ENV, "process")


def state_dir() -> Path:
    root = os.environ.get(STATE_DIR_ENV)
    if root:
        p = Path(root)
    else:
        p = Path.home() / ".mtpu"
    p.mkdir(parents=True, exist_ok=True)
    return p


def in_container() -> bool:
    return TASK_ID_ENV in os.environ


def task_id() -> str | None:
    return os.environ.get(TASK_ID_ENV)
