"""Open-loop load generator: production-shaped traffic for the serving fleet.

The vLLM/TGI serving-systems comparison (PAPERS.md, arxiv 2511.17593)
measures what matters with an OPEN-LOOP harness: arrivals follow a seeded
stochastic process at a fixed offered rate regardless of how the system
responds, so a saturated fleet shows up as a latency/goodput knee instead
of the silent self-throttling a closed loop hides. This module is that
harness for the OpenAI endpoint (serving/openai_api.py):

- **arrival processes** — ``poisson`` (exponential inter-arrivals) and
  ``heavy_tail`` (Pareto inter-arrivals, alpha 1.5: bursts and gaps at the
  same mean rate), both seeded and deterministic;
- **mixed request classes** — interactive / streaming / batch, each with
  its own prompt shape, token budget, engine priority class, and
  per-class latency SLO (the goodput denominator);
- **multi-tenant shared-prefix populations** — every tenant draws from a
  small pool of shared system-prompt prefixes, so the prefix cache and
  the affinity router are exercised the way production traffic exercises
  them, not defeated by unique prompts;
- **client-side measurement** — TTFT is stamped at first-SSE-chunk
  arrival on the wire (what a user sees), and TPOT is
  ``(last_chunk - first_chunk) / (completion_tokens - 1)`` using the
  ``stream_options.include_usage`` totals (per-chunk arrival gaps are
  meaningless when a starved client thread drains a burst of queued
  chunks at once); an HTTP 429 is a shed, a socket error an error, a
  stream that never terminates inside the drain window a wedge.

:meth:`LoadGenerator.sweep` runs a saturating rate ladder and finds the
knee (the first step whose goodput falls measurably below the offered
load); :func:`fleet_section` folds a pinned-fleet sweep, an autoscaled
sweep, and the autoscaler's scale events into the BENCH ``fleet`` section
``bench.py`` emits and ``tpurun benchdiff`` gates on (docs/fleet.md).

LAYERING: this module is a DRIVER, exactly like ``faults.chaos`` —
tests, ``bench.py``, and operators import it; production modules never do
(``tests/test_static.py`` enforces the ban). A serving-path import would
put traffic synthesis on the serving path.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
import urllib.parse

#: a request whose stream stays silent this long after the submit window
#: closes is WEDGED (the invariant the chaos harness hunts for)
DRAIN_TIMEOUT_S = 120.0

#: goodput shortfall that marks the knee: the first sweep step where
#: goodput < KNEE_GOODPUT_FRACTION * offered is past saturation
KNEE_GOODPUT_FRACTION = 0.8

_FILLER = "the quick brown fox jumps over the lazy dog "


@dataclasses.dataclass(frozen=True)
class RequestClass:
    """One traffic class: prompt shape, token budget, engine priority, and
    the latency SLO that decides whether a completion counts as goodput."""

    name: str
    priority: str  # engine priority class (scheduling/policy.py)
    weight: float  # sampling weight within the mix
    filler_sentences: tuple[int, int]  # prompt length range beyond the prefix
    max_tokens: int
    ttft_slo_s: float
    tpot_slo_s: float
    stream: bool = True  # SSE streaming vs one-shot JSON

    def met_slo(self, r: dict) -> bool:
        """Did this completed request land inside its latency SLO?"""
        if self.stream:
            if r["ttft_s"] is None or r["ttft_s"] > self.ttft_slo_s:
                return False
            return r["tpot_s"] is None or r["tpot_s"] <= self.tpot_slo_s
        # non-streamed: the whole response inside TTFT + tokens x TPOT
        budget = self.ttft_slo_s + self.max_tokens * self.tpot_slo_s
        return r["e2e_s"] <= budget


#: the default production-shaped mix (docs/fleet.md): mostly interactive
#: chat turns, a long-form streaming tail, and heavyweight batch jobs
DEFAULT_CLASSES: tuple[RequestClass, ...] = (
    RequestClass("interactive", "interactive", 0.6, (1, 3), 16, 2.0, 0.5),
    RequestClass("streaming", "default", 0.25, (2, 5), 48, 4.0, 0.5),
    RequestClass("batch", "batch", 0.15, (6, 12), 32, 30.0, 2.0, stream=False),
)

ARRIVAL_PROCESSES = ("poisson", "heavy_tail")


def _percentile(values: list[float], p: float) -> float:
    """The repo-wide nearest-rank percentile (utils/stats.py) — the same
    rank convention as bench.py's latency sections and the profiler's
    ``overhead`` section, so benchdiff never compares drifted quantiles."""
    from ..utils.stats import percentile_nearest_rank

    return percentile_nearest_rank(values, p)


class LoadGenerator:
    """Open-loop traffic against one OpenAI endpoint base URL."""

    def __init__(
        self,
        base_url: str,
        *,
        classes: tuple[RequestClass, ...] = DEFAULT_CLASSES,
        arrival: str = "poisson",
        tenants: int = 4,
        shared_prefixes: int = 2,
        seed: int = 0,
        request_timeout_s: float = DRAIN_TIMEOUT_S,
    ):
        if arrival not in ARRIVAL_PROCESSES:
            raise ValueError(
                f"unknown arrival process {arrival!r}; one of {ARRIVAL_PROCESSES}"
            )
        parsed = urllib.parse.urlparse(base_url)
        if parsed.scheme != "http" or not parsed.hostname:
            raise ValueError(f"base_url must be http://host:port, got {base_url!r}")
        self.host = parsed.hostname
        self.port = parsed.port or 80
        self.classes = tuple(classes)
        self.arrival = arrival
        self.seed = seed
        self.request_timeout_s = float(request_timeout_s)
        self._seq = 0
        self._seq_lock = threading.Lock()
        # tenant -> pool of shared system-prompt prefixes: repeats within a
        # (tenant, pool slot) share their first prefix-cache block, which is
        # exactly what the affinity router keys on
        self.prefixes = {
            f"tenant-{t}": [
                f"[tenant-{t} system prompt {k}] " + _FILLER
                for k in range(max(1, shared_prefixes))
            ]
            for t in range(max(1, tenants))
        }

    # -- arrivals ------------------------------------------------------------

    def _interarrival(self, rng: random.Random, rate_rps: float) -> float:
        if self.arrival == "poisson":
            return rng.expovariate(rate_rps)
        # heavy_tail: Pareto(alpha) with the same MEAN inter-arrival
        # 1/rate — alpha 1.5 gives infinite variance, i.e. real bursts
        alpha = 1.5
        mean = 1.0 / rate_rps
        scale = mean * (alpha - 1) / alpha
        return scale * rng.paretovariate(alpha)

    def _pick(self, rng: random.Random):
        cls = rng.choices(
            self.classes, weights=[c.weight for c in self.classes]
        )[0]
        tenant = rng.choice(sorted(self.prefixes))
        prefix = rng.choice(self.prefixes[tenant])
        with self._seq_lock:  # calibrate picks from worker threads
            self._seq += 1
            seq = self._seq
        n = rng.randint(*cls.filler_sentences)
        prompt = f"{prefix}request {seq}: " + _FILLER * n
        return cls, tenant, prompt

    # -- one request on the wire ---------------------------------------------

    def _do_request(self, cls: RequestClass, tenant: str, prompt: str) -> dict:
        out = {
            "class": cls.name,
            "tenant": tenant,
            "status": "error",
            "ttft_s": None,
            "tpot_s": None,
            "completion_tokens": None,
            # prompt tokens the server answered from its prefix cache
            # (usage.prompt_tokens_details.cached_tokens) — the client-side
            # check that shared-prefix traffic actually hits the trie
            "cached_tokens": None,
            "e2e_s": 0.0,
            "finish_reason": None,
            "pieces": 0,
        }
        body = json.dumps({
            "prompt": prompt,
            "max_tokens": cls.max_tokens,
            "stream": cls.stream,
            "priority": cls.priority,
            "user": tenant,
            "temperature": 1.0,
            # usage totals ride the stream's final chunk: TPOT is computed
            # as (e2e - ttft) / (completion_tokens - 1) — chunk-arrival
            # gaps are meaningless when a starved client thread drains a
            # burst of queued SSE chunks at once
            "stream_options": {"include_usage": True},
        })
        t0 = time.monotonic()
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.request_timeout_s
        )
        try:
            conn.request(
                "POST", "/v1/completions", body=body,
                headers={"content-type": "application/json"},
            )
            resp = conn.getresponse()
            if resp.status == 429:
                out["status"] = "shed"
                return out
            if resp.status != 200:
                return out
            if not cls.stream:
                payload = json.loads(resp.read())
                out["e2e_s"] = time.monotonic() - t0
                out["finish_reason"] = payload["choices"][0].get("finish_reason")
                usage = payload.get("usage") or {}
                out["completion_tokens"] = usage.get("completion_tokens")
                out["cached_tokens"] = (
                    usage.get("prompt_tokens_details") or {}
                ).get("cached_tokens")
                out["status"] = "ok" if out["finish_reason"] != "error" else "error"
                return out
            t_last = None
            for raw in resp:
                line = raw.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    break
                event = json.loads(data)
                if "error" in event:
                    out["e2e_s"] = time.monotonic() - t0
                    return out
                choices = event.get("choices") or []
                if not choices:
                    usage = event.get("usage") or {}
                    if usage.get("completion_tokens") is not None:
                        out["completion_tokens"] = usage["completion_tokens"]
                    details = usage.get("prompt_tokens_details") or {}
                    if details.get("cached_tokens") is not None:
                        out["cached_tokens"] = details["cached_tokens"]
                    continue
                now = time.monotonic()
                finish = choices[0].get("finish_reason")
                if finish is not None:
                    out["finish_reason"] = finish
                    continue
                if out["ttft_s"] is None:
                    out["ttft_s"] = now - t0
                t_last = now
                out["pieces"] += 1
            out["e2e_s"] = time.monotonic() - t0
            n = out["completion_tokens"]
            if out["ttft_s"] is not None and n and n > 1 and t_last is not None:
                out["tpot_s"] = max(0.0, (t_last - t0 - out["ttft_s"]) / (n - 1))
            if out["finish_reason"] is not None:
                out["status"] = "ok"
            else:
                # no terminal chunk: a stream that went SILENT past the
                # drain window is the wedge invariant; one whose socket
                # closed early is an ordinary server error
                out["status"] = (
                    "wedged"
                    if out["e2e_s"] >= self.request_timeout_s
                    else "error"
                )
            return out
        except (OSError, http.client.HTTPException, json.JSONDecodeError,
                KeyError, IndexError):
            out["e2e_s"] = time.monotonic() - t0
            # a timeout on a stream that never finished is the wedge signal;
            # anything else is a transport error
            out["status"] = (
                "wedged" if out["e2e_s"] >= self.request_timeout_s else "error"
            )
            return out
        finally:
            conn.close()

    # -- one offered-load step -----------------------------------------------

    def run_step(
        self, rate_rps: float, duration_s: float, *, label: str = ""
    ) -> dict:
        """Offer ``rate_rps`` for ``duration_s`` (open loop: arrivals never
        wait for completions), drain every in-flight stream, and return the
        step report: goodput, shed rate, client-observed TTFT/TPOT
        p50/p99, and per-class breakdowns."""
        # str seeds hash through sha512 inside Random — deterministic
        # across processes, unlike tuple hashes under PYTHONHASHSEED
        rng = random.Random(f"{self.seed}|{self.arrival}|{rate_rps:.6f}")
        results: list[dict] = []
        lock = threading.Lock()
        threads: list[threading.Thread] = []
        by_name = {c.name: c for c in self.classes}

        def worker(cls, tenant, prompt):
            r = self._do_request(cls, tenant, prompt)
            with lock:
                results.append(r)

        start = time.monotonic()
        next_at = start
        offered = 0
        offered_by_class = {c.name: 0 for c in self.classes}
        while True:
            next_at += self._interarrival(rng, rate_rps)
            if next_at - start > duration_s:
                break
            delay = next_at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            cls, tenant, prompt = self._pick(rng)
            t = threading.Thread(
                target=worker, args=(cls, tenant, prompt), daemon=True
            )
            t.start()
            threads.append(t)
            offered += 1
            offered_by_class[cls.name] += 1
        deadline = time.monotonic() + self.request_timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with lock:
            done = list(results)
        # a worker thread still running past the drain window IS a wedge
        wedged = offered - len(done) + sum(
            1 for r in done if r["status"] == "wedged"
        )
        ok = [r for r in done if r["status"] == "ok"]
        shed = sum(1 for r in done if r["status"] == "shed")
        errors = sum(1 for r in done if r["status"] == "error")
        good = [r for r in ok if by_name[r["class"]].met_slo(r)]
        ttfts = [r["ttft_s"] for r in ok if r["ttft_s"] is not None]
        tpots = [r["tpot_s"] for r in ok if r["tpot_s"] is not None]
        per_class: dict[str, dict] = {}
        for cls in self.classes:
            mine = [r for r in ok if r["class"] == cls.name]
            c_ttfts = [r["ttft_s"] for r in mine if r["ttft_s"] is not None]
            per_class[cls.name] = {
                # counted at SUBMIT: a worker that never returns (wedge)
                # must still appear in its class's offered count
                "offered": offered_by_class[cls.name],
                "completed": len(mine),
                "good": sum(1 for r in mine if cls.met_slo(r)),
                "ttft_p99": round(_percentile(c_ttfts, 0.99), 6),
            }
        return {
            "label": label,
            "offered_rps": round(rate_rps, 4),
            "duration_s": round(duration_s, 3),
            "offered": offered,
            "completed": len(ok),
            "shed": shed,
            "errors": errors,
            "wedged": wedged,
            "achieved_rps": round(len(ok) / duration_s, 4),
            "goodput_rps": round(len(good) / duration_s, 4),
            "shed_rate": round(shed / offered, 6) if offered else 0.0,
            "ttft": {
                "p50": round(_percentile(ttfts, 0.50), 6),
                "p99": round(_percentile(ttfts, 0.99), 6),
            },
            "tpot": {
                "p50": round(_percentile(tpots, 0.50), 6),
                "p99": round(_percentile(tpots, 0.99), 6),
            },
            # prefix-cache effectiveness as the CLIENT sees it, summed over
            # completed requests that reported usage details
            "cached_tokens": sum(
                r["cached_tokens"] for r in ok
                if r.get("cached_tokens") is not None
            ),
            "per_class": per_class,
        }

    def warm(self, n_per_class: int = 1) -> None:
        """Send ``n_per_class`` requests of EVERY class synchronously
        before measuring: first-touch jit compiles (per-bucket prefill,
        chunk offsets, the decode block) and prefix-cache cold misses
        belong to warmup, not to the capacity estimate or the first sweep
        step."""
        rng = random.Random(f"{self.seed}|warm")
        for cls in self.classes:
            for _ in range(n_per_class):
                _c, tenant, prompt = self._pick(rng)
                self._do_request(cls, tenant, prompt)

    def calibrate(
        self, duration_s: float = 2.0, *, concurrency: int = 8
    ) -> float:
        """CLOSED-loop capacity probe: ``concurrency`` workers each run
        back-to-back requests of the configured mix for ``duration_s``, so
        the fleet serves flat out with no open-loop backlog; completions
        per second IS single-fleet capacity. Used to place the sweep
        ladder relative to the hardware instead of hardcoding rates (an
        open-loop probe would count completions that drained after the
        submit window and overestimate wildly)."""
        counts = [0] * concurrency
        stop_at = time.monotonic() + duration_s

        def worker(i: int) -> None:
            rng = random.Random(f"{self.seed}|calibrate|{i}")
            while time.monotonic() < stop_at:
                cls, tenant, prompt = self._pick(rng)
                if self._do_request(cls, tenant, prompt)["status"] == "ok":
                    counts[i] += 1

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + self.request_timeout_s)
        return max(0.5, sum(counts) / duration_s)

    def sweep(
        self, rates: list[float], duration_s: float, *, settle_s: float = 0.25
    ) -> dict:
        """The saturating rate ladder: one step per offered rate, knee
        detection over the ladder. ``knee_index`` is the first step whose
        goodput falls below ``KNEE_GOODPUT_FRACTION`` x offered (the
        latency-vs-offered-load knee of arxiv 2511.17593); the step before
        it is the pre-knee operating point."""
        steps = []
        for rate in rates:
            steps.append(self.run_step(rate, duration_s, label=f"{rate:g}rps"))
            time.sleep(settle_s)
        # saturation is judged against the ACTUAL arrivals the process
        # produced (offered/duration), not the nominal rate — at small
        # samples a Poisson shortfall would otherwise mislabel an
        # underloaded step as the knee
        knee = next(
            (
                i for i, s in enumerate(steps)
                if s["offered"] > 0
                and s["goodput_rps"]
                < KNEE_GOODPUT_FRACTION * (s["offered"] / s["duration_s"])
            ),
            len(steps) - 1,
        )
        return {
            "arrival": self.arrival,
            "rates": [round(r, 4) for r in rates],
            "steps": steps,
            "knee_index": knee,
            "knee_rps": steps[knee]["offered_rps"] if steps else 0.0,
        }


def ab_index(sweep: dict) -> int:
    """The ladder index the fleet A/B lands on: the knee-adjacent step —
    the knee's lower neighbour when the knee is the ladder's top, else the
    knee itself (a knee at the bottom step means the ladder was misplaced;
    the A/B then lands there honestly)."""
    return max(0, min(sweep["knee_index"], max(0, len(sweep["steps"]) - 2)))


def fleet_section(
    pinned: dict,
    autoscaled: dict,
    *,
    scale_events: list[dict],
    capacity_rps: float,
    scaled_step: dict | None = None,
) -> dict:
    """Fold the two sweep arms + the autoscaler's journal slice into the
    BENCH ``fleet`` section (docs/fleet.md).

    The headline A/B (``ab``) lands at the knee-adjacent offered load —
    the rate a single pinned replica is just failing to serve inside SLO.
    ``scaled_step`` is that rate re-measured AFTER the ascending
    autoscaled sweep, while the fleet is still scaled out: the ascending
    ladder only triggers scale-out at its saturating step, so comparing
    ladder position i against ladder position i would compare two
    identical one-replica fleets. Closing the loop must show up as higher
    goodput and a lower shed rate / p99 TTFT; ``fleet.goodput`` and
    ``fleet.p99_tpot_at_knee`` are the benchdiff-gated headline numbers
    (utils/bench_diff.py). Without ``scaled_step`` the A/B falls back to
    the autoscaled ladder's knee-adjacent step."""
    idx = ab_index(pinned)
    p_step = pinned["steps"][idx]
    a_step = scaled_step or autoscaled["steps"][
        min(idx, len(autoscaled["steps"]) - 1)
    ]
    ups = [e for e in scale_events if e.get("action") == "scale_up"]
    downs = [e for e in scale_events if e.get("action") == "scale_down"]

    def arm(step: dict) -> dict:
        return {
            "goodput_rps": step["goodput_rps"],
            "achieved_rps": step["achieved_rps"],
            "shed_rate": step["shed_rate"],
            "ttft_p99": step["ttft"]["p99"],
            "tpot_p99": step["tpot"]["p99"],
            "wedged": step["wedged"],
        }

    return {
        "arrival": pinned["arrival"],
        "capacity_rps": round(capacity_rps, 4),
        "rates": pinned["rates"],
        "knee_rps": pinned["knee_rps"],
        "goodput": a_step["goodput_rps"],
        "p99_tpot_at_knee": a_step["tpot"]["p99"],
        "shed_rate": a_step["shed_rate"],
        "ab": {
            "offered_rps": p_step["offered_rps"],
            "scaled_out": scaled_step is not None,
            "pinned": arm(p_step),
            "autoscaled": arm(a_step),
            "improvement_goodput": round(
                a_step["goodput_rps"] / max(p_step["goodput_rps"], 1e-9), 3
            ),
            "improvement_p99_ttft": round(
                p_step["ttft"]["p99"] / max(a_step["ttft"]["p99"], 1e-9), 3
            ),
            "improvement_p99_tpot": round(
                p_step["tpot"]["p99"] / max(a_step["tpot"]["p99"], 1e-9), 3
            ),
        },
        "sweep": {
            "pinned": pinned["steps"],
            "autoscaled": autoscaled["steps"],
        },
        "scale_events": {
            "up": len(ups),
            "down": len(downs),
            "warm_boots": sum(1 for e in ups if e.get("boot") == "warm"),
        },
    }
