"""Fleet layer: closed-loop replica autoscaling + the production load harness.

Every organ for the "millions of users" story already exists — SLO burn
rates (observability/slo.py), snapshot warm boots (snapshot/), the
role-aware router with health re-admission (scheduling/router.py),
KV-pressure shedding (scheduling/admission.py) — but until this layer
nothing closed the loop at the *replica fleet* level: the executor
autoscaler scales containers, not serving replicas. Two cooperating
components (docs/fleet.md):

- :mod:`.autoscaler` — :class:`FleetAutoscaler`, a closed-loop controller
  that grows/shrinks prefill and decode replicas behind a
  ``PrefixAffinityRouter`` from SLO burn rate, per-class queue depth, and
  KV-page pressure, with hysteresis + cooldown and snapshot-restored warm
  boots (:class:`SnapshotWarmFactory`). Every decision is journaled to
  ``<state_dir>/fleet.jsonl`` and counted in the fleet catalog series
  (``FLEET_REPLICAS`` / ``FLEET_DECISIONS_TOTAL`` / ``FLEET_BOOT_SECONDS``;
  ``tpurun fleet``, gateway ``/fleet``).
- :mod:`.loadgen` — an open-loop load generator (Poisson / heavy-tail
  arrivals, mixed request classes with per-class SLOs, multi-tenant
  shared-prefix populations) driving the OpenAI endpoint and emitting the
  BENCH ``fleet`` section. It is a DRIVER like ``faults.chaos``:
  production code never imports it (``tests/test_static.py`` enforces
  the ban) — import it explicitly from tests, ``bench.py``, or operator
  tooling.
"""

from __future__ import annotations

from .autoscaler import FleetAutoscaler, SnapshotWarmFactory

__all__ = ["FleetAutoscaler", "SnapshotWarmFactory"]
