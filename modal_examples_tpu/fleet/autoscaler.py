"""Closed-loop fleet autoscaler: grow/shrink serving replicas from load.

The executor autoscaler (core/executor.py ``_autoscale``) scales *containers*
for remote functions; this controller scales *serving replicas* behind a
:class:`~..scheduling.router.PrefixAffinityRouter`. Once per tick it reads
three pressure signals —

- **SLO burn rate** (observability/slo.py): the declared TTFT/TPOT p95
  targets evaluated against the live registry; burn > 1 means the latency
  budget is being violated right now;
- **queue depth**: requests waiting for admission per decode-capable
  replica (``SchedulerPolicy.total_depth``), plus the admission layer's
  shed counter delta (a shed IS queue pressure the bounded queues already
  converted into a 429);
- **KV-page pressure**: the paged-cache occupancy fraction — the same
  signal admission control sheds on (docs/kv_cache.md);

— and decides per ROLE GROUP: prefill-role replicas scale on their own
outstanding prefill backlog, decode-capable replicas on the signals above,
so a disaggregated fleet scales its two sides independently
(docs/disagg.md). Decisions are damped two ways so the controller cannot
flap against the router's health re-admission cycle: a signal must persist
for ``up_ticks``/``down_ticks`` consecutive ticks (hysteresis), and any
action opens a ``cooldown_s`` window during which no further action is
taken.

Scale-out builds a replica through the ``factory`` callable — typically a
:class:`SnapshotWarmFactory`, which restores model params from the PR-1
memory-snapshot store instead of re-initializing, so a new replica boots
in roughly the time of one device transfer ("warm") rather than a full
init ("cold"). Scale-in is drain-safe: the victim is removed from
placement first (``router.remove_replica`` — new requests stop arriving;
requests it already owns keep streaming), parked on a draining list, and
its engine is stopped only once ``outstanding() == 0``.

Every decision appends a structured record to ``<state_dir>/fleet.jsonl``
(the PR-3 ``observability/journal.py`` pattern) and increments
``mtpu_fleet_decisions_total{action,trigger}``; the fleet's size by role
rides ``mtpu_fleet_replicas{role}`` and boot latency by kind in
``mtpu_fleet_boot_seconds{boot}`` — surfaced by ``tpurun fleet`` and the
gateway's ``/fleet`` route (docs/fleet.md).
"""

from __future__ import annotations

import threading
import time

from ..observability import catalog as C
from ..observability import metrics as _obs
from ..observability import slo as _slo
from ..observability.journal import named_journal
from ..utils.log import get_logger
from ..utils.prometheus import default_registry

logger = get_logger("fleet")

#: the SLO names whose burn rate feeds the scale-up signal (latency only:
#: error-budget SLOs say something is broken, not that the fleet is small)
_LATENCY_SLO_NAMES = ("ttft_p95", "tpot_p95")


def _role_group(replica) -> str:
    """prefill-role replicas scale as their own group; decode and unified
    replicas both own requests end to end and scale together."""
    return "prefill" if getattr(replica, "role", "unified") == "prefill" else "decode"


def shared_prefix_store(replica):
    """The replica engine's SHARED prefix store handle, or None. A private
    (``shared=False``) tier has no fleet membership to manage, so the
    autoscaler leaves it alone (docs/prefix_store.md)."""
    tiered = getattr(getattr(replica, "engine", None), "tiered", None)
    store = getattr(tiered, "store", None)
    if store is not None and getattr(store, "shared", False):
        return store
    return None


def _deregister_prefix_store(replica) -> None:
    """Drop a stopped replica out of the store's membership so its leases
    expire as dead (survivors take chains over) and its pins stop holding
    blocks against GC. Best-effort: a failed deregister just means the
    membership TTL does the same thing later."""
    store = shared_prefix_store(replica)
    if store is None:
        return
    try:
        store.deregister_replica()
    except Exception:
        logger.warning(
            "fleet: prefix-store deregister failed for %s",
            getattr(replica, "name", "?"),
        )


class SnapshotWarmFactory:
    """Replica factory with snapshot-restored warm boots.

    Wraps a ``build(name, role, params=None)`` callable (which constructs
    and returns a routable replica, typically an ``EngineReplica`` over a
    fresh ``LLMEngine``). The first build is cold; its engine's params are
    then captured into the PR-1 :class:`~..snapshot.SnapshotStore` (jax
    leaves devicelessly, via the snapshot codec), and every later build
    passes the restored tree back as ``params=`` — the expensive
    init/quantize step is skipped, which is what makes autoscaler
    scale-out near-instant. :meth:`prime` captures from an
    already-running engine so the very first scale-out is already warm.

    Calling the factory returns ``(replica, boot)`` with ``boot`` in
    ``{"warm", "cold"}``; a store/codec failure degrades to a cold build,
    never a scale-out outage.
    """

    def __init__(self, build, *, snapshot_key: str, store=None):
        from ..snapshot import SnapshotStore

        self._build = build
        self.snapshot_key = snapshot_key
        self.store = store if store is not None else SnapshotStore()
        self._lock = threading.Lock()

    def prime(self, engine) -> bool:
        """Capture ``engine.params`` into the store (idempotent); returns
        whether a snapshot is now available for warm boots."""
        with self._lock:
            if self.store.has(self.snapshot_key):
                return True
            return self._capture(engine.params)

    def _capture(self, params) -> bool:
        from ..snapshot.codec import CodecError, encode_attr
        from ..utils.metrics import record_snapshot_boot

        try:
            payload = encode_attr(params)
        except CodecError as e:
            logger.warning("fleet snapshot capture failed: %s", e)
            return False
        ok = self.store.put(
            self.snapshot_key, payload, manifest={"kind": "fleet-params"}
        )
        if ok:
            # the capturing replica itself booted cold: one miss + capture
            record_snapshot_boot("fleet", "miss", captured=True)
        return ok

    def _restore(self):
        from ..snapshot.codec import decode_attr

        got = self.store.get(self.snapshot_key)
        if got is None:
            return None
        payload, _meta = got
        try:
            return decode_attr(payload)
        except Exception as e:  # poison entry: drop it, boot cold
            logger.warning("fleet snapshot restore failed: %s", e)
            self.store.delete(self.snapshot_key)
            return None

    def __call__(self, name: str, role: str):
        from ..utils.metrics import record_snapshot_boot

        with self._lock:
            params = self._restore()
        boot = "warm" if params is not None else "cold"
        if params is not None:
            record_snapshot_boot("fleet", "hit")
        replica = self._build(name, role, params=params)
        if params is None:
            with self._lock:
                if not self.store.has(self.snapshot_key):
                    self._capture(replica.engine.params)
        # a scale-out joins the SHARED prefix store at boot: membership
        # makes it a rendezvous owner candidate immediately, and the tier
        # it promotes from is the fleet's — so a warm-weights boot also
        # serves its first traffic with a warm prefix hit rate instead of
        # recomputing prefixes the fleet already paid for
        pstore = shared_prefix_store(replica)
        if pstore is not None:
            try:
                pstore.register_replica(boot=boot)
            except Exception:
                logger.warning("fleet: prefix-store register failed for %s", name)
        return replica, boot


class FleetAutoscaler:
    """Closed-loop controller over a router's replica fleet."""

    def __init__(
        self,
        router,
        factory,
        *,
        min_replicas: dict | None = None,  # per role group; decode >= 1
        max_replicas: dict | None = None,
        queue_high: float = 4.0,  # queued requests per replica -> scale up
        kv_high: float = 0.85,  # max cache occupancy fraction -> scale up
        burn_high: float = 1.0,  # latency-SLO burn rate -> scale up
        shed_high: int = 1,  # sheds observed since last tick -> scale up
        idle_low: float = 0.25,  # fleet outstanding/capacity below -> down
        up_ticks: int = 2,  # consecutive pressured ticks before scale-up
        down_ticks: int = 6,  # consecutive idle ticks before scale-down
        cooldown_s: float = 5.0,  # no further action after any action
        tick_s: float = 0.5,
        drain_timeout_s: float = 60.0,
        # drain-by-migration (serving/failover.py, docs/failover.md): a
        # scale-in victim's live requests are checkpoint-migrated onto the
        # remaining fleet instead of waited out — drain time is bounded by
        # one migration per request, and the old forced reap (which killed
        # live streams at drain_timeout) becomes migrate-then-reap. False
        # restores the PR-11 idle-wait behavior.
        migrate_on_drain: bool = True,
        journal_path=None,
        registry=None,
        slos=None,  # SLO tuple for the burn signal; () disables it
        clock=None,  # injectable monotonic clock (deterministic tests)
    ):
        self.router = router
        self.factory = factory
        self.min_replicas = {"decode": 1, "prefill": 0, **(min_replicas or {})}
        self.max_replicas = {"decode": 4, "prefill": 2, **(max_replicas or {})}
        self.queue_high = float(queue_high)
        self.kv_high = float(kv_high)
        self.burn_high = float(burn_high)
        self.shed_high = int(shed_high)
        self.idle_low = float(idle_low)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_s = float(cooldown_s)
        self.tick_s = float(tick_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.migrate_on_drain = bool(migrate_on_drain)
        #: per-victim decode tokens carried off by drain migrations (what
        #: fleet.jsonl records instead of requests killed)
        self._drained_tokens: dict[str, int] = {}
        #: per-victim (last_attempt_at, consecutive_failures): a victim
        #: whose requests cannot move yet (targets shedding) is retried
        #: with a growing backoff instead of every tick — without this a
        #: stuck 60 s drain window would spam ~120 journal records,
        #: fallback metrics, and failover spans per request
        self._drain_attempts: dict[str, tuple[float, int]] = {}
        #: last shared-prefix-store heartbeat round (the controller is the
        #: fleet's one periodic loop, so it keeps every replica's store
        #: membership alive; throttled — TTL is tens of seconds)
        self._last_store_heartbeat = 0.0
        self.journal = named_journal("fleet", path=journal_path)
        self._registry = registry if registry is not None else default_registry
        self._slos = (
            slos
            if slos is not None
            else tuple(s for s in _slo.DEFAULT_SLOS if s.name in _LATENCY_SLO_NAMES)
        )
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._seq = 0
        self._up_streak = {"decode": 0, "prefill": 0}
        self._down_streak = {"decode": 0, "prefill": 0}
        self._cooldown_until = {"decode": 0.0, "prefill": 0.0}
        #: quarantined replicas a "quarantine" scale-up already replaced —
        #: the trigger is per-BENCHING (edge), not per-tick (level): one
        #: replacement per quarantined replica, re-armed when its
        #: quarantine lifts (pruned against the live quarantined set)
        self._quarantine_handled: dict[str, set[str]] = {
            "decode": set(), "prefill": set(),
        }
        #: names this controller created (only these are scale-in victims:
        #: the operator's seed replicas are never reaped)
        self._owned: dict[str, list[str]] = {"decode": [], "prefill": []}
        #: (replica, removed_at) — out of placement, waiting to drain
        self._draining: list[tuple[object, float]] = []
        self._last_sheds = self._registry.total(C.SHEDS_TOTAL)
        self.events: list[dict] = []  # every action taken, newest last
        self._running = False
        self._stopping = False  # stop() requested: discard in-flight builds
        self._thread: threading.Thread | None = None
        self._publish_sizes()

    # -- signals -------------------------------------------------------------

    def _replicas(self, group: str) -> list:
        return [r for r in self.router.replicas if _role_group(r) == group]

    def _burn_rate(self) -> float:
        if not self._slos:
            return 0.0
        reports = _slo.evaluate(
            self._registry, tuple(self._slos),
            burn_rate_registry=self._registry,
        )
        burns = [
            r["burn_rate"] for r in reports
            if r["kind"] == "latency" and r["burn_rate"] is not None
        ]
        return max(burns, default=0.0)

    def signals(self, *, consume_sheds: bool = True) -> dict:
        """One tick's pressure snapshot, per role group (also the
        ``/fleet`` payload's ``signals`` block). ``consume_sheds=False``
        reads the shed delta without resetting the tick baseline — the
        read-only path for :meth:`stats`, so an observer polling ``/fleet``
        cannot eat the controller's shed-pressure signal."""
        # canary probes (observability/canary.py) are synthetic: a shed or
        # queued probe is the canary observing pressure, not pressure worth
        # buying a replica for — subtract the canary class from both signals
        sheds = self._registry.total(C.SHEDS_TOTAL) - self._registry.total(
            C.SHEDS_TOTAL, {"class": "canary"}
        )
        shed_delta = sheds - self._last_sheds
        if consume_sheds:
            self._last_sheds = sheds
        out: dict = {"sheds_delta": shed_delta, "burn_rate": self._burn_rate()}
        for group in ("decode", "prefill"):
            everyone = self._replicas(group)
            # a watchdog-quarantined replica (serving/health.py,
            # docs/health.md) is benched capacity: it serves nothing, so
            # counting it would mask the exact pressure its absence
            # creates. The flag read is cheap and side-effect-free
            # (healthy() would consume fault-plan hits).
            quarantined = [
                r for r in everyone if getattr(r, "quarantined", False)
            ]
            replicas = [
                r for r in everyone if not getattr(r, "quarantined", False)
            ]
            if not replicas:
                out[group] = None
                continue
            # synthetic canary probes (observability/canary.py) are not
            # demand: a queued probe must never scale the fleet. depths()
            # is guarded — test fakes stub only total_depth()
            queued = sum(
                r.engine.policy.total_depth()
                - getattr(r.engine.policy, "depths", dict)().get("canary", 0)
                for r in replicas
            )
            outstanding = sum(r.outstanding() for r in replicas)
            capacity = sum(max(1, r.capacity()) for r in replicas)
            kv = max(self._kv_pressure(r.engine) for r in replicas)
            out[group] = {
                "replicas": len(replicas),
                "quarantined": len(quarantined),
                "quarantined_names": sorted(r.name for r in quarantined),
                "queued": queued,
                "queued_per_replica": queued / len(replicas),
                "outstanding": outstanding,
                "capacity": capacity,
                "utilization": outstanding / capacity,
                "kv_occupancy": kv,
            }
        return out

    @staticmethod
    def _kv_pressure(engine) -> float:
        """Occupancy that actually pins pages: allocated MINUS the prefix
        cache's reclaimable warmth, PLUS queued admissions' reservations.
        Raw ``occupancy()`` would read ~1.0 forever on a warm engine whose
        trie has absorbed the free pool — warmth is evictable on demand,
        and scaling out on it is pure flap (docs/kv_cache.md)."""
        occ = engine.cache.occupancy()
        cached = (
            engine.prefix_cache.cached_pages
            if engine.prefix_cache is not None
            else 0
        )
        pinned = max(0, occ["pages_used"] - cached) + getattr(
            engine.admission, "reserved_pages", 0
        )
        return min(1.0, pinned / max(1, occ["pages_total"]))

    def _pressure_trigger(self, group: str, sig: dict, fleet: dict) -> str | None:
        """The scale-up trigger for this group, or None. Prefill replicas
        have no decode latency to defend: only their own backlog counts."""
        q_names = set(sig.get("quarantined_names", ()))
        handled = self._quarantine_handled[group]
        handled &= q_names  # quarantine lifted: re-arm for a later re-bench
        if q_names - handled:
            # the watchdog benched a replica for repeated wedges: replace
            # its capacity via a snapshot warm boot NOW rather than waiting
            # for the queues the hole will back up (docs/health.md). Edge-
            # triggered per benched replica — the scale-up marks it handled,
            # so a 30s quarantine does not buy a build every cooldown
            return "quarantine"
        if sig["queued_per_replica"] > self.queue_high or (
            group == "prefill"
            and sig["outstanding"] / max(1, sig["replicas"]) > self.queue_high
        ):
            return "queue_pressure"
        if sig["kv_occupancy"] > self.kv_high:
            return "kv_pressure"
        if group == "decode" and fleet["sheds_delta"] >= self.shed_high > 0:
            return "shed_pressure"
        if group == "decode" and fleet["burn_rate"] > self.burn_high:
            return "slo_burn"
        return None

    # -- the control loop ----------------------------------------------------

    def tick(self) -> list[dict]:
        """One control-loop pass; returns the actions taken (also appended
        to :attr:`events`). Safe to call directly in tests instead of
        running the background thread.

        Scale-up BUILDS run outside the controller lock: restoring a
        multi-GB param tree and jit-warming an engine can take seconds,
        and an operator polling :meth:`stats` (or :meth:`stop`) must not
        block behind it. Only one caller drives ticks (the background
        thread, or a test), so deferring the build past the lock cannot
        interleave two decisions."""
        with self._lock:
            actions, deferred = self._tick_locked()
        for group, trigger, sig in deferred:
            rec = self._scale_up(group, trigger, sig)
            with self._lock:
                self._cooldown_until[group] = self._clock() + self.cooldown_s
                self.events.append(rec)
                del self.events[:-512]
                self._publish_sizes()
            actions.append(rec)
        return actions

    def _tick_locked(self) -> tuple[list[dict], list[tuple]]:
        now = self._clock()
        actions: list[dict] = []
        deferred: list[tuple] = []  # (group, trigger, sig) builds to run
        self._reap_drained(now)
        if now - self._last_store_heartbeat >= 15.0:
            self._last_store_heartbeat = now
            for r in self.router.replicas:
                store = shared_prefix_store(r)
                if store is not None:
                    try:
                        store.heartbeat()
                    except Exception:
                        pass
        fleet = self.signals()
        for group in ("decode", "prefill"):
            sig = fleet.get(group)
            if sig is None:
                # a group with no replicas yet only scales up if the
                # operator declared a floor for it
                if self.min_replicas.get(group, 0) > 0:
                    deferred.append((group, "min_replicas", {}))
                continue
            if sig["replicas"] < self.min_replicas.get(group, 0):
                # below the declared floor: fill unconditionally (no
                # hysteresis/cooldown — the floor is a hard promise)
                deferred.append((group, "min_replicas", sig))
                continue
            trigger = self._pressure_trigger(group, sig, fleet)
            if trigger is not None:
                self._down_streak[group] = 0
                self._up_streak[group] += 1
                if (
                    self._up_streak[group] >= self.up_ticks
                    and now >= self._cooldown_until[group]
                    and sig["replicas"] < self.max_replicas.get(group, 0)
                ):
                    deferred.append((group, trigger, sig))
                    self._up_streak[group] = 0
                    self._cooldown_until[group] = self._clock() + self.cooldown_s
                    if trigger == "quarantine":
                        # one replacement per benched replica: mark exactly
                        # one unhandled name; any further quarantined
                        # replicas keep the trigger armed for the next tick
                        new = (
                            set(sig["quarantined_names"])
                            - self._quarantine_handled[group]
                        )
                        if new:
                            self._quarantine_handled[group].add(min(new))
                continue
            self._up_streak[group] = 0
            n = sig["replicas"]
            idle = (
                sig["queued"] == 0
                and n > self.min_replicas.get(group, 0)
                and sig["outstanding"]
                <= self.idle_low * (sig["capacity"] - sig["capacity"] / n)
            )
            if idle:
                self._down_streak[group] += 1
                if (
                    self._down_streak[group] >= self.down_ticks
                    and now >= self._cooldown_until[group]
                ):
                    act = self._scale_down(group, sig)
                    if act is not None:
                        actions.append(act)
                        self._down_streak[group] = 0
                        self._cooldown_until[group] = (
                            self._clock() + self.cooldown_s
                        )
            else:
                self._down_streak[group] = 0
        self._publish_sizes()
        self.events.extend(actions)
        del self.events[:-512]  # bounded like the journal ring
        return actions, deferred

    def _scale_up(self, group: str, trigger: str, sig: dict) -> dict:
        """Build, start, warm, and register one replica. Runs OUTSIDE the
        controller lock (see :meth:`tick`); only ``_owned`` is touched
        under it."""
        with self._lock:
            self._seq += 1
            name = f"{group}-as{self._seq}"
        role = "prefill" if group == "prefill" else "decode"
        t0 = time.perf_counter()
        out = self.factory(name, role)
        replica, boot = out if isinstance(out, tuple) else (out, "cold")
        if getattr(replica, "serves_requests", True):
            replica.engine.start()
        with self._lock:
            stopping = self._stopping
        if stopping:
            # stop() arrived while this build was in flight (its thread
            # join timed out): registering now would hand a running engine
            # to a fleet nobody owns — discard the build instead
            try:
                replica.engine.stop()
            except Exception:
                logger.warning("fleet: engine stop failed for %s", name)
            _deregister_prefix_store(replica)
            rec = {
                "at": time.time(), "action": "scale_up", "trigger": trigger,
                "role": group, "replica": name, "boot": boot,
                "aborted": "controller_stopping",
            }
            self.journal.record(rec)
            logger.info("fleet: discarded in-flight build of %s (stopping)", name)
            return rec
        try:
            self.router.add_replica(replica)
        except Exception:
            # registration refused (e.g. a name collision with a replica a
            # previous controller left behind): the engine is already
            # running — stop it rather than leak a scheduler thread plus a
            # full weight set with no owner
            try:
                replica.engine.stop()
            except Exception:
                logger.warning("fleet: engine stop failed for %s", name)
            _deregister_prefix_store(replica)
            raise
        boot_s = time.perf_counter() - t0
        with self._lock:
            self._owned[group].append(name)
        _obs.record_fleet_decision("scale_up", trigger, registry=self._registry)
        _obs.record_fleet_boot(boot_s, boot, registry=self._registry)
        rec = {
            "at": time.time(),
            "action": "scale_up",
            "trigger": trigger,
            "role": group,
            "replica": name,
            "boot": boot,
            "boot_s": round(boot_s, 4),
            "queued": sig.get("queued", 0),
            "kv_occupancy": round(sig.get("kv_occupancy", 0.0), 4),
            "replicas_before": sig.get("replicas", 0),
            "replicas_after": sig.get("replicas", 0) + 1,
        }
        self.journal.record(rec)
        logger.info(
            "fleet scale_up %s (%s, %s boot %.3fs)", name, trigger, boot, boot_s
        )
        return rec

    def _scale_down(self, group: str, sig: dict) -> dict | None:
        # newest owned replica that is healthy — idle preferred, but with
        # drain-by-migration a BUSY victim is eligible too: its live
        # requests move to the remaining fleet in one migration each
        # (docs/failover.md), so scale-in no longer waits for request
        # completion. The seed fleet is never reaped, and a replica on the
        # router's down list is the health re-admission cycle's business,
        # not ours (anti-flap).
        victim = None
        busy = None
        for name in reversed(self._owned[group]):
            r = next(
                (x for x in self.router.replicas if x.name == name), None
            )
            if r is None or not r.healthy():
                continue
            if r.outstanding() == 0:
                victim = r
                break
            if (
                busy is None
                and self.migrate_on_drain
                # only engines with the live-migration surface: a busy
                # victim that cannot migrate would fall straight into the
                # drain_timeout forced reap (_reap_drained's duck-typing)
                and hasattr(r.engine, "migrate_out")
            ):
                busy = r
        if victim is None:
            victim = busy
        if victim is None:
            return None
        self.router.remove_replica(victim.name)
        self._owned[group].remove(victim.name)
        self._draining.append((victim, self._clock()))
        _obs.record_fleet_decision("scale_down", "idle", registry=self._registry)
        rec = {
            "at": time.time(),
            "action": "scale_down",
            "trigger": "idle",
            "role": group,
            "replica": victim.name,
            "queued": sig.get("queued", 0),
            "outstanding": sig.get("outstanding", 0),
            "replicas_before": sig.get("replicas", 0),
            "replicas_after": sig.get("replicas", 0) - 1,
        }
        self.journal.record(rec)
        logger.info("fleet scale_down %s (idle, draining)", victim.name)
        return rec

    def _reap_drained(self, now: float) -> None:
        """Stop the engines of removed replicas once their last requests
        are gone. With ``migrate_on_drain`` a victim's live requests are
        checkpoint-migrated onto the remaining fleet RIGHT HERE
        (serving/failover.py) — drain time is bounded by one migration per
        request, not request completion, and ``fleet.jsonl`` records the
        ``tokens_migrated`` carried off instead of requests killed. A
        replica that still will not drain within ``drain_timeout_s`` is
        stopped anyway (its engine releases any caller loudly; the
        router-level stream failover then resumes them reactively) — a
        leak bounded in time beats a zombie engine held forever."""
        still: list[tuple[object, float]] = []
        for replica, removed_at in self._draining:
            timed_out = now - removed_at > self.drain_timeout_s
            last_at, fails = self._drain_attempts.get(replica.name, (0.0, 0))
            if (
                self.migrate_on_drain
                and replica.outstanding() > 0
                and getattr(replica, "serves_requests", True)
                # duck-typed: only engines with the live-migration surface
                # (a remote/fake replica without it keeps the idle-wait +
                # timeout behavior)
                and hasattr(replica.engine, "migrate_out")
                # backoff: after N consecutive no-progress attempts, wait
                # tick_s * 2^N (capped) before trying again
                and now - last_at >= min(self.tick_s * (2 ** fails), 10.0)
            ):
                try:
                    from ..serving import failover as _failover

                    moved = _failover.drain_replica(replica, self.router)
                except Exception:
                    logger.exception(
                        "fleet: drain migration failed for %s", replica.name
                    )
                    moved = None
                progressed = bool(
                    moved and (moved["migrated"] or moved["resumed"])
                )
                self._drain_attempts[replica.name] = (
                    now, 0 if progressed else fails + 1
                )
                # journal progress always; pure-failure attempts only once
                # per stuck victim (the retry spam the backoff bounds)
                if moved and (
                    progressed or (moved["failed"] and fails == 0)
                ):
                    self._drained_tokens[replica.name] = (
                        self._drained_tokens.get(replica.name, 0)
                        + moved["tokens_migrated"]
                    )
                    rec = {
                        "at": time.time(),
                        "action": "drain_migrate",
                        "role": _role_group(replica),
                        "replica": replica.name,
                        **moved,
                    }
                    self.journal.record(rec)
                    self.events.append(rec)
                    logger.info(
                        "fleet drain_migrate %s: %s", replica.name, moved
                    )
            if replica.outstanding() == 0 or timed_out:
                try:
                    if timed_out and replica.outstanding() > 0:
                        # forced reap with live streams: release them as
                        # ERRORS so the router-level reactive failover
                        # resumes them — a "stop" release would end them
                        # as silently truncated successes
                        try:
                            replica.engine.stop(reason="error")
                        except TypeError:  # engine without the kwarg
                            replica.engine.stop()
                    else:
                        replica.engine.stop()
                except Exception:
                    logger.warning(
                        "fleet: engine stop failed for %s", replica.name
                    )
                _deregister_prefix_store(replica)
                if timed_out:
                    _obs.record_fleet_decision(
                        "scale_down", "drain_timeout",
                        registry=self._registry,
                    )
                    self.journal.record({
                        "at": time.time(),
                        "action": "scale_down",
                        "trigger": "drain_timeout",
                        "role": _role_group(replica),
                        "replica": replica.name,
                        "tokens_migrated": self._drained_tokens.get(
                            replica.name, 0
                        ),
                    })
                self._drained_tokens.pop(replica.name, None)
                self._drain_attempts.pop(replica.name, None)
            else:
                still.append((replica, removed_at))
        self._draining = still

    def _publish_sizes(self) -> None:
        counts = {"prefill": 0, "decode": 0, "unified": 0}
        for r in self.router.replicas:
            counts[getattr(r, "role", "unified")] += 1
        for role, n in counts.items():
            _obs.set_fleet_replicas(role, n, registry=self._registry)

    # -- lifecycle / surfaces ------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        if self._running:
            return self
        # re-baseline the shed delta at loop start: sheds recorded between
        # construction and start (e.g. a pinned-fleet A/B arm run first)
        # are history, not pressure — without this the first tick would
        # scale out on traffic this controller never saw
        self._last_sheds = self._registry.total(C.SHEDS_TOTAL)
        self._stopping = False
        self._running = True

        def loop():
            while self._running:
                try:
                    self.tick()
                except Exception:
                    logger.exception("fleet autoscaler tick failed")
                time.sleep(self.tick_s)

        self._thread = threading.Thread(
            target=loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, *, drain: bool = True) -> None:
        with self._lock:
            self._stopping = True
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if drain:
            with self._lock:
                # REAL time here, even under an injected fake clock: the
                # wait advances via sleep, and a fake clock that never
                # moves would spin this loop forever
                deadline = time.monotonic() + self.drain_timeout_s
                while self._draining and time.monotonic() < deadline:
                    self._reap_drained(self._clock())
                    if self._draining:
                        time.sleep(0.02)
                # anything still draining at the deadline is force-reaped
                self._reap_drained(self._clock() + self.drain_timeout_s + 1)

    def stats(self) -> dict:
        """Live controller snapshot (the ``/fleet`` route's payload half
        that cannot be reconstructed from pushed metrics)."""
        with self._lock:
            counts: dict[str, int] = {}
            for r in self.router.replicas:
                role = getattr(r, "role", "unified")
                counts[role] = counts.get(role, 0) + 1
            return {
                "replicas": counts,
                "owned": {k: list(v) for k, v in self._owned.items()},
                "draining": [r.name for r, _t in self._draining],
                "events": list(self.events[-50:]),
                "signals": self.signals(consume_sheds=False),
            }
