"""Request scheduling: priority + fair-share admission control, deadline-
aware shedding, and prefix-affinity multi-replica routing.

The first subsystem where the framework makes load-dependent decisions on
the serving path (ISSUE 4). Three layers, each usable on its own:

- :mod:`.policy` — pluggable queue-ordering policies. ``SchedulerPolicy``
  replaces the engine's FIFO pop: priority classes
  (``interactive`` > ``default`` > ``batch``) with weighted fair-share
  deficit scheduling across tenants within a class.
- :mod:`.admission` — bounded per-class queues with cost-aware admission
  (estimated KV pages vs. live occupancy), load shedding (HTTP 429 +
  ``Retry-After`` at the API layers), and per-request deadlines.
- :mod:`.router` — a multi-replica front that routes requests sharing a
  prompt prefix to the same replica (so paged-KV prefix reuse actually
  hits), with least-outstanding-work fallback and health/backpressure
  awareness.

The whole package is jax-free (like ``core/``): policies and admission run
on the control path and must never pay a jax import or chip attach.
"""

from .admission import AdmissionConfig, AdmissionController, ShedError
from .policy import (
    CLASS_RANK,
    DEFAULT_CLASS,
    PRIORITY_CLASSES,
    FairSharePolicy,
    FIFOPolicy,
    ScheduledRequest,
    SchedulerPolicy,
    validate_class,
)
from .router import EngineReplica, PrefixAffinityRouter

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "CLASS_RANK",
    "DEFAULT_CLASS",
    "EngineReplica",
    "FIFOPolicy",
    "FairSharePolicy",
    "PRIORITY_CLASSES",
    "PrefixAffinityRouter",
    "ScheduledRequest",
    "SchedulerPolicy",
    "ShedError",
    "validate_class",
]
