"""Admission control: bounded per-class queues, cost-aware shedding,
deadlines.

Overload used to mean unbounded queueing — every request eventually served,
every client waiting forever. Admission control turns overload into fast,
honest rejection instead: the OpenAI server and the web gateway surface a
:class:`ShedError` as HTTP 429 with a ``Retry-After`` hint, and
``mtpu_sheds_total{class,reason}`` counts what was turned away.

Three shedding rules, checked at submit time:

- ``queue_full`` — the request's priority class already has
  ``max_queue[class]`` entries waiting. Bounds are per class so a batch
  flood fills only the batch queue; interactive traffic keeps its own
  headroom.
- ``too_large`` — the request's estimated KV footprint exceeds the whole
  page pool; it could never be scheduled.
- ``kv_pressure`` — optional (off by default): live page occupancy plus the
  pages already promised to queued work plus this request would exceed the
  class's occupancy ceiling. Lower classes get lower ceilings, so batch
  work sheds first as the cache fills (occupancy comes from the PR 3
  telemetry: the same numbers ``mtpu_kv_page_occupancy`` exports).

Reservation accounting: an admitted-but-not-yet-scheduled request *reserves*
its estimated pages (``mtpu_kv_pages_reserved``). The engine releases the
reservation when the real claim happens at prefill admission — or when the
request is aborted or its deadline expires while still queued, which is what
keeps cost-aware admission from leaking budget on cancelled work.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable

from ..observability import metrics as _obs
from .policy import PRIORITY_CLASSES, ScheduledRequest, validate_class


class ShedError(RuntimeError):
    """Request rejected by admission control. API layers translate this to
    HTTP 429 with ``Retry-After: ceil(retry_after_s)``."""

    def __init__(self, reason: str, retry_after_s: float, message: str):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = max(1.0, float(retry_after_s))


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Per-class queue bounds + optional occupancy ceilings.

    ``max_queue`` maps class -> bound. ``kv_ceiling`` maps class -> max
    (occupancy + reserved + this request) fraction; a missing class never
    sheds on pressure. ``retry_after_s`` is the base back-off hint, scaled
    up with queue depth.
    """

    max_queue: dict = dataclasses.field(
        default_factory=lambda: {c: 4096 for c in PRIORITY_CLASSES}
    )
    kv_ceiling: dict = dataclasses.field(default_factory=dict)
    retry_after_s: float = 1.0

    @classmethod
    def from_env(cls) -> "AdmissionConfig":
        """Production defaults, env-overridable:

        - ``MTPU_SCHED_MAX_QUEUE`` (all classes) and per-class
          ``MTPU_SCHED_MAX_QUEUE_INTERACTIVE/_DEFAULT/_BATCH``;
        - ``MTPU_SCHED_KV_HEADROOM`` — the *batch* occupancy ceiling;
          ``default`` gets +0.10 (capped at 1.0) and ``interactive`` never
          sheds on pressure. Unset = pressure shedding off.
        """
        base = _env_int("MTPU_SCHED_MAX_QUEUE", 4096)
        max_queue = {
            c: _env_int(f"MTPU_SCHED_MAX_QUEUE_{c.upper()}", base)
            for c in PRIORITY_CLASSES
        }
        kv_ceiling: dict = {}
        headroom = _env_float("MTPU_SCHED_KV_HEADROOM")
        if headroom is not None:
            kv_ceiling = {
                "batch": headroom,
                "default": min(1.0, headroom + 0.10),
            }
        return cls(max_queue=max_queue, kv_ceiling=kv_ceiling)


class AdmissionController:
    """Stateful admission gate: bounds, pressure shedding, reservations."""

    def __init__(
        self,
        config: AdmissionConfig | None = None,
        *,
        clock: Callable[[], float] | None = None,
    ):
        self.config = config or AdmissionConfig.from_env()
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self.reserved_pages = 0
        self.sheds = 0  # monotonic, all classes/reasons
        self.admitted = 0
        # per-tenant usage accountant (observability/usage.py): the owning
        # engine assigns its EngineUsage here so sheds are charged to the
        # tenant/class that was turned away, not just a global counter
        self.usage = None

    def _shed(self, entry: ScheduledRequest, reason: str, depth: int,
              message: str) -> ShedError:
        with self._lock:
            self.sheds += 1
        _obs.record_shed(entry.priority, reason)
        if self.usage is not None:
            self.usage.note_shed(entry.tenant, entry.priority)
        bound = max(1, self.config.max_queue.get(entry.priority, 1))
        retry = self.config.retry_after_s * (1.0 + depth / bound)
        return ShedError(reason, retry, message)

    def admit(
        self,
        entry: ScheduledRequest,
        *,
        depths: dict,
        pages_used: int,
        pages_total: int,
    ) -> None:
        """Admit ``entry`` (reserving its cost) or raise :class:`ShedError`.

        ``depths`` is the policy's current per-class queue depth;
        ``pages_used``/``pages_total`` come from the live KV allocator
        (``PagedKVCache.occupancy()``).
        """
        validate_class(entry.priority)
        cfg = self.config
        depth = int(depths.get(entry.priority, 0))
        bound = cfg.max_queue.get(entry.priority)
        if bound is not None and depth >= bound:
            raise self._shed(
                entry, "queue_full", depth,
                f"{entry.priority} queue is full ({depth}/{bound})",
            )
        if pages_total > 0 and entry.cost > pages_total:
            raise self._shed(
                entry, "too_large", depth,
                f"request needs {entry.cost} KV pages; the pool has "
                f"{pages_total}",
            )
        ceiling = cfg.kv_ceiling.get(entry.priority)
        if ceiling is not None and pages_total > 0:
            with self._lock:
                projected = (
                    pages_used + self.reserved_pages + entry.cost
                ) / pages_total
            if projected > ceiling:
                raise self._shed(
                    entry, "kv_pressure", depth,
                    f"projected KV occupancy {projected:.2f} exceeds the "
                    f"{entry.priority} ceiling {ceiling:.2f}",
                )
        with self._lock:
            self.reserved_pages += entry.cost
            self.admitted += 1
            reserved = self.reserved_pages
        _obs.set_kv_pages_reserved(reserved)
        _obs.record_admitted(entry.priority)

    def release(self, entry: ScheduledRequest) -> None:
        """Return a queued entry's page reservation (popped for prefill,
        aborted, or deadline-expired)."""
        with self._lock:
            self.reserved_pages = max(0, self.reserved_pages - entry.cost)
            reserved = self.reserved_pages
        _obs.set_kv_pages_reserved(reserved)

    def reserve(self, entry: ScheduledRequest) -> None:
        """Re-take a reservation (claim failed; the entry was requeued)."""
        with self._lock:
            self.reserved_pages += entry.cost
            reserved = self.reserved_pages
        _obs.set_kv_pages_reserved(reserved)

    def shed_rate(self) -> float:
        """Lifetime shed fraction (sheds / offered load)."""
        with self._lock:
            offered = self.sheds + self.admitted
            return self.sheds / offered if offered else 0.0
