"""Prefix-affinity multi-replica routing.

One ``LLMEngine`` per process is the deployed shape; serving heavy traffic
means N replicas behind a front. A random/round-robin front wastes the
paged-KV prefix cache: two requests sharing a system prompt land on
different replicas and each pays the full prefill. This router keys every
request by its **first prefix-cache block** (the first ``prefix_tokens``
prompt tokens — the same page-aligned unit the :mod:`..serving.prefix_cache`
trie shares) and sends equal keys to the same replica via rendezvous
hashing, so prefix reuse actually hits (the Ragged Paged Attention paper's
motivating layout: KV pages are only reusable on the replica that holds
them).

Fallbacks keep affinity from becoming a hotspot:

- a **saturated** replica (outstanding work >= ``saturation_factor`` x its
  slot capacity) diverts new prompts to the least-loaded healthy replica;
- an **unhealthy** replica (scheduler stopped on error, or a custom health
  probe) is skipped — but NOT forever. Unhealthy used to be a one-way
  door: a replica that flapped once was filtered out of every future
  candidate set. Now an unhealthy observation marks the replica down for
  ``reprobe_s`` seconds, after which the router **re-probes** it
  (``probe()`` when the replica has one — ``EngineReplica.probe`` revives
  a stopped-on-error engine — else ``healthy()``) and re-admits it on
  success (``mtpu_router_readmissions_total``; docs/faults.md covers the
  flap -> evict -> re-admit cycle the chaos harness drives). A replica
  whose ``healthy()`` simply flips back to true rejoins immediately, no
  probe wait.

``mtpu_router_requests_total{route=affinity|fallback}`` counts placements;
``mtpu_router_affinity_hits_total`` counts the wins that matter — a repeated
key landing on the replica that already holds its prefix KV.

Replicas are duck-typed (``name``/``encode``/``submit``/``stream``/
``abort``/``outstanding``/``capacity``/``healthy``): :class:`EngineReplica`
adapts an in-process ``LLMEngine``; the same protocol fronts remote
replicas (e.g. an executor container pool proxying to a served engine) —
anything that can estimate its outstanding work can sit behind the router.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict

from ..faults import inject as _inject
from ..observability import metrics as _obs
from ..observability import reqtrace as _rt


#: disaggregated-serving roles (docs/disagg.md): a ``prefill`` replica only
#: computes prompt KV and ships pages (its engine never starts a scheduler
#: loop); a ``decode`` replica adopts shipped pages and continues decoding
#: (and can re-prefill as the unified fallback); ``unified`` does both.
ROLES = ("prefill", "decode", "unified")


def rendezvous_score(key: bytes, name: str) -> bytes:
    """THE fleet's rendezvous (highest-random-weight) score: ``max`` of
    this over member names picks the owner of ``key``. One function on
    purpose — request placement (:meth:`PrefixAffinityRouter._preferred`)
    and prefix-chain spill ownership (:mod:`..serving.prefix_store`) must
    agree on the hash, so the replica a shared prefix routes to is also
    the replica that owns spilling it."""
    return hashlib.sha1(key + name.encode()).digest()


class EngineReplica:
    """Adapter: one in-process ``LLMEngine`` as a routable replica."""

    def __init__(
        self,
        engine,
        name: str,
        *,
        saturation_factor: float = 2.0,
        role: str = "unified",
    ):
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}; one of {ROLES}")
        self.engine = engine
        self.name = name
        self.role = role
        # gray-failure watchdog surface (serving/health.py, docs/health.md):
        # the watchdog writes the graded classification here and benches a
        # repeatedly-wedging replica via the quarantine flag — healthy()
        # and probe() both honor it, so neither placement nor the router's
        # revival probe can resurrect a quarantined replica early
        self.health_state = "healthy"
        self.quarantined = False
        if role == "prefill" and hasattr(engine, "prefill_budget"):
            # prefill replicas have no decode to protect: the per-tick
            # prefill token budget (docs/scheduling.md, stall-free
            # admission) defaults to unlimited here even when
            # MTPU_PREFILL_BUDGET is set process-wide for the decode side
            engine.prefill_budget = 0
        self.saturation_factor = float(saturation_factor)
        # request-trace spans carry the FLEET name of the replica that
        # recorded them (track assignment in the Perfetto export); adopt
        # the engine unless something already named it
        if getattr(engine, "trace_name", "engine") == "engine":
            engine.trace_name = name

    @property
    def serves_requests(self) -> bool:
        """Whether this replica can own a full request end to end (prefill-
        only replicas cannot: they hold no decode loop)."""
        return self.role != "prefill"

    def encode(self, prompt: str) -> list[int]:
        return self.engine.tokenizer.encode(prompt)

    def submit(self, prompt: str, params=None, image=None, **kw):
        return self.engine.submit(prompt, params, image=image, **kw)

    def stream(self, req):
        return self.engine.stream(req)

    def abort(self, req) -> None:
        self.engine.abort(req)

    def outstanding(self) -> int:
        """Waiting + decoding requests (the router's load signal); for a
        prefill-role replica, slot-free prefills in flight count too."""
        active = sum(1 for s in self.engine.slots if not s.free)
        pending = getattr(self.engine, "_prefill_sync_pending", 0)
        return self.engine.policy.total_depth() + active + pending

    def capacity(self) -> int:
        return self.engine.max_slots

    def healthy(self) -> bool:
        if self.quarantined:
            return False
        # fault point (docs/faults.md): one flapped health observation —
        # the router evicts, re-probes, and re-admits this replica
        if _inject.fire("router.health_flap"):
            return False
        return not self.engine._stopped_on_error

    def probe(self) -> bool:
        """Re-admission probe (router, after ``reprobe_s`` down): a replica
        whose engine stopped on a scheduler error is revived and restarted
        — every caller it owed was already released with
        finish_reason="error", so it comes back empty. Prefill-role
        replicas never start a scheduler loop, so they only re-check
        health. A QUARANTINED replica refuses the probe outright: the
        watchdog benched it for repeated wedges and owns lifting the flag
        (docs/health.md) — reviving it early would put a known-bad replica
        back in placement. Returns post-probe health."""
        if self.quarantined:
            return False
        eng = self.engine
        if eng._stopped_on_error and self.serves_requests:
            try:
                eng.revive().start()
            except Exception:
                return False
        return self.healthy()

    def saturated(self) -> bool:
        return self.outstanding() >= self.saturation_factor * max(
            1, self.capacity()
        )

    def stats(self) -> dict:
        """Per-replica snapshot for router/gateway/CLI surfaces, including
        the last-progress watermark ages (read through the health API —
        docs/health.md; ``tpurun top`` and ``/health`` render these)."""
        from ..serving.health import replica_snapshot

        return {
            "role": self.role,
            "outstanding": self.outstanding(),
            "healthy": self.healthy(),
            "saturated": self.saturated(),
            "state": self.health_state,
            "quarantined": self.quarantined,
            "progress": replica_snapshot(self),
        }


class PrefixAffinityRouter:
    """Route requests to replicas by shared-prefix affinity."""

    #: remembered key -> replica-name placements (bounded LRU): an affinity
    #: *hit* requires the key to have been routed there before — the first
    #: occurrence builds the prefix KV, repeats reuse it
    SEEN_KEYS_MAX = 4096

    #: seconds a replica observed unhealthy stays out of the candidate set
    #: before the router re-probes it (ctor-overridable; short enough that
    #: a transient flap costs one probe interval, long enough that a truly
    #: dead replica isn't probed on every request)
    REPROBE_S = 5.0

    def __init__(
        self,
        replicas: list,
        *,
        prefix_tokens: int = 16,
        reprobe_s: float | None = None,
        clock=None,  # injectable monotonic clock (fake-clock flap tests)
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.replicas = list(replicas)
        self.prefix_tokens = max(1, int(prefix_tokens))
        self.reprobe_s = float(
            reprobe_s if reprobe_s is not None else self.REPROBE_S
        )
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._seen: OrderedDict[bytes, str] = OrderedDict()
        #: replica name -> next re-probe time (monotonic): the down list.
        #: Present = excluded from candidates until probed healthy again.
        self._down: dict[str, float] = {}
        #: replica name -> placement weight in (0, 1]: the GRADED health
        #: signal next to the binary healthy()/down cycle. The gray-failure
        #: watchdog down-weights a degraded replica (docs/health.md); a
        #: weight below 1.0 loses affinity preference and costs
        #: proportionally more in every least-loaded comparison, so new
        #: work drains away without cutting the replica off entirely.
        self._weights: dict[str, float] = {}
        self.affinity_hits = 0
        self.fallbacks = 0
        self.readmissions = 0
        # role-aware split (replicas without a .role are unified): route()
        # only ever places full requests on serving-capable replicas;
        # prefill-only ones are plan()'s business
        self._serving = [
            r for r in self.replicas
            if getattr(r, "role", "unified") != "prefill"
        ]
        if not self._serving:
            raise ValueError(
                "router needs at least one decode-capable (non-prefill) "
                "replica to own requests"
            )

    # -- fleet membership (modal_examples_tpu/fleet, docs/fleet.md) ----------

    def add_replica(self, replica) -> None:
        """Register a replica under live traffic. Rendezvous hashing means
        only the keys the newcomer now wins remap to it — every other
        prompt keeps its affinity replica, so a scale-out never stampedes
        the prefix caches. Lists are rebuilt copy-on-write under the lock;
        in-flight ``route()`` calls finish against the snapshot they read."""
        if getattr(replica, "role", "unified") not in ROLES:
            raise ValueError(f"unknown replica role {replica.role!r}")
        with self._lock:
            if any(r.name == replica.name for r in self.replicas):
                raise ValueError(f"replica name {replica.name!r} already registered")
            replicas = self.replicas + [replica]
            self.replicas = replicas
            self._serving = [
                r for r in replicas
                if getattr(r, "role", "unified") != "prefill"
            ]

    def remove_replica(self, name: str):
        """Deregister a replica from placement; returns it. The replica
        stops receiving NEW requests immediately, but requests it already
        owns keep streaming (ownership rides on the request, not on the
        router), so the caller drains ``outstanding()`` to zero before
        stopping the engine — see ``FleetAutoscaler._scale_down``."""
        with self._lock:
            victim = next((r for r in self.replicas if r.name == name), None)
            if victim is None:
                raise KeyError(f"no replica named {name!r}")
            replicas = [r for r in self.replicas if r.name != name]
            serving = [
                r for r in replicas
                if getattr(r, "role", "unified") != "prefill"
            ]
            if getattr(victim, "role", "unified") != "prefill" and not serving:
                raise ValueError(
                    "cannot remove the last decode-capable replica"
                )
            self.replicas = replicas
            self._serving = serving
            self._down.pop(name, None)
        return victim

    # -- placement -----------------------------------------------------------

    def _key(self, tokens: list[int]) -> bytes:
        head = tokens[: self.prefix_tokens]
        return hashlib.sha1(
            b",".join(str(int(t)).encode() for t in head)
        ).digest()

    def _preferred(self, key: bytes, candidates: list | None = None):
        """Rendezvous (highest-random-weight) hashing: stable per key, and
        removing a replica only remaps that replica's keys."""
        def score(replica) -> bytes:
            return rendezvous_score(key, replica.name)

        return max(
            candidates if candidates is not None else self.replicas, key=score
        )

    def _candidates(self, pool: list) -> list:
        """The healthy members of ``pool``, with down-tracking + re-probe.

        An unhealthy observation marks the replica down. While down it
        still gets the CHEAP ``healthy()`` recheck every placement —
        ``healthy()`` flipping back true re-admits it on the spot — but
        the EXPENSIVE ``probe()`` (which may revive and restart a
        stopped-on-error engine, ``EngineReplica.probe``) only runs once
        ``reprobe_s`` has passed, and a failed probe pushes the next one
        out by another interval. So a transient flap costs at most one
        placement, while a truly dead replica is revival-attempted at a
        bounded rate."""
        now = self._clock()
        out = []
        for r in pool:
            with self._lock:
                due = self._down.get(r.name)
            if due is None:
                if r.healthy():
                    out.append(r)
                else:
                    with self._lock:
                        self._down[r.name] = now + self.reprobe_s
                continue
            if r.healthy():
                self._readmit(r.name)
                out.append(r)
                continue
            if now < due:
                continue  # still down; not revival-probe time yet
            probe = getattr(r, "probe", None)
            if probe is not None and probe():
                self._readmit(r.name)
                out.append(r)
            else:
                with self._lock:
                    self._down[r.name] = now + self.reprobe_s
        return out

    # -- graded health (serving/health.py watchdog, docs/health.md) ----------

    def set_health_weight(self, name: str, weight: float) -> None:
        """Down-weight (or restore) one replica's placement. ``weight`` in
        (0, 1]; 1.0 clears the entry. In-flight requests are untouched —
        this only shapes where NEW work lands."""
        w = float(weight)
        if not (0.0 < w <= 1.0):
            raise ValueError(f"health weight must be in (0, 1], got {w}")
        with self._lock:
            if w >= 1.0:
                self._weights.pop(name, None)
            else:
                self._weights[name] = w

    def health_weight(self, name: str) -> float:
        with self._lock:
            return self._weights.get(name, 1.0)

    def reprobe(self) -> list:
        """One down-tracking/probe pass with no placement: the same
        ``_candidates`` walk a submit runs, minus the request. Returns
        the currently healthy replicas. Re-admission (and the revival
        probe of a stopped-on-error engine) otherwise only advances when
        a placement lands — with traffic stopped, a replica that died at
        the end of a load window would stay down forever. Operators and
        the chaos invariants (``faults.chaos.settle_recovered``) call
        this to settle recovery without synthesizing traffic."""
        return self._candidates(self.replicas)

    def _effective_load(self, replica) -> float:
        """Outstanding work scaled by the inverse health weight: a
        degraded replica at weight 0.25 competes as if 4x busier, plus a
        constant bias so an idle degraded replica still loses to an idle
        healthy one."""
        w = self.health_weight(replica.name)
        load = replica.outstanding() / w
        if w < 1.0:
            load += 1.0 / w
        return load

    def _readmit(self, name: str) -> None:
        with self._lock:
            self._down.pop(name, None)
            self.readmissions += 1
        _obs.record_router_readmission()

    def _prompt_key(self, prompt: str) -> bytes:
        # tokenize only enough text to cover the key's token prefix (the
        # engine re-encodes the full prompt at submit anyway — hashing the
        # whole thing here would pay full tokenization twice per request)
        head = prompt[: max(64, 8 * self.prefix_tokens)]
        return self._key(self.replicas[0].encode(head))

    def route(self, prompt: str):
        """Pick the serving replica for ``prompt``; records routing metrics.
        Prefill-only replicas are never chosen here — they cannot own a
        request (see :meth:`plan` for disaggregated placement)."""
        return self._route_ex(prompt)[0]

    def _route_ex(self, prompt: str):
        """:meth:`route` plus the placement kind — ``(replica,
        "affinity"|"fallback")`` — for the submit path's placement span."""
        key = self._prompt_key(prompt)
        preferred = self._preferred(key, self._serving)
        healthy = self._candidates(self._serving)
        if not healthy:
            raise RuntimeError("no healthy replicas")
        if (
            preferred in healthy
            and not preferred.saturated()
            # a down-weighted (degraded) replica loses affinity preference:
            # prefix warmth is not worth placing onto a replica the
            # watchdog says is limping (docs/health.md)
            and self.health_weight(preferred.name) >= 1.0
        ):
            chosen, route = preferred, "affinity"
        else:
            chosen = min(
                healthy, key=lambda r: (self._effective_load(r), r.name)
            )
            route = "fallback"
        with self._lock:
            hit = route == "affinity" and self._seen.get(key) == chosen.name
            self._seen[key] = chosen.name
            self._seen.move_to_end(key)
            while len(self._seen) > self.SEEN_KEYS_MAX:
                self._seen.popitem(last=False)
            if hit:
                self.affinity_hits += 1
            if route == "fallback":
                self.fallbacks += 1
        _obs.record_router_route(route, affinity_hit=hit)
        return chosen, route

    def plan(self, prompt: str):
        """Disaggregated placement: ``(prefill_replica | None,
        decode_replica)``.

        The prefill replica is chosen by PREFIX-BLOCK affinity among
        healthy, unsaturated prefill-role replicas — its prefix trie holds
        the shared-prefix KV, so a repeated system prompt prefills once and
        ships from cache-warm pages. Its decode target is a stable
        rendezvous pairing over decode-capable replicas (each prefill
        replica streams to "its" decode peer, keeping transfer fan-in
        bounded), diverted to the least-outstanding healthy one when the
        pair is saturated. ``None`` prefill means no healthy prefill peer:
        the caller serves unified on the returned decode replica."""
        key = self._prompt_key(prompt)
        decoders = self._candidates(self._serving)
        if not decoders:
            raise RuntimeError("no healthy decode-capable replicas")
        prefillers = [
            r for r in self._candidates([
                r for r in self.replicas
                if getattr(r, "role", "unified") == "prefill"
            ])
            if not r.saturated()
        ]
        if not prefillers:
            chosen = min(
                decoders, key=lambda r: (self._effective_load(r), r.name)
            )
            with self._lock:
                self.fallbacks += 1
            _obs.record_router_route("fallback")
            return None, chosen
        pre = self._preferred(key, prefillers)
        pair = self._preferred(
            hashlib.sha1(pre.name.encode()).digest(), decoders
        )
        if pair.saturated() or self.health_weight(pair.name) < 1.0:
            pair = min(
                decoders, key=lambda r: (self._effective_load(r), r.name)
            )
        with self._lock:
            hit = self._seen.get(key) == pre.name
            self._seen[key] = pre.name
            self._seen.move_to_end(key)
            while len(self._seen) > self.SEEN_KEYS_MAX:
                self._seen.popitem(last=False)
            if hit:
                self.affinity_hits += 1
        _obs.record_router_route("affinity", affinity_hit=hit)
        return pre, pair

    # -- request lifecycle (delegates to the owning replica) -----------------

    def submit(
        self, prompt: str, params=None, image=None, *, trace=_rt.UNSET, **kw
    ):
        # distributed tracing: mint the request's context HERE when no
        # entry point upstream did (trace id becomes the request id; an
        # upstream None means SAMPLED OUT and passes through); the routing
        # decision itself is a `placement` span, and a health flap
        # observed during it lands as a fault event via the ambient frame
        ctx = _rt.resolve_entry_trace(trace, "router")
        t0 = time.time()
        with _rt.active(ctx, replica="router"):
            replica, route = self._route_ex(prompt)
        _rt.record_span(
            ctx, "placement", start=t0, replica="router", route=route,
            decode_replica=replica.name,
        )
        req = replica.submit(prompt, params, image=image, trace=ctx, **kw)
        # ownership rides ON the request (not a router-side map that would
        # grow one entry per request forever): the request's lifetime IS
        # the mapping's lifetime
        req._router_replica = replica
        return req

    def replica_for(self, req):
        replica = getattr(req, "_router_replica", None)
        if replica is None:
            raise KeyError(f"request {req.request_id} not routed here")
        return replica

    def failover_target(self, exclude: str | None = None):
        """A healthy decode-capable replica to resume a failed request on
        (serving/failover.py, docs/failover.md): least-outstanding among
        healthy serving replicas, preferring any replica other than
        ``exclude`` — but allowing ``exclude`` itself when it is the only
        healthy one left (an injected transient crash leaves the engine
        alive and able to take its own requests back). None = no healthy
        replica; the caller surfaces the error honestly."""
        healthy = self._candidates(self._serving)
        pool = [r for r in healthy if r.name != exclude] or healthy
        if not pool:
            return None
        return min(pool, key=lambda r: (self._effective_load(r), r.name))

    def stream(self, req):
        """Stream ``req``'s pieces with in-flight failover: a replica
        dying mid-stream (terminal ``error``) is checkpoint-resumed on a
        healthy peer and the stream continues token-identically — the
        consumer never sees the seam (serving/failover.py)."""
        from ..serving import failover as _failover

        yield from _failover.stream_with_failover(self, req)

    def abort(self, req) -> None:
        self.replica_for(req).abort(req)

    def stats(self) -> dict:
        with self._lock:
            hits, fallbacks, keys, readmissions = (
                self.affinity_hits, self.fallbacks, len(self._seen),
                self.readmissions,
            )
            down = dict(self._down)
            weights = dict(self._weights)

        def one(r) -> dict:
            # EngineReplica grows a stats() with watermark last-progress
            # fields (docs/health.md); bare duck-typed replicas keep the
            # legacy shape
            base = (
                r.stats()
                if hasattr(r, "stats")
                else {
                    "role": getattr(r, "role", "unified"),
                    "outstanding": r.outstanding(),
                    "healthy": r.healthy(),
                    "saturated": r.saturated(),
                }
            )
            base["down"] = r.name in down
            base["weight"] = weights.get(r.name, 1.0)
            return base

        return {
            "replicas": {r.name: one(r) for r in self.replicas},
            "affinity_hits": hits,
            "fallbacks": fallbacks,
            "readmissions": readmissions,
            "keys_tracked": keys,
        }
