"""Scheduler policies: the pluggable queue behind the engine's admission.

``LLMEngine`` used to pop one unbounded FIFO — a bulk batch job could starve
interactive chat traffic indefinitely (the vLLM/TGI comparative study's
finding: scheduling policy, not kernels, dominates tail latency under
contention). A :class:`SchedulerPolicy` owns the waiting set instead and the
engine asks it for the next admission batch.

Two levels of differentiation:

- **priority classes** — ``interactive`` > ``default`` > ``batch``, strict:
  a class is only served when every higher class is empty. Batch work is
  throughput filler by definition; its starvation under sustained
  interactive load is the documented trade-off (admission bounds its queue,
  so callers see fast 429s, not unbounded waits).
- **tenant fair share** — within a class, tenants are served by weighted
  deficit round robin (DRR) over their *cost* (estimated KV pages), so one
  tenant's flood of heavyweight prompts can't crowd out another tenant in
  the same class. Weights default to 1; ``tenant_weights`` skews capacity.

Policies are synchronized internally (client threads submit, the scheduler
thread pops) and take an injectable ``clock`` so deadline behavior is
testable with a fake clock, deterministically.
"""

from __future__ import annotations

import abc
import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Callable

#: priority classes, highest first — the order IS the strict service order.
#: "canary" is the synthetic golden-set probe class (observability/canary.py):
#: lowest rank so probes never starve real traffic, excluded from autoscaler
#: signals and per-tenant usage billing.
PRIORITY_CLASSES = ("interactive", "default", "batch", "canary")
DEFAULT_CLASS = "default"
#: class -> rank (lower serves first); shared by the executor's pool
CLASS_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


def validate_class(name: str) -> str:
    """Return ``name`` if it is a known priority class, else raise — servers
    call this up front so a typo'd class is a 400, not silent ``default``."""
    if name not in CLASS_RANK:
        raise ValueError(
            f"unknown priority class {name!r}; known: {PRIORITY_CLASSES}"
        )
    return name


@dataclasses.dataclass
class ScheduledRequest:
    """One queued unit of work: the engine's ``Request`` (or any payload)
    plus everything the policy and admission layers decide on."""

    payload: object
    priority: str = DEFAULT_CLASS
    tenant: str = "default"
    #: estimated cost in KV pages (admission fills it in); DRR charges it
    cost: int = 1
    #: absolute deadline in the policy's clock domain; None = no deadline
    deadline: float | None = None
    enqueued_at: float = 0.0


class SchedulerPolicy(abc.ABC):
    """The full waiting-set contract the engine schedules against.

    Every method is required — a policy that can't remove or expire entries
    would silently leak aborted/deadline-expired requests, so partial
    implementations are rejected by the ABC machinery (and a static guard in
    ``tests/test_static.py`` asserts no concrete subclass ships with
    abstract methods remaining).
    """

    def __init__(self, *, clock: Callable[[], float] | None = None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()

    @abc.abstractmethod
    def submit(self, entry: ScheduledRequest) -> None:
        """Enqueue one entry (stamps ``enqueued_at`` if unset)."""

    @abc.abstractmethod
    def next_batch(self, max_n: int) -> list[ScheduledRequest]:
        """Pop up to ``max_n`` entries in service order."""

    @abc.abstractmethod
    def requeue(self, entries: list[ScheduledRequest]) -> None:
        """Preemption-safe return: put popped-but-unscheduled entries back
        at the FRONT of their queues, original order preserved, without
        re-charging their fair-share cost."""

    @abc.abstractmethod
    def remove(self, entry: ScheduledRequest) -> bool:
        """Remove one queued entry (abort path). False = already popped."""

    @abc.abstractmethod
    def expired(self, now: float | None = None) -> list[ScheduledRequest]:
        """Remove and return every queued entry whose deadline has passed."""

    @abc.abstractmethod
    def depths(self) -> dict[str, int]:
        """Queued entries per priority class (every class always present)."""

    # -- shared conveniences (concrete; built on the ABC surface) -----------

    def total_depth(self) -> int:
        return sum(self.depths().values())

    def oldest_enqueued_at(self) -> float | None:
        """``enqueued_at`` of the oldest queued entry, or None when empty —
        the queue-age-head progress watermark (serving/health.py): a head
        that only ever gets older while the scheduler keeps ticking is a
        gray failure the depth gauges cannot see. Concrete subclasses
        override with an O(depth) scan; the default None opts a custom
        policy out of the signal rather than breaking it."""
        return None

    def drain(self) -> list[ScheduledRequest]:
        """Pop everything (engine stop/release path)."""
        out: list[ScheduledRequest] = []
        while True:
            batch = self.next_batch(1024)
            if not batch:
                return out
            out.extend(batch)


class FIFOPolicy(SchedulerPolicy):
    """The pre-scheduler behavior: one global FIFO, classes ignored for
    ordering (still tracked for depth gauges). The baseline policy for
    A/B-ing fairness changes."""

    def __init__(self, *, clock: Callable[[], float] | None = None):
        super().__init__(clock=clock)
        self._queue: deque[ScheduledRequest] = deque()

    def submit(self, entry: ScheduledRequest) -> None:
        with self._lock:
            if not entry.enqueued_at:
                entry.enqueued_at = self._clock()
            self._queue.append(entry)

    def next_batch(self, max_n: int) -> list[ScheduledRequest]:
        out: list[ScheduledRequest] = []
        with self._lock:
            while self._queue and len(out) < max_n:
                out.append(self._queue.popleft())
        return out

    def requeue(self, entries: list[ScheduledRequest]) -> None:
        with self._lock:
            for e in reversed(entries):
                self._queue.appendleft(e)

    def remove(self, entry: ScheduledRequest) -> bool:
        with self._lock:
            try:
                self._queue.remove(entry)
                return True
            except ValueError:
                return False

    def expired(self, now: float | None = None) -> list[ScheduledRequest]:
        now = self._clock() if now is None else now
        with self._lock:
            out = [
                e for e in self._queue
                if e.deadline is not None and now >= e.deadline
            ]
            for e in out:
                self._queue.remove(e)
        return out

    def depths(self) -> dict[str, int]:
        with self._lock:
            d = {c: 0 for c in PRIORITY_CLASSES}
            for e in self._queue:
                d[e.priority] = d.get(e.priority, 0) + 1
            return d

    def oldest_enqueued_at(self) -> float | None:
        with self._lock:
            return min(
                (e.enqueued_at for e in self._queue if e.enqueued_at),
                default=None,
            )


class FairSharePolicy(SchedulerPolicy):
    """Strict class priority + weighted deficit round robin across tenants.

    Per (class, tenant) FIFO queues. ``next_batch`` serves classes in
    :data:`PRIORITY_CLASSES` order; within a class it cycles tenants in
    first-seen order, crediting each visit ``quantum * weight`` cost units
    of deficit and popping entries while the head's cost fits — the
    classic DRR guarantee that long-run service is proportional to weight
    regardless of per-request cost. A tenant's deficit resets when its
    queue empties (no hoarding credit while idle).
    """

    def __init__(
        self,
        *,
        clock: Callable[[], float] | None = None,
        tenant_weights: dict[str, float] | None = None,
        quantum: int = 4,
    ):
        super().__init__(clock=clock)
        #: class -> tenant -> deque (OrderedDict keeps tenant visit order
        #: deterministic: first submission order)
        self._queues: dict[str, OrderedDict[str, deque]] = {
            c: OrderedDict() for c in PRIORITY_CLASSES
        }
        self._deficit: dict[tuple[str, str], float] = {}
        self.tenant_weights = dict(tenant_weights or {})
        self.quantum = max(1, int(quantum))

    def _weight(self, tenant: str) -> float:
        return max(0.01, float(self.tenant_weights.get(tenant, 1.0)))

    def submit(self, entry: ScheduledRequest) -> None:
        validate_class(entry.priority)
        with self._lock:
            if not entry.enqueued_at:
                entry.enqueued_at = self._clock()
            q = self._queues[entry.priority].setdefault(entry.tenant, deque())
            q.append(entry)

    def next_batch(self, max_n: int) -> list[ScheduledRequest]:
        out: list[ScheduledRequest] = []
        with self._lock:
            for cls in PRIORITY_CLASSES:
                tenants = self._queues[cls]
                while len(out) < max_n and any(tenants.values()):
                    for tenant in list(tenants):
                        q = tenants[tenant]
                        if not q:
                            del tenants[tenant]
                            continue
                        key = (cls, tenant)
                        self._deficit[key] = self._deficit.get(key, 0.0) + (
                            self.quantum * self._weight(tenant)
                        )
                        while (
                            q
                            and len(out) < max_n
                            and q[0].cost <= self._deficit[key]
                        ):
                            e = q.popleft()
                            self._deficit[key] -= e.cost
                            out.append(e)
                        if not q:
                            # idle tenants don't hoard credit
                            self._deficit.pop(key, None)
                            del tenants[tenant]
                        if len(out) >= max_n:
                            break
                if len(out) >= max_n:
                    break
        return out

    def requeue(self, entries: list[ScheduledRequest]) -> None:
        with self._lock:
            for e in reversed(entries):
                tenants = self._queues[e.priority]
                q = tenants.get(e.tenant)
                if q is None:
                    q = deque()
                    tenants[e.tenant] = q
                    tenants.move_to_end(e.tenant, last=False)
                q.appendleft(e)
                # refund the DRR charge: the entry was never actually served
                key = (e.priority, e.tenant)
                self._deficit[key] = self._deficit.get(key, 0.0) + e.cost

    def remove(self, entry: ScheduledRequest) -> bool:
        with self._lock:
            q = self._queues[entry.priority].get(entry.tenant)
            if q is None:
                return False
            try:
                q.remove(entry)
                return True
            except ValueError:
                return False

    def expired(self, now: float | None = None) -> list[ScheduledRequest]:
        now = self._clock() if now is None else now
        out: list[ScheduledRequest] = []
        with self._lock:
            for tenants in self._queues.values():
                for q in tenants.values():
                    dead = [
                        e for e in q
                        if e.deadline is not None and now >= e.deadline
                    ]
                    for e in dead:
                        q.remove(e)
                    out.extend(dead)
        return out

    def depths(self) -> dict[str, int]:
        with self._lock:
            return {
                c: sum(len(q) for q in tenants.values())
                for c, tenants in self._queues.items()
            }

    def oldest_enqueued_at(self) -> float | None:
        with self._lock:
            oldest = None
            for tenants in self._queues.values():
                for q in tenants.values():
                    for e in q:
                        if e.enqueued_at and (
                            oldest is None or e.enqueued_at < oldest
                        ):
                            oldest = e.enqueued_at
            return oldest
