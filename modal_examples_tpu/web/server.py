"""``@app.server`` — raw-port, low-latency serving with regional routing.

Reference spec: ``@app.server(port=8000, routing_region=..., compute_region=...,
target_concurrency=100, startup_timeout=..., exit_grace_period=...,
unauthenticated=True)`` decorating a class whose ``@modal.enter`` starts an
HTTP server on ``port`` (vllm_inference.py:139-209, 07_web/server.py:49-60);
the replica is advertised only once the port accepts connections
(vllm_inference.py:127-128). Sticky routing via rendezvous hashing
(server_sticky.py:16-27) is modeled by the ``sticky_header`` option.

Locally the decorated class becomes a Cls whose single container runs the
user's server; ``serve()`` boots it, waits for port readiness, and publishes
the URL.
"""

from __future__ import annotations

import time
from typing import Callable

from . import registry
from .gateway import wait_for_port


class ServerHandle:
    """Deployed-server handle: boot, readiness, URL."""

    def __init__(self, cls_handle, cfg: dict):
        self._cls = cls_handle
        self.cfg = cfg
        self._obj = None

    @property
    def port(self) -> int:
        return self.cfg["port"]

    def serve(self, wait_ready: bool = True) -> str:
        """Boot one replica (runs @enter hooks, which start the server)."""
        if self._obj is None:
            self._obj = self._cls()
            # Booting = creating the pool with a warm container. Submitting a
            # no-op readiness method forces container boot + enter hooks.
            pool = self._obj._pool()
            if hasattr(pool, "_ensure_target"):  # inline backend
                pool._ensure_target()
            else:
                pool.spec.min_containers = max(1, pool.spec.min_containers)
                pool._autoscale(time.monotonic())
        url = f"http://127.0.0.1:{self.port}"
        if wait_ready:
            ok = wait_for_port(
                "127.0.0.1", self.port, self.cfg.get("startup_timeout", 60.0)
            )
            if not ok:
                raise TimeoutError(
                    f"server on port {self.port} not ready after "
                    f"{self.cfg.get('startup_timeout', 60.0)}s"
                )
        registry.publish(self._cls._spec.tag, url)
        return url

    def stop(self) -> None:
        if self._obj is not None:
            self._obj._pool().shutdown()
            self._obj = None

    def get_web_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def make_server_decorator(
    app,
    *,
    port: int,
    tpu=None,
    image=None,
    volumes=None,
    secrets=None,
    startup_timeout: float = 60.0,
    target_concurrency: int | None = None,
    routing_region: str | None = None,
    compute_region: str | None = None,
    exit_grace_period: float | None = None,
    unauthenticated: bool = False,
    scaledown_window: float = 300.0,
    max_containers: int = 1,
    timeout: float | None = None,
    sticky_header: str | None = None,
    **kw,
) -> Callable:
    cfg = {
        "port": port,
        "startup_timeout": startup_timeout,
        "target_concurrency": target_concurrency,
        "routing_region": routing_region,
        "compute_region": compute_region,
        "exit_grace_period": exit_grace_period,
        "unauthenticated": unauthenticated,
        "sticky_header": sticky_header,
    }

    def deco(user_cls: type) -> ServerHandle:
        cls_handle = app.cls(
            tpu=tpu,
            image=image,
            volumes=volumes,
            secrets=secrets,
            scaledown_window=scaledown_window,
            max_containers=max_containers,
            timeout=timeout,
        )(user_cls)
        cls_handle._spec.web = {"type": "server", **cfg}
        handle = ServerHandle(cls_handle, cfg)
        if not hasattr(app, "registered_servers"):
            app.registered_servers = {}
        app.registered_servers[user_cls.__name__] = handle
        return handle

    return deco
