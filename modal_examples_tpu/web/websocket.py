"""Minimal RFC 6455 websocket server support for the stdlib web gateway.

The reference's streaming-ASR tier serves browser microphones over
websockets through fastapi (/root/reference/06_gpu_and_ml/speech-to-text/
streaming_kyutai_stt.py, streaming_parakeet.py — websocket endpoints that
stream partial transcripts back while audio chunks arrive). fastapi/uvicorn
are optional in this image, so the gateway implements the protocol
directly: handshake (Sec-WebSocket-Accept), frame codec (text/binary/
ping/pong/close, client masking), and a blocking ``WebSocket`` connection
object handlers use as ``ws.receive()`` / ``ws.send_text()``.

Server frames are unmasked, client frames must be masked (RFC 6455 §5.1 —
both enforced). Fragmented messages are reassembled; pings are answered
inline. No extensions/subprotocols (not needed by the workloads).
"""

from __future__ import annotations

import base64
import hashlib
import socket
import struct

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def build_frame(opcode: int, payload: bytes, *, fin: bool = True) -> bytes:
    """Server-to-client frame (unmasked)."""
    head = bytes([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < 1 << 16:
        head += bytes([126]) + struct.pack("!H", n)
    else:
        head += bytes([127]) + struct.pack("!Q", n)
    return head + payload


class ConnectionClosed(Exception):
    """Peer closed (or the socket died); carries the close code."""

    def __init__(self, code: int = 1005):
        self.code = code
        super().__init__(f"websocket closed (code={code})")


class WebSocket:
    """Blocking connection; one handler thread per socket.

    Server side by default (unmasked sends, requires masked receives);
    ``client=True`` flips both directions per RFC 6455 §5.1."""

    #: total assembled-message cap (close 1009 beyond it): the gateway
    #: buffers one message per handler thread, so this bounds per-client
    #: memory the way the reference's ASGI servers cap request bodies
    MAX_MESSAGE_BYTES = 32 * 1024 * 1024

    def __init__(self, sock: socket.socket, *, client: bool = False):
        self._sock = sock
        self._buf = b""
        self.closed = False
        self._client = client

    # -- receive ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                self.closed = True
                raise ConnectionClosed(1006)
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_frame(self) -> tuple[int, bool, bytes]:
        b1, b2 = self._read_exact(2)
        fin = bool(b1 & 0x80)
        opcode = b1 & 0x0F
        masked = bool(b2 & 0x80)
        n = b2 & 0x7F
        if n == 126:
            (n,) = struct.unpack("!H", self._read_exact(2))
        elif n == 127:
            (n,) = struct.unpack("!Q", self._read_exact(8))
        if n > self.MAX_MESSAGE_BYTES:
            # enforce on the DECLARED length before buffering the payload —
            # checking the assembled message only would let one huge frame
            # grow the buffer unbounded first
            self.close(1009)
            raise ConnectionClosed(1009)
        if self._client:
            # server frames are unmasked (a masked one is a protocol error
            # we tolerate by unmasking anyway)
            if masked:
                mask = self._read_exact(4)
                payload = bytearray(self._read_exact(n))
                for i in range(n):
                    payload[i] ^= mask[i % 4]
                return opcode, fin, bytes(payload)
            return opcode, fin, self._read_exact(n)
        if not masked:
            # RFC 6455 §5.1: a server MUST close on unmasked client frames
            self.close(1002)
            raise ConnectionClosed(1002)
        mask = self._read_exact(4)
        payload = bytearray(self._read_exact(n))
        for i in range(n):
            payload[i] ^= mask[i % 4]
        return opcode, fin, bytes(payload)

    def receive(self) -> tuple[str, bytes]:
        """Next complete message -> ("text" | "binary", payload).

        Control frames are handled inline; raises ConnectionClosed on
        close/EOF.
        """
        message = b""
        msg_op = None
        while True:
            opcode, fin, payload = self._read_frame()
            if opcode == OP_PING:
                self._send_raw(self._frame(OP_PONG, payload))
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                code = (
                    struct.unpack("!H", payload[:2])[0]
                    if len(payload) >= 2 else 1005
                )
                if not self.closed:
                    self._send_raw(self._frame(OP_CLOSE, payload[:2]))
                    self.closed = True
                raise ConnectionClosed(code)
            if opcode in (OP_TEXT, OP_BINARY):
                if msg_op is not None:
                    # RFC 6455 §5.4: a new data frame while a fragmented
                    # message is open is a protocol violation
                    self.close(1002)
                    raise ConnectionClosed(1002)
                msg_op = opcode
                message = payload
            elif opcode == OP_CONT:
                if msg_op is None:
                    # continuation with no message in progress: without
                    # this check a malicious client could grow `message`
                    # unboundedly in the gateway process
                    self.close(1002)
                    raise ConnectionClosed(1002)
                message += payload
            else:
                # RFC 6455 §5.2: reserved opcodes fail the connection —
                # falling through could return a truncated fragmented
                # message as complete
                self.close(1002)
                raise ConnectionClosed(1002)
            if len(message) > self.MAX_MESSAGE_BYTES:
                self.close(1009)  # message too big
                raise ConnectionClosed(1009)
            if fin and msg_op is not None:
                kind = "text" if msg_op == OP_TEXT else "binary"
                return kind, message

    # -- send ---------------------------------------------------------------

    def _send_raw(self, data: bytes) -> None:
        try:
            self._sock.sendall(data)
        except OSError as e:
            self.closed = True
            raise ConnectionClosed(1006) from e

    def _frame(self, opcode: int, payload: bytes) -> bytes:
        if self._client:
            return build_masked_frame(opcode, payload)
        return build_frame(opcode, payload)

    def send_text(self, text: str) -> None:
        self._send_raw(self._frame(OP_TEXT, text.encode()))

    def send_bytes(self, data: bytes) -> None:
        self._send_raw(self._frame(OP_BINARY, data))

    def send_json(self, obj) -> None:
        import json

        self.send_text(json.dumps(obj))

    def close(self, code: int = 1000) -> None:
        if not self.closed:
            self.closed = True
            try:
                self._sock.sendall(
                    self._frame(OP_CLOSE, struct.pack("!H", code))
                )
            except OSError:
                pass


def perform_handshake(handler) -> WebSocket | None:
    """Upgrade an http.server request to a websocket; returns the live
    connection, or None (400 sent) when the upgrade headers are invalid."""
    key = handler.headers.get("Sec-WebSocket-Key")
    if not key:
        # the gateway already routed only Upgrade: websocket requests here
        # (426 otherwise); a missing key is a malformed handshake
        handler.send_response(400)
        handler.end_headers()
        handler.wfile.write(b"missing Sec-WebSocket-Key")
        return None
    # RFC 6455 requires the handshake over HTTP/1.1; http.server's default
    # protocol_version writes an HTTP/1.0 status line, which real browsers
    # reject ("Error during WebSocket handshake")
    handler.protocol_version = "HTTP/1.1"
    handler.send_response(101, "Switching Protocols")
    handler.send_header("Upgrade", "websocket")
    handler.send_header("Connection", "Upgrade")
    handler.send_header("Sec-WebSocket-Accept", accept_key(key))
    handler.end_headers()
    handler.wfile.flush()
    return WebSocket(handler.connection)


def build_masked_frame(opcode: int, payload: bytes, *, fin: bool = True) -> bytes:
    """Client-to-server frame (masked, RFC 6455 §5.1)."""
    import os as _os

    head = bytes([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([0x80 | n])
    elif n < 1 << 16:
        head += bytes([0x80 | 126]) + struct.pack("!H", n)
    else:
        head += bytes([0x80 | 127]) + struct.pack("!Q", n)
    mask = _os.urandom(4)
    body = bytearray(payload)
    for i in range(n):
        body[i] ^= mask[i % 4]
    return head + mask + bytes(body)


def connect(
    host: str,
    port: int,
    path: str = "/",
    timeout: float = 30.0,
    read_timeout: float | None = None,
) -> WebSocket:
    """Minimal client: TCP connect + upgrade handshake -> WebSocket
    (client mode: masked sends). ``timeout`` bounds the connect+handshake;
    ``read_timeout`` (default None = block forever) applies afterwards —
    a server may legitimately go >30 s between frames (e.g. first-request
    JIT compilation), which must not kill a healthy stream."""
    key = base64.b64encode(hashlib.sha1(str(id(object())).encode()).digest()[:16]).decode()
    sock = socket.create_connection((host, port), timeout=timeout)
    req = (
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}:{port}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n\r\n"
    )
    sock.sendall(req.encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionClosed(1006)
        buf += chunk
    head, rest = buf.split(b"\r\n\r\n", 1)
    status = head.split(b"\r\n", 1)[0]
    if b"101" not in status:
        raise ConnectionError(f"handshake rejected: {status.decode(errors='replace')}")
    want = accept_key(key).encode()
    if want not in head:
        raise ConnectionError("bad Sec-WebSocket-Accept")
    sock.settimeout(read_timeout)
    ws = WebSocket(sock, client=True)
    ws._buf = rest
    return ws
