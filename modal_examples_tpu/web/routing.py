"""Request routing: rendezvous (highest-random-weight) hashing.

Reference parity: 07_web/server_sticky.py:16-27 routes each session key to a
stable replica via rendezvous hashing so stateful servers (KV caches,
sessions) see consistent traffic; replicas joining/leaving only move the
keys they own. ``@app.server(sticky_header=...)`` uses this to pick the
replica for a request.
"""

from __future__ import annotations

import hashlib


def _weight(key: str, node: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(f"{key}\x00{node}".encode(), digest_size=8).digest(), "big"
    )


def rendezvous_pick(key: str, nodes: list[str]) -> str:
    """The node owning ``key``: argmax over hash(key, node)."""
    if not nodes:
        raise ValueError("no nodes to route to")
    return max(nodes, key=lambda n: _weight(key, n))


def rendezvous_rank(key: str, nodes: list[str]) -> list[str]:
    """All nodes ordered by preference for ``key`` (failover order)."""
    return sorted(nodes, key=lambda n: _weight(key, n), reverse=True)
