"""Web endpoint decorators — HTTP/ASGI/WSGI wrappers over Functions.

Reference spec: ``@modal.fastapi_endpoint(docs=True)`` (basic_web.py:43-46),
``@modal.asgi_app`` (text_to_image.py:239), ``@modal.wsgi_app``
(torch_profiling.py:301), ``@modal.web_server(port)`` (pushgateway.py:66),
``f.get_web_url()`` (text_to_image.py:254).

These decorators attach web metadata under the ``@app.function`` / ``@app.cls``
decorator; ``tpurun serve`` turns the registrations into live servers:

- ``fastapi_endpoint`` — if fastapi is installed, the function becomes a
  FastAPI route; otherwise our stdlib JSON gateway (web.gateway) serves it.
- ``asgi_app`` / ``wsgi_app`` — the function *returns* an ASGI/WSGI app which
  is hosted in-container.
- ``web_server(port)`` — the function starts its own server on ``port``
  (subprocess or thread); the gateway proxies/publishes that port.
"""

from __future__ import annotations

from typing import Callable


def _mark(kind: str, **cfg) -> Callable:
    def deco(fn):
        fn.__mtpu_web__ = {"type": kind, **cfg}
        return fn

    return deco


def fastapi_endpoint(
    *,
    method: str = "GET",
    label: str | None = None,
    docs: bool = False,
    custom_domains: list[str] | None = None,
    requires_proxy_auth: bool = False,
) -> Callable:
    return _mark("fastapi_endpoint", method=method.upper(), label=label, docs=docs)


# modal's deprecated spelling, still used by some reference examples
web_endpoint = fastapi_endpoint


def asgi_app(*, label: str | None = None, custom_domains: list[str] | None = None) -> Callable:
    return _mark("asgi_app", label=label)


def wsgi_app(*, label: str | None = None, custom_domains: list[str] | None = None) -> Callable:
    return _mark("wsgi_app", label=label)


def web_server(
    port: int, *, startup_timeout: float = 30.0, label: str | None = None
) -> Callable:
    return _mark("web_server", port=port, startup_timeout=startup_timeout, label=label)


def websocket_endpoint(*, label: str | None = None) -> Callable:
    """Websocket handler: ``fn(ws, **query_params)`` receives a live
    ``web.websocket.WebSocket`` (blocking receive/send) after the RFC 6455
    handshake. The reference's streaming-ASR tier serves this shape via
    fastapi websockets (streaming_kyutai_stt.py); here the stdlib gateway
    speaks the protocol itself. Handlers run in the gateway process (a
    live socket cannot cross the container boundary) — keep them thin and
    call ``.remote`` for heavy work, or keep model state in the module.
    """
    return _mark("websocket_endpoint", label=label)
