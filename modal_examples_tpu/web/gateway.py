"""Local web gateway: hosts an app's web endpoints over HTTP.

This is the local analog of the reference platform's web proxy in front of
``@modal.fastapi_endpoint`` / ``@modal.asgi_app`` / ``@modal.wsgi_app`` /
``@modal.web_server`` functions (07_web/*, SURVEY.md L6). fastapi/uvicorn are
optional: the gateway is stdlib ``http.server`` and dispatches requests into
the same container pools as ``.remote`` calls, so web traffic exercises the
exact same scheduling path (autoscaling, @concurrent, @batched) as RPC
traffic. Generator functions stream as ``text/event-stream`` (SSE), matching
07_web/streaming.py:38-45.
"""

from __future__ import annotations

import inspect
import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import registry
from ..scheduling.admission import ShedError as _ShedError
from ..utils.log import get_logger

_log = get_logger("gateway")

#: every built-in observability surface the gateway serves: route label ->
#: one-line description. ONE table shared by the dispatch check, the ``/``
#: root index payload, and the endpoint smoke-matrix test — so a new
#: surface cannot land without being discoverable (and a dropped one
#: cannot linger in the index). ``/metrics`` is prometheus text; every
#: other route answers JSON.
BUILTIN_ROUTES: dict[str, str] = {
    "healthz": "SLO pass/fail gate + burn rates",
    "health": "gray-failure watchdog: per-replica progress classification",
    "metrics": "prometheus exposition (live registry + pushed jobs)",
    "alerts": "alert-rule firing state + fire/clear history",
    "incidents": "incident-bundle index (/incidents/<id>[?file=NAME])",
    "usage": "per-tenant usage meters + roofline MFU/MBU",
    "prefixstore": "shared prefix-store dedup/hit-origin/takeover counters",
    "profile": "hot-path profiler: tick phases, host fraction, compiles",
    "traces": "request/call trace index (/traces/<id>[?explain=1])",
    "fleet": "fleet autoscaler: replicas, decisions, boot latencies",
    "disagg": "disaggregated serving: roles, migrations, prefix tiers",
    "chaos": "injected-fault counters + chaos episode journal",
    "canary": "correctness canary: golden-set probe results + drift",
    "autoscaler": "executor autoscaler decision journal",
}


def _coerce_kwargs(fn, raw: dict) -> dict:
    """Coerce string query params to the entrypoint's annotated types."""
    sig = inspect.signature(fn)
    out = {}
    for name, value in raw.items():
        param = sig.parameters.get(name)
        if param is None:
            out[name] = value
            continue
        ann = param.annotation
        try:
            if ann is int:
                value = int(value)
            elif ann is float:
                value = float(value)
            elif ann is bool:
                value = str(value).lower() in ("1", "true", "yes", "on")
        except (TypeError, ValueError):
            pass
        out[name] = value
    return out


def _disagg_snapshot() -> dict:
    """Disaggregated-serving snapshot from the process registry: replica
    roles, migration counters/latency, and prefix-tier occupancy + hits —
    the ``/disagg`` route's payload (``tpurun disagg`` renders the same
    series from pushed metrics)."""
    from ..observability import catalog as C
    from ..utils.prometheus import default_registry as reg

    roles = {
        labels.get("replica", "?"): labels.get("role", "?")
        for labels, _v in reg.series(C.REPLICA_ROLE)
    }
    by_result = {
        labels.get("result", "?"): v
        for labels, v in reg.series(C.DISAGG_MIGRATIONS_TOTAL)
    }
    tiers: dict = {}
    for labels, v in reg.series(C.PREFIX_TIER_PAGES):
        tiers.setdefault(labels.get("tier", "?"), {})["pages"] = v
    for labels, v in reg.series(C.PREFIX_TIER_BYTES):
        tiers.setdefault(labels.get("tier", "?"), {})["bytes"] = v
    hits = {
        labels.get("tier", "?"): v
        for labels, v in reg.series(C.PREFIX_TIER_HITS_TOTAL)
    }
    return {
        "replicas": roles,
        "migrations": {
            "by_result": by_result,
            "inflight": reg.value(C.DISAGG_MIGRATIONS_INFLIGHT),
            "pages": reg.total(C.DISAGG_PAGES_MIGRATED_TOTAL),
            "bytes": reg.total(C.DISAGG_MIGRATION_BYTES_TOTAL),
            "latency": reg.histogram_quantiles(C.DISAGG_MIGRATION_SECONDS),
        },
        "tiers": {"occupancy": tiers, "hits": hits},
    }


def _fleet_snapshot(last: int = 20) -> dict:
    """Fleet-autoscaler snapshot: replica counts by role and decision
    counters from the process registry, boot-latency quantiles by kind
    (warm snapshot-restore vs cold init), plus the newest records from the
    fleet decision journal — the ``/fleet`` route's payload (``tpurun
    fleet`` renders the same data from pushed metrics; docs/fleet.md)."""
    from ..observability import catalog as C
    from ..observability.journal import named_journal
    from ..utils.prometheus import default_registry as reg

    replicas = {
        labels.get("role", "?"): v
        for labels, v in reg.series(C.FLEET_REPLICAS)
    }
    decisions: dict = {}
    for labels, v in reg.series(C.FLEET_DECISIONS_TOTAL):
        action = labels.get("action", "?")
        decisions.setdefault(action, {})[labels.get("trigger", "?")] = v
    boots = {
        boot: reg.histogram_quantiles(
            C.FLEET_BOOT_SECONDS, aggregate={"boot": boot}
        )
        for boot in ("warm", "cold")
    }
    journal = named_journal("fleet").tail(last)
    return {
        "replicas": replicas,
        "decisions": decisions,
        "boot_seconds": {k: v for k, v in boots.items() if v},
        "journal": journal,
    }


def _health_snapshot(last: int = 20) -> dict:
    """Gray-failure watchdog snapshot: per-replica classification +
    progress-age watermarks (one-hot ``mtpu_watchdog_replica_state`` +
    ``mtpu_watchdog_progress_age_seconds`` from the live registry), ladder
    transition/recovery counters, and the newest watchdog ladder decisions
    from ``<state_dir>/watchdog.jsonl`` — the ``/health`` route's payload
    (``tpurun health`` renders the same data from pushed metrics;
    docs/health.md). Distinct from ``/healthz``: that is the SLO pass/fail
    gate; this is the per-replica progress detail view."""
    from ..observability.journal import named_journal
    from ..serving.health import decode_watchdog_series
    from ..utils.prometheus import default_registry as reg

    wd = decode_watchdog_series(reg)
    journal = named_journal("watchdog").tail(last)
    return {
        "replicas": {
            name: {"state": state, "progress_age_s": wd["ages"].get(name)}
            for name, state in wd["states"].items()
        },
        "transitions": wd["transitions"],
        "recoveries": wd["recoveries"],
        "journal": journal,
    }


def _chaos_snapshot(last: int = 10) -> dict:
    """Chaos-harness snapshot: injected-fault counters per catalog point
    (live registry) plus the newest episode records from the chaos journal
    — the ``/chaos`` route's payload (``tpurun chaos`` renders the same
    data from pushed metrics + the journal; docs/faults.md)."""
    from ..observability import catalog as C
    from ..observability.journal import named_journal
    from ..utils.prometheus import default_registry as reg

    injected = {
        labels.get("point", "?"): v
        for labels, v in reg.series(C.FAULTS_INJECTED_TOTAL)
    }
    episodes = named_journal("chaos").tail(last)
    return {
        "injected": injected,
        "injected_total": sum(injected.values()),
        "router_readmissions": reg.total(C.ROUTER_READMISSIONS_TOTAL),
        "episodes": episodes,
        "wedged": sum(int(e.get("wedged", 0)) for e in episodes),
    }


def _prefixstore_snapshot(last: int = 10) -> dict:
    """Shared prefix-store snapshot: fleet-wide dedup/hit/takeover
    counters (live registry) plus the newest ownership records from the
    ``prefix_store`` journal — the ``/prefixstore`` route's payload
    (``tpurun prefixstore`` renders the same data from pushed metrics +
    the journal; docs/prefix_store.md)."""
    from ..observability import catalog as C
    from ..observability.journal import named_journal
    from ..utils.prometheus import default_registry as reg

    hits = {
        labels.get("origin", "?"): v
        for labels, v in reg.series(C.PREFIX_STORE_HITS_TOTAL)
    }
    return {
        "hits": hits,
        "hits_total": sum(hits.values()),
        "misses": reg.total(C.PREFIX_STORE_MISSES_TOTAL),
        "dedup_ratio": reg.total(C.PREFIX_STORE_DEDUP_RATIO),
        "bytes": reg.total(C.PREFIX_STORE_BYTES),
        "owner_takeovers": reg.total(C.PREFIX_STORE_OWNER_TAKEOVERS_TOTAL),
        "journal": named_journal("prefix_store").tail(last),
    }


def _alerts_snapshot(last: int = 20) -> dict:
    """Alert-rule snapshot: per-rule firing state — from the live
    evaluator when this process runs the tsdb sampler, else a one-shot
    evaluation over the on-disk window — plus the newest fire/clear
    transitions from the ``alerts`` journal; the ``/alerts`` route's
    payload (``tpurun alerts`` renders the same data;
    docs/observability.md#alert-rules)."""
    from ..observability import alerts as _alerts
    from ..observability import timeseries as _ts

    sampler = _ts.global_sampler()
    ev = sampler.evaluator if sampler is not None else None
    # a sampler built with evaluate_alerts=False has no evaluator: fall
    # through to the one-shot offline evaluation below
    if ev is not None:
        rules = ev.snapshot()
        active = ev.active()
    else:
        rules = _alerts.evaluate_offline(_ts.read_window())
        active = [r["rule"] for r in rules if r["firing"]]
    return {
        "rules": rules,
        "active": active,
        "live_evaluator": ev is not None,
        "history": _alerts.read_alert_journal(last),
    }


def _incidents_snapshot() -> dict:
    """Bundle index — the ``/incidents`` route's payload (``tpurun
    incidents`` renders the same data;
    docs/observability.md#incident-bundles)."""
    from ..observability import incident as _incident

    return {"incidents": _incident.list_incidents()}


def _profile_snapshot(last: int = 20) -> dict:
    """Hot-path profiler snapshot: per-replica overhead summaries + raw
    Perfetto-ready ring/compile snapshots from every live profiler in the
    process, plus the newest compile-ledger records from
    ``<state_dir>/compiles.jsonl`` — the ``/profile`` route's payload
    (``tpurun profile`` renders the same data from pushed metrics + the
    ledger; docs/observability.md#hot-path-profiling). Empty ``replicas``
    means no engine in this process runs with MTPU_PROFILE on."""
    from ..observability import profiler as _prof

    replicas = {}
    for p in _prof.active_profilers():
        replicas[p.replica] = {
            "summary": p.overhead_summary(),
            "perfetto": p.perfetto_snapshot(),
        }
    # the unfinished scan reads a DEEP tail regardless of the display size
    # `last`: 20+ later begin/end pairs (one multi-bucket warmup) would
    # otherwise push the crash-diagnosing begin-without-end row out of the
    # window and the gateway would report no unfinished builds while the
    # ledger still holds the smoking gun
    deep = _prof.read_ledger(n=2000)
    return {
        "replicas": replicas,
        "ledger": deep[-last:] if last else [],
        "unfinished_builds": _prof.unfinished_builds(deep),
    }


def _usage_snapshot(last: int = 10) -> dict:
    """Usage-accounting snapshot: every live engine's per-tenant meters +
    roofline position, plus the newest per-request records from the
    ``usage`` journal — the ``/usage`` route's payload (``tpurun usage``
    renders the same data from pushed metrics + the journal;
    docs/observability.md#roofline-and-usage-accounting)."""
    from ..observability import incident as _incident
    from ..observability import usage as _usage
    from ..observability.journal import named_journal

    engines = {}
    for eng in _incident.live_engines():
        u = getattr(eng, "usage", None)
        if u is None:
            continue
        engines[u.replica] = {"roofline": u.summary(), **u.tenants()}
    records = named_journal("usage").tail(last)
    return {
        "engines": engines,
        "journal_totals": _usage.journal_tenant_totals(records),
        "records": records,
    }


def _canary_snapshot(last: int = 20) -> dict:
    """Correctness-canary snapshot: the live prober's state (when this
    process runs one), per-replica probe/drift counters from the registry,
    and the newest probe-round records from the ``canary`` journal — the
    ``/canary`` route's payload (``tpurun canary`` renders the same data
    from pushed metrics; docs/observability.md#correctness-canary)."""
    from ..observability import canary as _canary
    from ..observability import catalog as C
    from ..observability.journal import named_journal
    from ..utils.prometheus import default_registry as reg

    probes: dict = {}
    for labels, v in reg.series(C.CANARY_PROBES_TOTAL):
        rep = labels.get("replica", "?")
        probes.setdefault(rep, {})[labels.get("result", "?")] = int(v)
    drift = {
        labels.get("replica", "?"): int(v)
        for labels, v in reg.series(C.CANARY_DRIFT_TOTAL)
    }
    failing = {
        labels.get("replica", "?"): int(v)
        for labels, v in reg.series(C.CANARY_FAILING)
    }
    prober = _canary.live_prober()
    return {
        "probes": probes,
        "drift": drift,
        "failing": failing,
        "prober": prober.snapshot() if prober is not None else None,
        "journal": named_journal("canary").tail(last),
    }


def _root_index() -> dict:
    """The ``/`` discovery payload: every built-in observability surface,
    straight from :data:`BUILTIN_ROUTES` so index and dispatch can't drift."""
    return {
        "service": "modal_examples_tpu gateway",
        "routes": {f"/{label}": desc for label, desc in BUILTIN_ROUTES.items()},
    }


class _Handler(BaseHTTPRequestHandler):
    gateway: "Gateway"

    def log_message(self, fmt, *args):  # quiet by default; logs go to stdout
        pass

    def _query_kwargs(self, fn, parsed) -> dict:
        raw = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        return _coerce_kwargs(fn.raw_f, raw)

    def _route(self):
        path = urllib.parse.urlparse(self.path)
        label = path.path.strip("/").split("/")[0]
        return self.gateway.routes.get(label), path

    # -- WSGI/ASGI hosting (the function RETURNS the app; we serve it) ------

    def _read_request(self, parsed) -> tuple[bytes, str]:
        """(body, decoded subpath below the route label)."""
        length = int(self.headers.get("content-length") or 0)
        body = self.rfile.read(length) if length else b""
        raw = "/" + "/".join(parsed.path.strip("/").split("/")[1:])
        return body, urllib.parse.unquote(raw)

    def _send_payload(self, status: int, headers, payload: bytes) -> None:
        self._started_response = True
        self.send_response(status)
        for k, v in headers:
            k = k.decode() if isinstance(k, bytes) else k
            v = v.decode() if isinstance(v, bytes) else v
            if k.lower() != "content-length":
                self.send_header(k, v)
        self.send_header("content-length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_wsgi(self, wsgi_app, parsed, method: str) -> None:
        import io

        body, subpath = self._read_request(parsed)
        environ = {
            "REQUEST_METHOD": method,
            "PATH_INFO": subpath,
            "QUERY_STRING": parsed.query or "",
            "CONTENT_LENGTH": str(len(body)),
            "CONTENT_TYPE": self.headers.get("content-type", ""),
            "SERVER_NAME": self.gateway.host,
            "SERVER_PORT": str(self.gateway.port),
            "SERVER_PROTOCOL": "HTTP/1.1",
            "wsgi.version": (1, 0),
            "wsgi.url_scheme": "http",
            "wsgi.input": io.BytesIO(body),
            "wsgi.errors": io.StringIO(),
            "wsgi.multithread": True,
            "wsgi.multiprocess": False,
            "wsgi.run_once": False,
        }
        for k, v in self.headers.items():
            environ["HTTP_" + k.upper().replace("-", "_")] = v
        status_headers = {}

        def start_response(status, headers, exc_info=None):
            status_headers["status"] = status
            status_headers["headers"] = headers

        result = wsgi_app(environ, start_response)
        try:
            payload = b"".join(result)
        finally:
            if hasattr(result, "close"):  # PEP 3333: server must call close()
                result.close()
        code = int(status_headers["status"].split()[0])
        self._send_payload(code, status_headers["headers"], payload)

    def _serve_asgi(self, asgi_app, parsed, method: str) -> None:
        import asyncio

        body, subpath = self._read_request(parsed)
        scope = {
            "type": "http",
            "asgi": {"version": "3.0"},
            "http_version": "1.1",
            "method": method,
            "path": subpath,
            "raw_path": subpath.encode(),
            "query_string": (parsed.query or "").encode(),
            "headers": [
                (k.lower().encode(), v.encode()) for k, v in self.headers.items()
            ],
            "server": (self.gateway.host, self.gateway.port),
            "client": self.client_address,
        }
        received = {"sent": False}

        async def receive():
            if received["sent"]:
                await asyncio.sleep(3600)
            received["sent"] = True
            return {"type": "http.request", "body": body, "more_body": False}

        messages: list[dict] = []

        async def send(message):
            messages.append(message)

        asyncio.run(asgi_app(scope, receive, send))
        status = next(
            (m for m in messages if m["type"] == "http.response.start"),
            {"status": 500, "headers": []},
        )
        payload = b"".join(
            m.get("body", b"") for m in messages if m["type"] == "http.response.body"
        )
        self._send_payload(status["status"], status.get("headers", []), payload)

    def _respond_json(
        self, code: int, obj, extra_headers: dict | None = None
    ) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("content-type", "application/json")
        self.send_header("content-length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # -- built-in observability routes --------------------------------------

    def _serve_builtin(self, parsed, method: str) -> bool:
        """Built-in observability routes: ``/metrics`` (prometheus
        exposition: this process's registry + every pushed job file),
        ``/traces[/<call_id>]`` (call-lifecycle span JSON), ``/healthz``
        (SLO pass/fail + burn rates), ``/autoscaler[?function=tag]``
        (the autoscaler decision journal), ``/disagg`` (replica roles,
        migration counters, prefix-tier occupancy — docs/disagg.md),
        ``/chaos`` (injected-fault counters + episode journal —
        docs/faults.md), ``/prefixstore`` (shared prefix-store dedup,
        hit-origin, takeover counters + ownership journal —
        docs/prefix_store.md), ``/fleet`` (fleet-autoscaler replica counts,
        decisions, boot latencies + journal — docs/fleet.md), and
        ``/health`` (gray-failure watchdog: per-replica progress
        classification, watermark ages, ladder decisions —
        docs/health.md), ``/profile`` (hot-path profiler: per-replica
        tick-phase summaries, host fraction, compile ledger —
        docs/observability.md#hot-path-profiling), ``/alerts``
        (alert-rule firing state + fire/clear history —
        docs/observability.md#alert-rules), and
        ``/incidents[/<id>[?file=NAME]]`` (incident-bundle index /
        manifest / bundled file — docs/observability.md#incident-bundles),
        and ``/usage[?n=N]`` (per-tenant usage meters + roofline MFU/MBU —
        docs/observability.md#roofline-and-usage-accounting), and
        ``/canary[?n=N]`` (correctness-canary probe results, drift counters,
        prober state — docs/observability.md#correctness-canary). ``/``
        serves the :data:`BUILTIN_ROUTES` discovery index.
        User endpoints with the same label win — these only answer when no
        route claimed the path."""
        parts = parsed.path.strip("/").split("/")
        label = parts[0] if parts else ""
        if method != "GET" or (label and label not in BUILTIN_ROUTES):
            return False
        if not label:
            # `/` — the discovery index (ISSUE: operators should not need
            # the docs open to find a surface)
            self._respond_json(200, _root_index())
            return True
        if label == "canary":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 20))
            except ValueError:
                n = 20
            self._respond_json(200, _canary_snapshot(last=n))
            return True
        if label == "usage":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 10))
            except ValueError:
                n = 10
            self._respond_json(200, _usage_snapshot(last=n))
            return True
        if label == "alerts":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 20))
            except ValueError:
                n = 20
            self._respond_json(200, _alerts_snapshot(last=n))
            return True
        if label == "incidents":
            from ..observability import incident as _incident

            if len(parts) > 1 and parts[1]:
                # by-id fetch: the manifest, or one bundled file via
                # ?file=NAME (manifest-whitelisted — read_bundle_file
                # refuses names capture() never wrote)
                token = urllib.parse.unquote(parts[1])
                manifest = _incident.read_manifest(token)
                if manifest is None:
                    self._respond_json(
                        404, {"error": f"no incident {token!r}"}
                    )
                    return True
                q = {
                    k: v[-1]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                name = q.get("file")
                if name:
                    body = _incident.read_bundle_file(manifest["id"], name)
                    if body is None:
                        self._respond_json(
                            404,
                            {"error": f"no file {name!r} in {manifest['id']}"},
                        )
                    else:
                        self._respond_json(
                            200,
                            {"id": manifest["id"], "file": name,
                             "content": body},
                        )
                else:
                    self._respond_json(200, manifest)
                return True
            self._respond_json(200, _incidents_snapshot())
            return True
        if label == "disagg":
            self._respond_json(200, _disagg_snapshot())
            return True
        if label == "profile":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 20))
            except ValueError:
                n = 20
            self._respond_json(200, _profile_snapshot(last=n))
            return True
        if label == "health":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 20))
            except ValueError:
                n = 20
            self._respond_json(200, _health_snapshot(last=n))
            return True
        if label == "fleet":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 20))
            except ValueError:
                n = 20
            self._respond_json(200, _fleet_snapshot(last=n))
            return True
        if label == "chaos":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 10))
            except ValueError:
                n = 10
            self._respond_json(200, _chaos_snapshot(last=n))
            return True
        if label == "prefixstore":
            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 10))
            except ValueError:
                n = 10
            self._respond_json(200, _prefixstore_snapshot(last=n))
            return True
        if label == "healthz":
            from ..observability.slo import healthz

            payload = healthz()
            code = 200 if payload["status"] == "ok" else 503
            self._respond_json(code, payload)
            return True
        if label == "autoscaler":
            from ..observability.journal import default_journal

            q = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            try:
                n = int(q.get("n", 50))
            except ValueError:
                n = 50
            self._respond_json(
                200,
                {
                    "decisions": default_journal.tail(
                        n, function=q.get("function")
                    )
                },
            )
            return True
        if label == "metrics":
            from ..observability.export import live_and_pushed_metrics

            body = live_and_pushed_metrics(
                job=f"gateway-{self.gateway.app.name}"
            ).encode()
            self.send_response(200)
            self.send_header("content-type", "text/plain; version=0.0.4")
            self.send_header("content-length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return True
        from ..observability import reqtrace as _reqtrace

        if len(parts) > 1 and parts[1]:
            # either id namespace resolves here — executor calls (in-…)
            # AND serving requests (req-…) — and request traces merge
            # across every registered per-replica store, so a disagg
            # request's prefill/transfer/decode spans come back as ONE tree
            token = urllib.parse.unquote(parts[1])
            # resolve() whitelists the token shape and already matches
            # exact ids first — an unresolvable token is a 404, NEVER a
            # raw-path fallback (that would reopen traversal reads)
            trace_id = _reqtrace.resolve(token)
            spans = _reqtrace.read_trace(trace_id) if trace_id else []
            if not spans:
                self._respond_json(404, {"error": f"no trace {token!r}"})
            else:
                payload = {
                    "trace_id": trace_id,
                    "kind": _reqtrace.trace_kind(trace_id),
                    "spans": spans,
                }
                q = urllib.parse.parse_qs(parsed.query)
                if q.get("explain"):
                    payload["narrative"] = _reqtrace.explain_lines(
                        spans, trace_id
                    )
                self._respond_json(200, payload)
        else:
            # same store set as the by-id fetch: ids served by
            # /traces/<id> must also show up in the index
            self._respond_json(200, {"traces": _reqtrace.list_traces()})
        return True

    def _handle(self, method: str) -> None:
        route, parsed = self._route()
        if route is None:
            if self._serve_builtin(parsed, method):
                return
            self._respond_json(404, {"error": f"no endpoint at {parsed.path}"})
            return
        fn = route["function"]
        web = fn.spec.web
        if web["type"] == "websocket_endpoint":
            if (self.headers.get("Upgrade") or "").lower() != "websocket":
                self._respond_json(
                    426, {"error": "websocket endpoint: upgrade required"}
                )
                return
            from .websocket import ConnectionClosed, perform_handshake

            ws = perform_handshake(self)
            if ws is None:
                return
            kwargs = self._query_kwargs(fn, parsed)
            try:
                # in-process: the live socket cannot cross the container
                # boundary (see endpoints.websocket_endpoint docstring)
                fn.raw_f(ws, **kwargs)
            except ConnectionClosed:
                pass
            except BaseException as e:
                _log.warning(
                    "websocket handler error: %s: %s", type(e).__name__, e
                )
            finally:
                ws.close()
                self.close_connection = True
            return
        if web["type"] in ("wsgi_app", "asgi_app"):
            # the function returns an app object, built once (under the
            # route lock: concurrent first requests must not double-build)
            with self.gateway.app_build_lock:
                if "app_instance" not in route:
                    route["app_instance"] = fn.raw_f()
            self._started_response = False
            try:
                if web["type"] == "wsgi_app":
                    self._serve_wsgi(route["app_instance"], parsed, method)
                else:
                    self._serve_asgi(route["app_instance"], parsed, method)
            except (BrokenPipeError, ConnectionResetError):
                self.close_connection = True
            except BaseException as e:
                if getattr(self, "_started_response", False):
                    # response underway: a second status line would corrupt it
                    self.close_connection = True
                else:
                    self._respond_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if web["type"] == "fastapi_endpoint" and web.get("method", "GET") != method:
            self._respond_json(405, {"error": f"method {method} not allowed"})
            return
        kwargs = {
            k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()
        }
        if method == "POST":
            length = int(self.headers.get("content-length") or 0)
            if length:
                try:
                    body = json.loads(self.rfile.read(length))
                    if isinstance(body, dict):
                        kwargs.update(body)
                except json.JSONDecodeError:
                    self._respond_json(400, {"error": "invalid JSON body"})
                    return
        kwargs = _coerce_kwargs(fn.raw_f, kwargs)  # noqa: E501 — POST merges body first; websocket path uses _query_kwargs
        headers_sent = False
        try:
            if fn.spec.is_generator:
                # submit BEFORE the SSE headers: a shed (bounded queue)
                # must still be able to answer 429
                gen = fn.remote_gen(**kwargs)
                self.send_response(200)
                self.send_header("content-type", "text/event-stream")
                self.send_header("cache-control", "no-cache")
                self.end_headers()
                headers_sent = True
                for item in gen:
                    data = item if isinstance(item, str) else json.dumps(item)
                    self.wfile.write(f"data: {data}\n\n".encode())
                    self.wfile.flush()
                return
            result = fn.remote(**kwargs)
            if isinstance(result, (bytes, bytearray)):
                self.send_response(200)
                self.send_header("content-type", "application/octet-stream")
                self.send_header("content-length", str(len(result)))
                self.end_headers()
                headers_sent = True
                self.wfile.write(result)
            else:
                self._respond_json(200, result)
        except BrokenPipeError:
            pass
        except _ShedError as e:
            # bounded pool queue (max_pending_inputs=) rejected the input:
            # overload surfaces as a fast 429 + Retry-After, the same
            # contract the OpenAI layer keeps — never unbounded queueing
            if headers_sent:
                self.close_connection = True
            else:
                import math

                self._respond_json(
                    429,
                    {"error": str(e), "reason": e.reason},
                    extra_headers={
                        "retry-after": str(math.ceil(e.retry_after_s))
                    },
                )
        except BaseException as e:
            if headers_sent:
                # Response already started: a second status line would corrupt
                # the stream. Drop the connection so the client sees EOF.
                _log.warning(
                    "error mid-response: %s: %s", type(e).__name__, e
                )
                self.close_connection = True
            else:
                self._respond_json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")


class Gateway:
    """One HTTP server hosting all web endpoints of an app."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0):
        self.app = app
        self.app_build_lock = threading.Lock()
        self.routes: dict[str, dict] = {}
        for name in app.registered_web_endpoints:
            fn = app.registered_functions[name]
            label = (fn.spec.web or {}).get("label") or name
            self.routes[label] = {"function": fn}
        handler = type("BoundHandler", (_Handler,), {"gateway": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "Gateway":
        for label, route in self.routes.items():
            url = f"http://{self.host}:{self.port}/{label}"
            registry.publish(route["function"].spec.tag, url)
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"


def wait_for_port(host: str, port: int, timeout: float) -> bool:
    """Poll until a TCP port accepts — the readiness gate the reference uses
    before advertising a replica (vllm_inference.py:127-128)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            time.sleep(0.1)
    return False
