"""Web URL registry: maps deployed web endpoints to live local URLs.

The reference platform assigns stable ``*.modal.run`` URLs per endpoint
(``f.get_web_url()``, text_to_image.py:254). Locally, ``tpurun serve`` binds
a host port per app and records it here so ``get_web_url`` resolves in any
process on the host.
"""

from __future__ import annotations

import json
from pathlib import Path

from .._internal import config as _config


def _path() -> Path:
    return _config.state_dir() / "web_endpoints.json"


def _load() -> dict:
    try:
        return json.loads(_path().read_text())
    except (FileNotFoundError, json.JSONDecodeError):
        return {}


def publish(tag: str, url: str) -> None:
    d = _load()
    d[tag] = url
    _path().write_text(json.dumps(d, indent=2))


def web_url_for(spec) -> str | None:
    d = _load()
    url = d.get(spec.tag)
    if url:
        return url
    # Not serving yet: return the deterministic URL serve would assign.
    label = (spec.web or {}).get("label") or spec.tag.split(".")[-1]
    return f"http://127.0.0.1:0/{label}"
