"""Paged KV cache: device pages + host-side block allocator.

The TPU analog of vLLM's PagedAttention block manager (the engine inside the
reference's vllm_inference.py). Device side: two arrays
``[n_layers, n_pages, page_size, n_kv_heads, head_dim]`` living in HBM — a
page holds all kv heads contiguously so the decode kernel moves one fat DMA
per page — with page 0 reserved as the trash page (padded/dead slots write
there). Host side: a
free-list allocator — intentionally simple; each sequence claims
``ceil(max_tokens/page_size)`` pages at admission so decode can never fail
mid-flight (no preemption/swap in v1, documented trade-off vs vLLM's
best-effort allocation + preemption).
"""

from __future__ import annotations

import dataclasses
import threading

import jax.numpy as jnp

from ..observability import metrics as _obs


class OutOfPages(RuntimeError):
    pass


class PageAllocator:
    """Thread-safe free-list over physical page ids (page 0 is reserved).

    Occupancy telemetry: every alloc/free refreshes the
    ``mtpu_kv_pages_used`` / ``mtpu_kv_pages_free`` / ``mtpu_kv_page_occupancy``
    gauges — per-request frequency (admission/release), never per-token, so
    the decode hot loop pays nothing. Multiple allocators in one process
    share the gauges last-writer-wins (one serving engine per process is the
    deployed shape); ``track=False`` opts an auxiliary allocator out.
    """

    def __init__(self, n_pages: int, *, track: bool = True):
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))  # pop() yields low ids first
        self._lock = threading.Lock()
        self._track = track

    def _emit_gauges_locked(self) -> None:
        if not self._track:
            return
        usable = self.n_pages - 1  # page 0 is the reserved trash page
        free = len(self._free)
        _obs.set_kv_occupancy(
            used=usable - free, free=free, total_usable=usable
        )

    def alloc(self, n: int) -> list[int]:
        with self._lock:
            if n > len(self._free):
                raise OutOfPages(f"need {n} pages, {len(self._free)} free")
            out = [self._free.pop() for _ in range(n)]
            self._emit_gauges_locked()
            return out

    def free(self, pages: list[int]) -> None:
        with self._lock:
            for p in pages:
                if p != 0:
                    self._free.append(p)
            self._emit_gauges_locked()

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used(self) -> int:
        with self._lock:
            return (self.n_pages - 1) - len(self._free)

    @property
    def occupancy(self) -> float:
        """Allocated fraction of the usable pool (0..1)."""
        usable = self.n_pages - 1
        return self.used / usable if usable > 0 else 0.0


@dataclasses.dataclass
class PagedKVCache:
    k_pages: object  # [L, P, page_size, Hkv, hd]
    v_pages: object
    page_size: int
    allocator: PageAllocator

    @classmethod
    def create(
        cls,
        *,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        n_pages: int,
        page_size: int = 16,
        dtype=jnp.bfloat16,
        prefer_native: bool = True,
    ) -> "PagedKVCache":
        shape = (n_layers, n_pages, page_size, n_kv_heads, head_dim)
        allocator = None
        if prefer_native:
            try:  # C++ free list (native/mtpu_host.cpp); same semantics
                from ..native import NativePageAllocator

                allocator = NativePageAllocator(n_pages)
            except Exception:
                allocator = None
        return cls(
            k_pages=jnp.zeros(shape, dtype),
            v_pages=jnp.zeros(shape, dtype),
            page_size=page_size,
            allocator=allocator or PageAllocator(n_pages),
        )

    @property
    def n_pages(self) -> int:
        return self.k_pages.shape[1]

    def bytes(self) -> int:
        return 2 * self.k_pages.size * self.k_pages.dtype.itemsize

    def pages_for(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def occupancy(self) -> dict:
        """Page-pool occupancy snapshot (works for the native allocator too,
        which has no gauge hooks of its own): used/free/total pages, the
        allocated fraction, and the HBM bytes that fraction pins."""
        usable = self.n_pages - 1
        free = self.allocator.available
        used = usable - free
        bytes_per_page = self.bytes() // self.n_pages
        return {
            "pages_used": used,
            "pages_free": free,
            "pages_total": usable,
            "occupancy": used / usable if usable > 0 else 0.0,
            "bytes_used": used * bytes_per_page,
            "bytes_total": self.bytes(),
        }
