"""Paged KV cache: device pages + host-side block allocator.

The TPU analog of vLLM's PagedAttention block manager (the engine inside the
reference's vllm_inference.py). Device side: two arrays
``[n_layers, n_pages, page_size, n_kv_heads, head_dim]`` living in HBM — a
page holds all kv heads contiguously so the decode kernel moves one fat DMA
per page — with page 0 reserved as the trash page (padded/dead slots write
there). Host side: a
free-list allocator — intentionally simple; each sequence claims
``ceil(max_tokens/page_size)`` pages at admission so decode can never fail
mid-flight (no preemption/swap in v1, documented trade-off vs vLLM's
best-effort allocation + preemption).

``create(kv_dtype="int8")`` stores the pages quantized
(:class:`~..ops.kv_quant.QuantizedKV`: int8 data + per-token-head f32
scales ``[L, P, page_size, Hkv]``), making the cache a **4-leaf jax
pytree** — k data/scale + v data/scale — that flows through jit/donation/
sharding like the plain 2-leaf bf16 cache. Halves KV HBM traffic AND
residency (~2x the slots/context in the same HBM); see docs/kv_cache.md
for the layout and the tolerance-based accuracy contract.
"""

from __future__ import annotations

import dataclasses
import threading

import jax
import jax.numpy as jnp

from ..observability import metrics as _obs
from ..ops.kv_quant import is_quantized, kv_dtype_name, kv_empty


class OutOfPages(RuntimeError):
    pass


class PageAllocator:
    """Thread-safe free-list over physical page ids (page 0 is reserved).

    Occupancy telemetry: every alloc/free refreshes the
    ``mtpu_kv_pages_used`` / ``mtpu_kv_pages_free`` / ``mtpu_kv_page_occupancy``
    gauges — per-request frequency (admission/release), never per-token, so
    the decode hot loop pays nothing. Multiple allocators in one process
    share the gauges last-writer-wins (one serving engine per process is the
    deployed shape); ``track=False`` opts an auxiliary allocator out.
    """

    def __init__(self, n_pages: int, *, track: bool = True):
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))  # pop() yields low ids first
        self._lock = threading.Lock()
        self._track = track

    def _emit_gauges_locked(self) -> None:
        if not self._track:
            return
        usable = self.n_pages - 1  # page 0 is the reserved trash page
        free = len(self._free)
        _obs.set_kv_occupancy(
            used=usable - free, free=free, total_usable=usable
        )

    def alloc(self, n: int) -> list[int]:
        with self._lock:
            if n > len(self._free):
                raise OutOfPages(f"need {n} pages, {len(self._free)} free")
            out = [self._free.pop() for _ in range(n)]
            self._emit_gauges_locked()
            return out

    def free(self, pages: list[int]) -> None:
        with self._lock:
            for p in pages:
                if p != 0:
                    self._free.append(p)
            self._emit_gauges_locked()

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used(self) -> int:
        with self._lock:
            return (self.n_pages - 1) - len(self._free)

    @property
    def occupancy(self) -> float:
        """Allocated fraction of the usable pool (0..1)."""
        usable = self.n_pages - 1
        return self.used / usable if usable > 0 else 0.0


@dataclasses.dataclass
class PagedKVCache:
    # plain [L, P, page_size, Hkv, hd] arrays, or QuantizedKV (int8 data +
    # [L, P, page_size, Hkv] f32 scales) — two device leaves each way, so
    # the whole cache is a 2- (bf16) or 4-leaf (int8) pytree
    k_pages: object
    v_pages: object
    page_size: int
    allocator: PageAllocator

    @classmethod
    def create(
        cls,
        *,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        n_pages: int,
        page_size: int = 16,
        kv_dtype=None,  # "int8" | jnp dtype; the canonical spelling
        dtype=None,  # legacy alias for kv_dtype (kept for callers)
        prefer_native: bool = True,
    ) -> "PagedKVCache":
        if kv_dtype is not None and dtype is not None:
            raise ValueError("pass kv_dtype= or dtype=, not both")
        kv_dtype = kv_dtype if kv_dtype is not None else dtype
        if kv_dtype is None:
            kv_dtype = jnp.bfloat16
        shape = (n_layers, n_pages, page_size, n_kv_heads, head_dim)
        allocator = None
        if prefer_native:
            try:  # C++ free list (native/mtpu_host.cpp); same semantics
                from ..native import NativePageAllocator

                allocator = NativePageAllocator(n_pages)
            except Exception:
                allocator = None
        return cls(
            k_pages=kv_empty(shape, kv_dtype),
            v_pages=kv_empty(shape, kv_dtype),
            page_size=page_size,
            allocator=allocator or PageAllocator(n_pages),
        )

    @property
    def n_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def kv_dtype(self) -> str:
        """Reporting name of the page dtype: "int8" (quantized) or the
        array dtype name ("bfloat16"/"float32")."""
        return kv_dtype_name(self.k_pages)

    @property
    def quantized(self) -> bool:
        return is_quantized(self.k_pages)

    def bytes(self) -> int:
        """Total device bytes of the page arrays, dtype-aware: int8 caches
        count the int8 payload plus the f32 scale rows (~3% at D=128) —
        about half the bf16 figure, which is exactly the headroom the
        occupancy gauges and bench.py's ``kv_cache`` section report.
        (``nbytes`` is a property on QuantizedKV and jax.Array alike.)"""
        return self.k_pages.nbytes + self.v_pages.nbytes

    def pages_for(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def occupancy(self) -> dict:
        """Page-pool occupancy snapshot (works for the native allocator too,
        which has no gauge hooks of its own): used/free/total pages, the
        allocated fraction, and the HBM bytes that fraction pins (dtype-
        aware via :meth:`bytes` — int8 caches report ~half the bf16
        footprint for the same page count)."""
        usable = self.n_pages - 1
        free = self.allocator.available
        used = usable - free
        bytes_per_page = self.bytes() // self.n_pages
        return {
            "pages_used": used,
            "pages_free": free,
            "pages_total": usable,
            "occupancy": used / usable if usable > 0 else 0.0,
            "bytes_used": used * bytes_per_page,
            "bytes_total": self.bytes(),
        }


# a jax pytree (device leaves: k/v pages — 2 for bf16, 4 for int8 with the
# scale arrays riding alongside) so tree utilities (jax.tree.leaves,
# utils.sync.force, snapshot codecs) see the device state. The leaf set is
# also the WIRE CONTRACT of disaggregated serving: the KV-page transport
# (serving/disagg/transport.wire_leaves) enumerates these leaves by tree
# flattening and ships every one per migrated page, with every leaf's page
# axis at axis 1 — keep that invariant when adding leaves (a static guard
# asserts codec leaves == pytree leaves; docs/disagg.md). CAUTION: the
# allocator rides in meta_fields and compares by IDENTITY (mutable host
# state, no __eq__) — do NOT pass a whole cache as a jit argument; every
# distinct allocator would be a distinct static key (silent retraces).
# Jitted programs take cache.k_pages / cache.v_pages, as the engine does.
jax.tree_util.register_dataclass(
    PagedKVCache,
    data_fields=("k_pages", "v_pages"),
    meta_fields=("page_size", "allocator"),
)
