"""The fused speculative round program: propose(γ) + verify + accept in one
dispatch.

Program shape (docs/speculative.md): one engine round of speculative
decoding is ONE jitted program — in draft mode a γ-step draft-model propose
loop on :func:`~...ops.scan_loop.masked_scan` (the same control-flow core
the macro-step decode runtime scans its decode steps with — lanes die when
their per-slot γ budget is spent or they run out of page-table capacity,
and a step whose every lane is dead skips the draft transformer entirely),
then ONE ragged teacher-forced target forward over all γ+1 chain positions
against the paged KV cache (``llama.verify_step``), then the accept/reject
cut in-graph. Prompt-lookup (ngram) mode skips the draft scan — proposals
arrive host-computed — and runs the same verify + accept tail.

Per-slot γ rides the batch as a traced ``gammas [B]`` argument, so mixed
spec/non-spec slots coexist in one compiled program: a lane with
``gammas[i] == 0`` proposes nothing and takes the CLASSIC sampling path —
its one token is drawn by the very same ``serving.sampling.sample`` call
the block/multistep programs make, (seed, position)-keyed, with
top_p/top_k honored — which is what lets the adaptive controller
(:mod:`.controller`) shrink γ to 0 per request without switching programs,
and what makes temperature>0 (always-seeded, see ``auto_seed``) requests
token-identical to the non-speculative engine.

Output is the multistep harvest plane (docs/multistep.md): ``(toks [N, B],
valid [N, B], last [B], caches...)`` with ``N = γ_max + 1`` —
``valid[k, i]`` marks row ``k`` of lane ``i`` as an accepted token, so the
engine's ONE harvest site (``_process_block``: exactly two blocking reads,
AST-pinned) accepts spec rounds and macro-step blocks identically and the
off-thread detok worker never knows which program produced its tokens.

KV rollback is implicit and trie-safe: ``verify_step`` writes KV for every
chain position, rejected-suffix entries are simply overwritten as the
accepted position advances and are never attended past the accept point
(the causal mask inside the verify attention), and the prefix trie only
ever indexes host-ACCEPTED tokens — junk KV beyond a request's final
position lives on private (non-trie) pages and dies with the slot.

Exactness contract (docs/speculative.md#exactness): greedy lanes commit
only target-argmax tokens, token-identical to the non-spec engine
(asserted across {bf16, int8} x TP1 in tests/test_speculative.py);
temperature>0 lanes never speculate (γ pinned 0) and keep the
(seed, position)-keyed stream; cross-TP stays the logit-tolerance
contract — never asserted token-exact anywhere in this repo.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ...models import llama
from ...ops.scan_loop import masked_scan
from ..sampling import sample

#: the adaptive-γ knob (engine rule: explicit ctor arg beats env beats off)
SPEC_ADAPTIVE_ENV = "MTPU_SPEC_ADAPTIVE"


def resolve_spec_adaptive(arg: bool | None = None) -> bool:
    """Resolve the adaptive-γ controller switch ONCE at engine build
    (the MTPU_DECODE_STEPS / MTPU_KV_DTYPE knob rule): explicit arg beats
    ``MTPU_SPEC_ADAPTIVE`` beats off. Lands on a runtime-mutable engine
    attribute so benches A/B fixed-vs-adaptive without a rebuild."""
    if arg is None:
        raw = os.environ.get(SPEC_ADAPTIVE_ENV, "")
        arg = raw.strip().lower() in ("1", "true", "yes", "on")
    return bool(arg)


def accept_reject(
    t_logits, proposals, temps, keys2, active, *, gamma,
    proposal_logps=None, prop_valid=None,
):
    """The speculative accept/reject cut (both spec modes route here so the
    math can never drift). ``proposal_logps`` is the draft model's log-probs
    ``[B, γ, V]``; ``None`` means a degenerate (delta) proposal
    distribution — prompt-lookup mode — where acceptance is min(1, p_t(x))
    and the rejection residual is p_t with x zeroed. ``prop_valid``
    ``[B, γ]`` marks which proposal slots are real (per-slot γ budgets,
    capacity-died draft lanes, empty ngram lookups); slots beyond it are
    never accepted and an all-false row degrades to exactly one plain
    target step.

    Greedy lanes (temperature 0) accept while proposal == target argmax —
    reproducing the target's greedy decode token-for-token. Sampling lanes
    use standard speculative sampling (accept x with prob
    min(1, p_t(x)/p_d(x)); resample rejections from the residual
    max(p_t - p_d, 0)), so the OUTPUT DISTRIBUTION equals the target's —
    but the engine never dispatches sampling lanes with γ>0 (they are not
    (seed, position)-reproducible through this path; see
    docs/speculative.md#exactness). Returns ``(out [B, γ+1], n_emit [B])``.
    """
    B = proposals.shape[0]
    t_scaled = t_logits / jnp.maximum(temps, 1e-6)[:, None, None]
    t_logp = jax.nn.log_softmax(t_scaled, axis=-1)
    greedy_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

    rows = jnp.arange(B)
    valid = (
        jnp.ones((B, gamma), bool) if prop_valid is None else prop_valid
    )
    n_prop = valid.sum(axis=1)
    match = (proposals == greedy_choice[:, :gamma]) & valid
    lp_t = jnp.take_along_axis(
        t_logp[:, :gamma], proposals[..., None], axis=-1
    )[..., 0]
    if proposal_logps is None:
        accept_prob = jnp.exp(lp_t)  # min(1, p_t / 1)
    else:
        lp_d = jnp.take_along_axis(
            proposal_logps, proposals[..., None], axis=-1
        )[..., 0]
        accept_prob = jnp.exp(jnp.minimum(0.0, lp_t - lp_d))
    u = jax.random.uniform(keys2[0], (B, gamma))
    accept = jnp.where(
        (temps <= 0.0)[:, None], match, (u < accept_prob) & valid
    )
    n_acc = jnp.argmin(
        jnp.concatenate(
            [accept.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)],
            axis=1,
        ),
        axis=1,
    )  # first rejection; == γ when all accepted

    # token at the cut: target's fix on rejection, fresh bonus sample when
    # every real proposal was accepted
    j = n_acc
    p_t_row = jnp.exp(t_logp[rows, j])  # [B, V]
    if proposal_logps is None:
        prop_at_j = proposals[rows, jnp.minimum(j, gamma - 1)]
        residual = p_t_row.at[rows, prop_at_j].set(0.0)
    else:
        p_d_row = jnp.exp(proposal_logps[rows, jnp.minimum(j, gamma - 1)])
        residual = jnp.maximum(p_t_row - p_d_row, 0.0)
    rejected = j < n_prop
    has_res = residual.sum(-1, keepdims=True) > 0
    residual = jnp.where(rejected[:, None] & has_res, residual, p_t_row)
    sampled_fix = jax.vmap(jax.random.categorical)(
        jax.random.split(keys2[1], B), jnp.log(residual + 1e-20)
    ).astype(jnp.int32)
    fix = jnp.where(temps <= 0.0, greedy_choice[rows, j], sampled_fix)
    out = jnp.concatenate(
        [proposals, jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    out = out.at[rows, j].set(fix)
    n_emit = jnp.where(active, n_acc + 1, 0)
    return out, n_emit


def _emit_plane(out, n_emit, active, gammas, classic_tok):
    """Convert an accept/reject result to the multistep harvest plane.

    ``classic_tok`` replaces row 0 for γ=0 lanes — the token the classic
    sampling path (``sample`` with the full temperature/top_p/top_k/seed
    surface, (seed, position)-keyed) drew from the verify logits' first
    position, which IS the classic decode distribution for that position.
    Returns ``(toks [N, B], valid [N, B], last [B])``."""
    B, N = out.shape
    rows = jnp.arange(B)
    out = out.at[:, 0].set(
        jnp.where(active & (gammas == 0), classic_tok, out[:, 0])
    )
    toks = out.T  # [N, B]
    valid = jnp.arange(N)[:, None] < n_emit[None, :]  # [N, B]
    last = out[rows, jnp.maximum(n_emit - 1, 0)]
    return toks, valid, last


def build_spec_round_fn(
    cfg,
    draft_cfg,
    *,
    paged_impl: str,
    scatter_impl: str,
    mesh,
    gamma: int,
):
    """Build the jittable draft-mode speculative round for one engine
    config: γ-step draft propose on ``masked_scan`` + one ragged target
    verify + accept, emitting the harvest plane.

    Signature: ``(params, d_params, tk, tv, dk, dv, tokens, positions,
    page_tables, active, gammas, key, temps, top_ps, top_ks, seeds)`` →
    ``(toks [γ+1, B], valid [γ+1, B], last [B], tk, tv, dk, dv)``.
    ``gammas [B]`` is the per-slot proposal budget (≤ the compiled γ);
    lanes at 0 take the classic sampling path inside the same program.
    """

    def spec_round_fn(
        params, d_params, tk, tv, dk, dv, tokens, positions, page_tables,
        active, gammas, key, temps, top_ps, top_ks, seeds,
    ):
        B = tokens.shape[0]
        page_size = tk.shape[2]
        cap = page_tables.shape[1] * page_size
        keys = jax.random.split(key, gamma + 3)
        spec_lane = active & (gammas > 0)

        def step(live, state, k_i):
            tok, pos, taken, dkp, dvp = state
            logits, dkp, dvp = llama.decode_step(
                d_params, tok, pos, dkp, dvp, page_tables, live, draft_cfg,
                impl=paged_impl, scatter_impl=scatter_impl, mesh=mesh,
            )
            scaled = (
                logits / jnp.maximum(temps, 1e-6)[:, None]
            ).astype(jnp.float32)
            proposed = jnp.where(
                temps <= 0.0,
                jnp.argmax(logits, axis=-1),
                jax.vmap(jax.random.categorical)(
                    jax.random.split(k_i, B), scaled
                ),
            ).astype(jnp.int32)
            proposed = jnp.where(live, proposed, tok)  # dead lanes hold
            logp = jax.nn.log_softmax(scaled, axis=-1)
            prop_valid = live
            one = live.astype(taken.dtype)
            taken = taken + one
            pos = pos + one  # dead lanes stop advancing
            live = live & (taken < gammas) & (pos < cap)
            return (
                live, (proposed, pos, taken, dkp, dvp),
                (proposed, logp, prop_valid),
            )

        def hold(live, state, k_i):
            # all draft lanes dead: hold tokens, emit junk log-probs under
            # an all-false validity row (never accepted)
            V = cfg.vocab_size
            return (
                state[0],
                jnp.zeros((B, V), jnp.float32),
                jnp.zeros((B,), bool),
            )

        taken0 = jnp.zeros_like(positions)
        live, state, (draft_toks, draft_logps, prop_valid) = masked_scan(
            step,
            hold,
            spec_lane & (positions < cap),
            (tokens, positions, taken0, dk, dv),
            keys[:gamma],
        )
        last_d, last_pos, _taken, dk, dv = state
        # complete the draft cache: the scan proposed its last token but
        # never wrote its KV — without this, a fully-accepted round leaves
        # a hole at position+γ and the NEXT round's draft attends to stale
        # state, collapsing acceptance (logits discarded; the draft is
        # small)
        _, dk, dv = llama.decode_step(
            d_params, last_d, last_pos, dk, dv, page_tables,
            spec_lane & (last_pos < cap), draft_cfg, impl=paged_impl,
            scatter_impl=scatter_impl, mesh=mesh,
        )
        draft_toks = draft_toks.T  # [B, γ]
        draft_logps = draft_logps.transpose(1, 0, 2)  # [B, γ, V]
        prop_valid = prop_valid.T  # [B, γ]

        # target scores the whole chain in ONE ragged pass against the
        # paged cache (γ=0 lanes still write their committed token's KV —
        # the classic decode_step's scatter, chain position 0)
        chain = jnp.concatenate([tokens[:, None], draft_toks], axis=1)
        t_logits, tk, tv = llama.verify_step(
            params, chain, positions, tk, tv, page_tables, active, cfg
        )  # [B, γ+1, V]
        out, n_emit = accept_reject(
            t_logits, draft_toks, temps, (keys[gamma], keys[gamma + 1]),
            active, gamma=gamma, proposal_logps=draft_logps,
            prop_valid=prop_valid,
        )
        classic_tok = sample(
            t_logits[:, 0], keys[gamma + 2], temps, top_ps, top_ks,
            seeds=seeds, step_ids=positions,
        )
        toks, valid, last = _emit_plane(
            out, n_emit, active, gammas, classic_tok
        )
        return toks, valid, last, tk, tv, dk, dv

    return spec_round_fn


def build_ngram_round_fn(cfg, *, gamma: int):
    """Build the jittable prompt-lookup round: host proposals → one ragged
    target verify + accept, emitting the harvest plane. No draft model, no
    draft cache, no device propose loop.

    Signature: ``(params, tk, tv, proposals [B, γ], n_prop [B], gammas
    [B], tokens, positions, page_tables, active, key, temps, top_ps,
    top_ks, seeds)`` → ``(toks [γ+1, B], valid [γ+1, B], last [B], tk,
    tv)``. ``n_prop`` counts real proposal slots per lane (already clamped
    ≤ gammas by the host); empty lookups degrade to one plain target step.
    """

    def ngram_round_fn(
        params, tk, tv, proposals, n_prop, gammas, tokens, positions,
        page_tables, active, key, temps, top_ps, top_ks, seeds,
    ):
        k1, k2, k3 = jax.random.split(key, 3)
        chain = jnp.concatenate([tokens[:, None], proposals], axis=1)
        t_logits, tk, tv = llama.verify_step(
            params, chain, positions, tk, tv, page_tables, active, cfg
        )  # [B, γ+1, V]
        prop_valid = jnp.arange(gamma)[None, :] < n_prop[:, None]
        out, n_emit = accept_reject(
            t_logits, proposals, temps, (k1, k2), active, gamma=gamma,
            prop_valid=prop_valid,
        )
        classic_tok = sample(
            t_logits[:, 0], k3, temps, top_ps, top_ks,
            seeds=seeds, step_ids=positions,
        )
        toks, valid, last = _emit_plane(
            out, n_emit, active, gammas, classic_tok
        )
        return toks, valid, last, tk, tv

    return ngram_round_fn
