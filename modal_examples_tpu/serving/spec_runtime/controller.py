"""Adaptive speculation depth: the γ-schedule policy (docs/speculative.md).

Speculation competes with continuous batching for the same flops: a draft
chain that gets rejected is a pure tax on every other slot sharing the
round, and a full batch amortizes the host round-trip so well that the
marginal win of speculation inverts. This controller answers that inside
the SCHEDULER — per request, per round — instead of leaving it to offline
bench tuning:

- **Acceptance EWMA (per request):** each request carries an exponentially
  weighted acceptance rate, initialized optimistically (speculate until
  proven wasteful). γ scales with the EWMA, so a request whose draft stops
  predicting it (topic shift, code → prose) spends fewer draft steps.
- **Collapse + probe recovery (hysteresis):** below ``collapse_below`` the
  request stops speculating entirely (γ=0 — the fused program's classic
  lane, docs/speculative.md#program-shape). Every ``probe_every``-th round
  it proposes a single probe token; only a recovered EWMA ≥
  ``recover_above`` (> collapse_below — the hysteresis band) re-enables
  full speculation, so a borderline request cannot flap.
- **Batch-fill pressure:** at ``batch_fill_cutoff`` occupancy the round
  speculates for no one — verify flops scale with γ+1 per lane, and a full
  batch is already amortized; the marginal token is cheaper decoded than
  speculated.
- **Prefill contention:** while chunked prefills or queued admissions are
  waiting (the PR-10 stall-free budget is actively slicing), γ caps at 1 —
  long speculative rounds stretch the tick and starve admission cadence.

Everything is a pure function of observed (proposed, accepted) pairs and
the pressure flags passed in — no clocks, no engine state — so the whole
matrix is unit-testable with hand-fed rounds (tests/test_spec_adaptive.py).
The engine calls :meth:`observe` at harvest (the controller sees exactly
what the host accepted), :meth:`gamma_for` at dispatch, and
:meth:`forget` at slot release.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class _ReqState:
    ewma: float
    collapsed: bool = False
    rounds_since_probe: int = 0


class AdaptiveGammaController:
    """Per-request speculation depth from acceptance history + pressure."""

    def __init__(
        self,
        gamma_max: int,
        *,
        ewma_alpha: float = 0.4,
        collapse_below: float = 0.3,
        recover_above: float = 0.6,
        probe_every: int = 16,
        batch_fill_cutoff: float = 0.95,
        init_acceptance: float = 1.0,
    ):
        if not (0.0 <= collapse_below <= recover_above <= 1.0):
            raise ValueError(
                "need 0 <= collapse_below <= recover_above <= 1 (the "
                f"hysteresis band), got {collapse_below}/{recover_above}"
            )
        self.gamma_max = int(gamma_max)
        self.ewma_alpha = float(ewma_alpha)
        self.collapse_below = float(collapse_below)
        self.recover_above = float(recover_above)
        self.probe_every = max(1, int(probe_every))
        self.batch_fill_cutoff = float(batch_fill_cutoff)
        self.init_acceptance = float(init_acceptance)
        self._reqs: dict[str, _ReqState] = {}

    def _state(self, request_id: str) -> _ReqState:
        st = self._reqs.get(request_id)
        if st is None:
            st = self._reqs[request_id] = _ReqState(
                ewma=self.init_acceptance
            )
        return st

    def gamma_for(
        self,
        request_id: str,
        *,
        gamma_cap: int | None = None,
        batch_fill: float = 0.0,
        prefill_pressure: bool = False,
    ) -> int:
        """Proposal budget for this request's next round. Advances the
        request's probe counter when collapsed (each call = one dispatched
        round), so callers must call it exactly once per round per live
        request."""
        cap = self.gamma_max if gamma_cap is None else min(
            int(gamma_cap), self.gamma_max
        )
        if cap <= 0:
            return 0
        if batch_fill >= self.batch_fill_cutoff:
            # global pressure: nobody speculates this round, and nobody's
            # per-request state is touched — pressure is not evidence of
            # bad acceptance
            return 0
        st = self._state(request_id)
        if st.collapsed:
            st.rounds_since_probe += 1
            if st.rounds_since_probe >= self.probe_every:
                st.rounds_since_probe = 0
                return 1  # probe: one cheap proposal feeds the EWMA
            return 0
        g = max(1, round(st.ewma * cap))
        if prefill_pressure:
            g = min(g, 1)
        return min(g, cap)

    def observe(self, request_id: str, proposed: int, accepted: int) -> None:
        """Fold one harvested round's (proposed, accepted) into the
        request's EWMA. Rounds that proposed nothing (classic lanes,
        collapsed non-probe rounds) carry no acceptance evidence and are
        ignored."""
        if proposed <= 0:
            return
        rate = min(1.0, max(0.0, accepted / proposed))
        st = self._state(request_id)
        a = self.ewma_alpha
        st.ewma = (1.0 - a) * st.ewma + a * rate
        if st.collapsed:
            if st.ewma >= self.recover_above:
                st.collapsed = False
        elif st.ewma < self.collapse_below:
            st.collapsed = True
            st.rounds_since_probe = 0

    def forget(self, request_id: str) -> None:
        """Drop a finished request's state (slot release)."""
        self._reqs.pop(request_id, None)

    def snapshot(self) -> dict:
        """Debug/stats view: per-request EWMA + collapse flags."""
        return {
            rid: {
                "ewma": round(st.ewma, 4),
                "collapsed": st.collapsed,
            }
            for rid, st in self._reqs.items()
        }
