"""Fused adaptive speculative decoding (docs/speculative.md).

The engine-scheduler-integrated speculation runtime: one jitted
propose+verify+accept round per dispatch (:mod:`.runtime`, built on
``ops.scan_loop.masked_scan`` and emitting the multistep harvest plane)
plus the acceptance-driven per-request γ policy (:mod:`.controller`).
The standalone ``serving.speculative`` loop is NOT part of the serving
path anymore — it survives only as the reference oracle for parity tests
(enforced statically in tests/test_static.py)."""

from .controller import AdaptiveGammaController
from .runtime import (
    SPEC_ADAPTIVE_ENV,
    accept_reject,
    build_ngram_round_fn,
    build_spec_round_fn,
    resolve_spec_adaptive,
)

__all__ = [
    "AdaptiveGammaController",
    "SPEC_ADAPTIVE_ENV",
    "accept_reject",
    "build_ngram_round_fn",
    "build_spec_round_fn",
    "resolve_spec_adaptive",
]
