"""Token sampling (jittable, static-shaped — runs inside the decode step).

Covers the sampling surface the reference's served engines expose via the
OpenAI API (temperature / top_p / top_k / greedy; vllm_inference.py client
:309-345 and openai_compatible/client.py)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    max_tokens: int = 128
    stop: tuple[str, ...] = ()
    seed: int | None = None  # per-request determinism (OpenAI `seed`)
    #: relative deadline in seconds from submit (``x-mtpu-deadline-ms`` over
    #: HTTP). Past it, queued requests are cancelled and in-flight decodes
    #: aborted with finish_reason="deadline" (scheduling/admission.py).
    deadline_s: float | None = None


def seeded_row_keys(
    key: jax.Array,
    seeds: jax.Array,  # [B] int32; >=0 selects the seeded derivation
    step_ids: jax.Array,  # [B] int32 per-slot decode position
) -> jax.Array:  # [B, 2] PRNG keys
    """Per-row sampling keys, (seed, position)-derived for seeded rows.

    A row with ``seeds[i] >= 0`` gets ``fold_in(fold_in(PRNGKey(0),
    seed), step_id)`` — a function of the REQUEST's seed and its absolute
    decode position only. This is the exactness anchor the multi-step
    decode runtime relies on (docs/multistep.md): classic one-block-
    per-dispatch and N-step macro dispatch burn the engine key
    differently, but every real request carries a seed (submit() assigns
    ``auto_seed`` when the caller passes none), so its sampled tokens
    depend on nothing the dispatch shape changes. Unseeded rows fall back
    to splits of the per-dispatch engine ``key`` and make no cross-shape
    promise."""
    B = seeds.shape[0]
    base_keys = jax.random.split(key, B)

    def row_key(i):
        seeded = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), seeds[i]), step_ids[i]
        )
        return jnp.where(seeds[i] >= 0, seeded, base_keys[i])

    return jax.vmap(row_key)(jnp.arange(B))


def sample(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32 (0 = off)
    seeds: jax.Array | None = None,  # [B] int32; >=0 rows use fold_in(seed,
    #                                  step) instead of the engine key, so a
    #                                  request with seed= samples identically
    #                                  regardless of batch composition
    step_ids: jax.Array | None = None,  # [B] int32 per-slot decode step
) -> jax.Array:  # [B] int32
    """Vectorized per-slot sampling; temperature 0 means greedy."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)

    t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / t

    def _mask_topk_topp(scaled):
        # top-k: mask everything below the k-th logit
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
        k_idx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
        kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=-1)
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

        # top-p (nucleus): keep the smallest prefix of the sorted
        # distribution with cumulative prob >= top_p
        sort_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
        sorted_scaled = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        keep_sorted = cum - probs_sorted < top_p[:, None]
        keep_sorted = keep_sorted.at[:, 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(keep_sorted.shape[0])[:, None], sort_idx
        ].set(keep_sorted)
        return jnp.where(keep, scaled, -jnp.inf)

    # both vocab-size sorts are dead weight for the common temperature-only
    # request mix — branch them out at RUNTIME (measured 4.8 ms/step at
    # 32k vocab on v5e; the decode hot loop runs this every step)
    needs_filter = jnp.any((top_p < 1.0) | (top_k > 0))
    scaled = jax.lax.cond(
        needs_filter, _mask_topk_topp, lambda s: s, scaled
    )

    if seeds is not None:
        B = logits.shape[0]
        if step_ids is None:
            step_ids = jnp.zeros((B,), jnp.int32)
        keys = seeded_row_keys(key, seeds, step_ids)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row)
        )(keys, scaled)
    else:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
