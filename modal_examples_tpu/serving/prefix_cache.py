"""Prefix cache: page-granular prompt KV reuse (the radix-cache analog).

SGLang's headline serving trick is radix-tree KV reuse across requests
(SURVEY.md §2.4: "same JAX engine; prefix KV reuse in the paged cache").
Here: prompts sharing a page-aligned token prefix share the physical KV
pages of that prefix — N chat sessions over one system prompt hold ONE copy
of its KV in HBM, which is the binding constraint on a 16GB chip.

Mechanics:
- a trie keyed by full-page token tuples; each node owns one physical page
  with a refcount of active users;
- ``acquire(tokens)`` walks the trie: matched nodes are shared (incref) and
  the caller allocates only the remaining pages; the caller then ``insert``s
  its own full prompt pages so later requests can share them;
- prefill recomputes K/V for shared positions and rewrites identical values
  into the shared pages (benign: same tokens + same weights => same KV;
  this keeps correctness decoupled from the compute-skip optimization,
  which chunked prefill enables later);
- zero-ref pages stay cached until ``evict()`` reclaims them LRU-first under
  allocator pressure. Decode never writes shared pages: a sequence's writes
  start at its first non-shared page.

int8 KV (``kv_dtype="int8"``, docs/kv_cache.md): sharing is by PHYSICAL
page id, and the quantized cache's f32 scale rows are indexed by the same
page ids as their int8 data — so a shared prefix page always travels with
its scale row, and nothing here changes. The rewrite-identical-values
property holds too: quantization (per token-head amax/127) is
deterministic, so same tokens + same weights => same int8 bytes AND same
scale rows when concurrent prefills rewrite a shared page. Bonus: int8
pages are half the HBM, so the same allocator headroom caches ~2x the
prefix pages before eviction pressure starts.
"""

from __future__ import annotations

import threading
import time

from ..observability import metrics as _obs


class _Node:
    __slots__ = ("page_id", "refcount", "children", "last_used")

    def __init__(self, page_id: int):
        self.page_id = page_id
        self.refcount = 0
        self.children: dict[tuple, _Node] = {}
        self.last_used = time.monotonic()


class PrefixCache:
    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self._root: dict[tuple, _Node] = {}
        self._by_page: dict[int, _Node] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0  # monotonic: pages reclaimed by evict()
        #: optional spill hook (set by the engine when a TieredPrefixCache
        #: wraps this trie): called with the page ids of each eviction wave
        #: BEFORE their pages return to the allocator, so the lower tier can
        #: read the device pages while they still hold valid KV. Runs under
        #: this cache's lock and on the cache-owning thread (evict is only
        #: reached from the engine's claim path) — it must not call back in.
        self.spill = None

    def _page_keys(self, tokens: list[int]) -> list[tuple]:
        n_full = len(tokens) // self.page_size
        return [
            tuple(tokens[i * self.page_size : (i + 1) * self.page_size])
            for i in range(n_full)
        ]

    # -- request lifecycle ---------------------------------------------------

    def acquire(self, tokens: list[int]) -> tuple[list[int], int]:
        """Longest shared page-aligned prefix: returns (shared page ids,
        n_shared_tokens); increfs every returned page."""
        shared: list[int] = []
        with self._lock:
            level = self._root
            for key in self._page_keys(tokens):
                node = level.get(key)
                if node is None:
                    break
                node.refcount += 1
                node.last_used = time.monotonic()
                shared.append(node.page_id)
                level = node.children
        # hit/miss accounting is the ENGINE's job at admission (acquire can
        # run multiple times for one request under OutOfPages retries)
        return shared, len(shared) * self.page_size

    def insert(
        self, tokens: list[int], page_ids: list[int], n_shared_pages: int
    ) -> tuple[list[int], list[int]]:
        """Register this request's full prompt pages beyond the shared prefix.

        ``page_ids``: the request's pages for the full prompt pages, in order
        (indices < n_shared_pages came from acquire()). Returns
        ``(final_pages, displaced)``: final_pages[i] is the canonical page for
        prompt page i (use these in the page table; release() them on
        finish); ``displaced`` are the caller's own pages superseded by a
        concurrent insert of the same content (free them immediately)."""
        keys = self._page_keys(tokens)
        final: list[int] = []
        displaced: list[int] = []
        with self._lock:
            level = self._root
            for i, key in enumerate(keys):
                node = level.get(key)
                if node is None:
                    node = _Node(page_ids[i])
                    node.refcount = 1
                    level[key] = node
                    self._by_page[node.page_id] = node
                elif i >= n_shared_pages:
                    # someone inserted this content first: adopt their page
                    node.refcount += 1
                    node.last_used = time.monotonic()
                    if page_ids[i] != node.page_id:
                        displaced.append(page_ids[i])
                else:
                    node.last_used = time.monotonic()  # our acquire()d prefix
                final.append(node.page_id)
                level = node.children
            _obs.set_prefix_cache_pages(len(self._by_page))
        return final, displaced

    def release(self, page_ids: list[int]) -> None:
        """Decref trie pages a finished request held (zero-ref pages stay
        cached until eviction)."""
        with self._lock:
            for pid in page_ids:
                node = self._by_page.get(pid)
                if node is not None and node.refcount > 0:
                    node.refcount -= 1

    def invalidate(self, page_ids: list[int]) -> None:
        """Decref AND drop these pages from the trie where possible — used
        when a prefill failed so the pages never got valid KV. (A shared node
        another live request holds stays: their own prefill rewrites it with
        correct values before any read.) Pages are NOT freed here; the caller
        owns them."""
        with self._lock:
            for pid in page_ids:
                node = self._by_page.get(pid)
                if node is not None and node.refcount > 0:
                    node.refcount -= 1
            # drop zero-ref childless nodes among them, deepest first
            for pid in reversed(page_ids):
                node = self._by_page.get(pid)
                if node is None or node.refcount > 0 or node.children:
                    continue
                parent = self._find_parent(node)
                if parent is not None:
                    children, key = parent
                    del children[key]
                    del self._by_page[pid]
            _obs.set_prefix_cache_pages(len(self._by_page))

    def _find_parent(self, target: _Node):
        def walk(children):
            for key, node in children.items():
                if node is target:
                    return children, key
                found = walk(node.children)
                if found:
                    return found
            return None

        return walk(self._root)

    # -- eviction ------------------------------------------------------------

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` zero-ref cached pages back to the
        allocator, oldest first, leaves before parents. Returns # freed.
        One trie walk collects a whole wave of evictable leaves; waves repeat
        only when removing leaves exposes evictable parents."""
        freed = 0
        with self._lock:
            while freed < n_pages:
                wave: list[tuple[dict, tuple, _Node]] = []

                def walk(children):
                    for key, node in children.items():
                        if not node.children and node.refcount == 0:
                            wave.append((children, key, node))
                        else:
                            walk(node.children)

                walk(self._root)
                if not wave:
                    break
                wave.sort(key=lambda t: t[2].last_used)
                batch: list[int] = []
                for children, key, node in wave[: n_pages - freed]:
                    del children[key]
                    del self._by_page[node.page_id]
                    batch.append(node.page_id)
                    freed += 1
                # one allocator call per wave: per-page frees would pay a
                # lock round-trip + 3 gauge writes per page on the
                # allocator-pressure path
                if self.spill is not None:
                    # HBM -> lower tier: serialize the evicted pages while
                    # their KV is still resident (docs/disagg.md)
                    self.spill(batch)
                self.allocator.free(batch)
            self.evictions += freed
            _obs.set_prefix_cache_pages(len(self._by_page))
        _obs.record_prefix_evictions(freed)
        return freed

    @property
    def cached_pages(self) -> int:
        with self._lock:
            return len(self._by_page)

    def stats(self) -> dict:
        """Occupancy/effectiveness snapshot for /metrics and `tpurun top`."""
        with self._lock:
            total = self.hits + self.misses
            return {
                "cached_pages": len(self._by_page),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_ratio": self.hits / total if total else 0.0,
            }
