"""In-flight request failover: decode checkpoints, live KV migration on
drain, and token-identical stream resumption (docs/failover.md).

Before this module, every failure boundary lost work: a decode replica
dying mid-stream errored every in-flight request, and fleet scale-in could
only wait for idle or force-reap live streams at ``drain_timeout``. The
repair is one small, self-contained piece of state — a
:class:`DecodeCheckpoint` capturing everything needed to resume a running
request — plus two paths that compose it:

- **proactive live migration** (:func:`migrate_request` /
  :func:`drain_replica`): the victim engine extracts the request's KV pages
  mid-decode on its scheduler thread (``LLMEngine.migrate_out``), ships
  them through the PR-6 MTKV1 chunked codec — the envelope grows a
  **decode-state leg** (``meta["resume"]``: accepted tokens + emitted-text
  cursor), a purely additive meta extension, so plain first-token blocks
  still decode — and the target reserves admission headroom *before any
  byte moves*, then adopts mid-decode through the ``submit_adopted`` lane
  generalized past first-token. Fleet scale-in drain time becomes one
  migration per request instead of request completion.
- **reactive failover** (:func:`resume_request` /
  :func:`stream_with_failover`): on replica death (router health flip,
  scheduler crash, mid-transfer ``TransportError``) the checkpoint alone
  is enough — the target re-prefills the ORIGINAL prompt (cheap when the
  tiered prefix cache still holds the blocks), replays the generated
  prefix teacher-forced through the decode program, and feeds the last
  accepted token at its original position.

**The exactness contract.** Per-request sampling is keyed
``(seed, position)`` (serving/sampling.py): the engine-assigned
``auto_seed`` rides the checkpoint, the resumed request's next token is
sampled at exactly the position the uninterrupted run would have used
(``LLMEngine.submit_resumed`` feeds the last accepted token through the
fresh-slot override lane rather than re-sampling it), and the rebuilt
prefix KV is BIT-identical to the decode-written KV it replaces — the
prompt via the same prefill program, the generated prefix via
``_replay_decode_prefix`` (the same decode block body the dead replica
ran; a prefill recompute of those positions drifts by a bf16 rounding
asymmetry and flips greedy argmaxes) — so the resumed stream is
**token-identical** to the uninterrupted one, greedy and seeded, bf16 and
int8 KV (tests/test_failover.py pins the matrix). Emission resumes at the
checkpoint's text cursor, and :func:`stream_with_failover` clips any
overlap, so the client stream continues with zero visible errors, zero
duplicated chars.

Both paths keep the SAME request object — same request id, same out_queue,
same trace id — so a blocked ``stream()`` consumer and the PR-9 stitched
timeline both continue across the takeover (the ``failover`` span marks
the seam).
"""

from __future__ import annotations

import time

from ..observability import metrics as _obs
from ..observability import reqtrace as _rt
from ..scheduling.admission import ShedError
from ..scheduling.policy import ScheduledRequest
from ..utils.log import get_logger
from .disagg.transport import (
    DEFAULT_CHUNK_BYTES,
    LoopbackChannel,
    TransferAborted,
    deserialize_block,
    serialize_block,
    transfer,
)

_log = get_logger("failover")

#: reactive takeovers per request before the error is surfaced honestly
DEFAULT_MAX_FAILOVERS = 2


class DecodeCheckpoint:
    """Everything needed to resume one running request on another replica.

    Built from the request object alone (:func:`checkpoint_request`) — the
    request carries its own accepted-token history and emitted-text cursor
    (``Request.generated_tokens`` / ``.emitted_len``), so a checkpoint can
    be taken *after* the owning replica died and its slot was recycled.
    ``prompt_tokens`` is always the ORIGINAL prompt (a resumed request's
    working ``prompt_tokens`` include the replayed prefix)."""

    __slots__ = (
        "request_id", "prompt", "prompt_tokens", "generated", "params",
        "auto_seed", "priority", "tenant", "deadline", "emitted_len",
    )

    def __init__(
        self, *, request_id, prompt, prompt_tokens, generated, params,
        auto_seed, priority, tenant, deadline, emitted_len,
    ):
        self.request_id = request_id
        self.prompt = prompt
        self.prompt_tokens = [int(t) for t in prompt_tokens]
        self.generated = [int(t) for t in generated]
        self.params = params
        self.auto_seed = auto_seed
        self.priority = priority
        self.tenant = tenant
        self.deadline = deadline
        self.emitted_len = int(emitted_len)

    @property
    def position(self) -> int:
        """Sequence position of the last accepted token (-1 + prompt len
        when nothing was generated yet)."""
        return len(self.prompt_tokens) + len(self.generated) - 1

    @property
    def tokens_replayed(self) -> int:
        """Generated-prefix tokens a reactive resume must re-prefill."""
        return max(0, len(self.generated) - 1)


def checkpoint_request(req) -> DecodeCheckpoint:
    """Snapshot ``req``'s resumable state. Safe after the owning replica
    died (the request object is the source of truth); on a live replica
    the scheduler may still be appending — use ``LLMEngine.migrate_out``
    for a consistent mid-decode extraction instead.

    Under the macro-step decode runtime (docs/multistep.md) this stays
    exact while a slot holds an in-flight N-step dispatch: the harvest
    plane appends only device-validated tokens to
    ``req.generated_tokens``, so a checkpoint taken mid-macro-step
    contains exactly the committed prefix — the un-harvested tail is
    discarded with the in-flight block and re-decoded on resume, and
    sampling's (seed, position) keying makes the re-decode identical."""
    base = getattr(req, "_orig_prompt_tokens", None)
    if base is None:
        base = req.prompt_tokens or []
    return DecodeCheckpoint(
        request_id=req.request_id,
        prompt=req.prompt,
        prompt_tokens=base,
        generated=list(req.generated_tokens),
        params=req.params,
        auto_seed=req.auto_seed,
        priority=req.priority,
        tenant=req.tenant,
        deadline=req.deadline,
        emitted_len=req.emitted_len,
    )


def checkpoint_from_block(block, req) -> DecodeCheckpoint:
    """Checkpoint recovered from an extracted MTKV1 block's decode-state
    leg — the reactive fallback when a live migration fails after
    extraction (the block's meta is the scheduler-thread-consistent record;
    the request object may not have been updated since)."""
    resume = block.meta.get("resume") or {}
    return DecodeCheckpoint(
        request_id=block.meta.get("request_id", req.request_id),
        prompt=req.prompt,
        prompt_tokens=block.meta.get("prompt_tokens") or req.prompt_tokens,
        generated=resume.get("generated", []),
        params=req.params,
        auto_seed=block.meta.get("auto_seed", req.auto_seed),
        priority=req.priority,
        tenant=req.tenant,
        deadline=req.deadline,
        emitted_len=resume.get("emitted_len", 0),
    )


def _reopen_trace(req):
    """A terminally-closed trace context (the dead replica's release path
    recorded the root with status=error) reopened as a NON-owning context
    on the same trace id: the resumed legs keep stitching onto the same
    timeline without minting a second root (the PR-9 no-dup-root rule)."""
    ctx = req.trace
    if ctx is None or not getattr(ctx, "done", False):
        return ctx
    reopened = _rt.from_wire(
        {"trace_id": ctx.trace_id, "parent_id": ctx.root.span_id},
        store=ctx.store,
    )
    return reopened if reopened is not None else ctx


def _finish_marker(reason: str):
    from .engine import _Finish

    return _Finish(reason)


def resume_request(
    req,
    target,
    *,
    checkpoint: DecodeCheckpoint | None = None,
    source: str = "?",
    t_detect: float | None = None,
) -> bool:
    """Reactive failover: resubmit ``req`` from its decode checkpoint onto
    ``target`` (an ``EngineReplica``). Returns True when the resumed
    request was accepted — the caller keeps draining the SAME out_queue.
    False (target shed it / refused) leaves the request terminal; the
    caller surfaces the original error honestly."""
    t0 = t_detect if t_detect is not None else time.monotonic()
    ckpt = checkpoint if checkpoint is not None else checkpoint_request(req)
    req.trace = _reopen_trace(req)
    # opened BEFORE the resubmission: a resume with nothing left to decode
    # terminates inside submit_resumed, and the terminal sweep then closes
    # this span WITH the takeover on record (a post-hoc record would no-op
    # against the already-closed context)
    sp = _rt.begin(
        req.trace, "failover", replica="fleet", mode="reactive",
        source=source, target=target.name, position=ckpt.position,
        tokens_replayed=ckpt.tokens_replayed,
    )
    try:
        target.engine.submit_resumed(
            req,
            prompt_tokens=ckpt.prompt_tokens,
            generated=ckpt.generated,
            emitted_len=ckpt.emitted_len,
        )
    except (ShedError, ValueError, RuntimeError) as e:
        _log.warning(
            "failover of %s -> %s refused (%s: %s)",
            req.request_id, target.name, type(e).__name__, e,
        )
        _obs.record_failover("reactive", "failed")
        _rt.finish(req.trace, sp, status="error", result="failed")
        return False
    req._router_replica = target
    _obs.record_failover(
        "reactive", "ok", tokens_replayed=ckpt.tokens_replayed
    )
    _obs.record_failover_takeover(time.monotonic() - t0)
    _rt.finish(req.trace, sp, result="ok")
    return True


def migrate_request(
    source,
    target,
    req,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    max_rounds: int = 3,
    channel_factory=None,
) -> str:
    """Proactive live migration of one request from ``source`` to
    ``target`` (both ``EngineReplica``): reserve-then-extract-then-adopt.
    Returns ``"ok"`` (adopted mid-decode), ``"resumed"`` (reactive resume
    after a requeue/wire failure — still zero client-visible errors),
    ``"aborted"`` (client abort / deadline during the migration; honest
    terminal marker delivered), ``"gone"`` (nothing to move), or
    ``"failed"`` (target shed the reservation AND the resume; the request
    stays wherever it was).

    Admission pages are reserved on the target BEFORE any byte moves (the
    PR-6 rule: a shed is an honest refusal, never a half-migrated
    request); abort/deadline trips between chunks release the reservation
    and the victim's pages on both sides."""
    eng_t = target.engine
    t0 = time.monotonic()
    t_wall = time.time()
    entry = ScheduledRequest(
        payload=req,
        priority=req.priority,
        tenant=req.tenant,
        cost=eng_t.request_cost(req),
        deadline=req.deadline,
        enqueued_at=eng_t._clock(),
    )
    occ = eng_t.cache.occupancy()
    try:
        eng_t.admission.admit(
            entry,
            depths=eng_t.policy.depths(),
            pages_used=occ["pages_used"],
            pages_total=occ["pages_total"],
        )
    except ShedError:
        _obs.record_live_migration("failed")
        _rt.record_span(
            req.trace, "failover", start=t_wall, status="error",
            replica="fleet", mode="migrate", source=source.name,
            target=target.name, result="failed",
        )
        return "failed"
    try:
        kind, block = source.engine.migrate_out(req)
    except Exception as e:
        # the victim's scheduler is dead or unresponsive: its release path
        # (or the stream-level reactive failover) owns this request now —
        # a second resubmission here would double-deliver the stream
        eng_t.admission.release(entry)
        _log.warning(
            "migrate_out of %s from %s failed (%s: %s); leaving it to the "
            "reactive path", req.request_id, source.name,
            type(e).__name__, e,
        )
        _obs.record_live_migration("failed")
        return "failed"
    if kind == "gone":
        eng_t.admission.release(entry)
        return "gone"
    if kind == "requeue":
        # queued or mid-prefill: nothing decoded, nothing to ship — a
        # fresh resubmission on the target is token-identical
        eng_t.admission.release(entry)
        ok = resume_request(
            req, target, source=source.name, t_detect=t0
        )
        return "resumed" if ok else "failed"

    def should_abort() -> bool:
        if req.aborted:
            return True
        if req.deadline is not None and eng_t._clock() >= req.deadline:
            req.deadline_expired = True
            return True
        return False

    sp = _rt.begin(
        req.trace, "failover", replica="fleet", mode="migrate",
        source=source.name, target=target.name,
    )
    try:
        with _rt.active(
            req.trace,
            parent=sp.span_id if sp is not None else None,
            replica="fleet",
        ):
            payload = serialize_block(block)
            wire = transfer(
                payload,
                (channel_factory or LoopbackChannel)(),
                transfer_id=req.request_id,
                chunk_bytes=chunk_bytes,
                max_rounds=max_rounds,
                should_abort=should_abort,
            )
            if should_abort():
                raise TransferAborted(req.request_id)
            eng_t.submit_adopted(req, entry, deserialize_block(wire))
        req._router_replica = target
        tokens = len(block.meta.get("resume", {}).get("generated", []))
        _obs.record_live_migration("ok", tokens=tokens)
        _obs.record_live_migration_seconds(time.monotonic() - t0)
        _obs.record_failover_takeover(time.monotonic() - t0)
        _rt.finish(
            req.trace, sp,
            position=int(block.meta.get("position", -1)),
            tokens_replayed=0, result="ok",
        )
        return "ok"
    except TransferAborted:
        eng_t.admission.release(entry)
        _obs.record_live_migration("aborted")
        if req.deadline_expired:
            _obs.record_deadline_miss("migrating")
        reason = "deadline" if req.deadline_expired else "stop"
        _rt.finish(req.trace, sp, status="aborted", result="aborted")
        _rt.finish_request(req, reason)
        req.out_queue.put(_finish_marker(reason))
        return "aborted"
    except Exception as e:
        # wire corruption beyond retry, adopt failure: the victim already
        # released its pages, but the block's decode-state leg is a full
        # checkpoint — fall back to the reactive re-prefill resume
        eng_t.admission.release(entry)
        _log.warning(
            "live migration of %s (%s -> %s) failed (%s: %s); reactive "
            "resume", req.request_id, source.name, target.name,
            type(e).__name__, e,
        )
        _rt.finish(req.trace, sp, status="error", result="fallback")
        ok = resume_request(
            req, target, checkpoint=checkpoint_from_block(block, req),
            source=source.name, t_detect=t0,
        )
        # recorded AFTER the resume attempt so the label is the truth:
        # "fallback" = the reactive resume carried it, "failed" = it did
        # not and the caller got an honest error
        _obs.record_live_migration("fallback" if ok else "failed")
        if not ok:
            _rt.finish_request(req, "error")
            req.out_queue.put(_finish_marker("error"))
        return "resumed" if ok else "failed"


def drain_replica(
    victim,
    router,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    channel_factory=None,
) -> dict:
    """Move every request ``victim`` still owns onto the rest of the fleet
    (the autoscaler's drain-by-migration step, docs/failover.md). The
    victim must already be OUT of placement (``router.remove_replica``),
    so no new work arrives while this runs. Returns counts:
    ``{"migrated", "resumed", "failed", "tokens_migrated"}`` —
    ``tokens_migrated`` is what ``fleet.jsonl`` records instead of
    requests killed."""
    eng = victim.engine
    out = {"migrated": 0, "resumed": 0, "failed": 0, "tokens_migrated": 0}
    # queued entries first: nothing decoded, a fresh resubmission is exact
    for entry in eng.policy.drain():
        req = entry.payload
        eng.admission.release(entry)
        eng._close_queue_span(req)
        if req.aborted:
            eng._finish_stream(
                req,
                _finish_marker(
                    "deadline" if req.deadline_expired else "stop"
                ),
            )
            continue
        target = router.failover_target(exclude=victim.name)
        if target is None or not resume_request(
            req, target, source=victim.name
        ):
            out["failed"] += 1
            eng._finish_stream(req, _finish_marker("error"))
        else:
            out["resumed"] += 1
    # then live slots: checkpoint + KV extraction on the scheduler thread
    for slot in list(eng.slots):
        req = slot.request
        if req is None:
            continue
        target = router.failover_target(exclude=victim.name)
        if target is None:
            out["failed"] += 1
            continue
        n_before = len(req.generated_tokens)
        result = migrate_request(
            victim, target, req,
            chunk_bytes=chunk_bytes, channel_factory=channel_factory,
        )
        if result == "ok":
            out["migrated"] += 1
            out["tokens_migrated"] += n_before
        elif result == "resumed":
            out["resumed"] += 1
            out["tokens_migrated"] += n_before
        elif result in ("failed",):
            out["failed"] += 1
    return out


def stream_with_failover(front, req, *, max_failovers: int | None = None):
    """Yield ``req``'s text pieces, transparently resuming on another
    replica when the owning one fails — the stream splice. ``front`` is a
    router-like object (``replica_for`` / ``failover_target``). An
    ``"error"`` terminal marker triggers a checkpoint resume instead of
    surfacing; the resumed engine continues emission from the checkpoint's
    text cursor, and any overlap with what was already delivered (the
    cursor can trail the queue by one piece when the crash landed between
    the put and the cursor update) is clipped here — zero duplicated
    chars, zero visible errors. After ``max_failovers`` takeovers (or with
    no healthy target) the error surfaces honestly."""
    budget = (
        max_failovers if max_failovers is not None else DEFAULT_MAX_FAILOVERS
    )
    delivered = 0
    skip = 0
    failovers = 0
    while True:
        replica = front.replica_for(req)
        for piece in replica.stream(req):
            if skip:
                cut = min(skip, len(piece))
                piece = piece[cut:]
                skip -= cut
                if not piece:
                    continue
            delivered += len(piece)
            yield piece
        if req.finish_reason != "error" or req.aborted:
            return
        if failovers >= budget:
            return
        failovers += 1
        t_detect = time.monotonic()
        ckpt = checkpoint_request(req)
        target = front.failover_target(exclude=replica.name)
        if target is None:
            _obs.record_failover("reactive", "failed")
            return
        if not resume_request(
            req, target, checkpoint=ckpt, source=replica.name,
            t_detect=t_detect,
        ):
            return
        skip = max(0, delivered - ckpt.emitted_len)
