"""Streaming ASR: incremental windowed Whisper with local-agreement
stabilization — the TPU-native counterpart of the reference's websocket
streaming-ASR tier (/root/reference/06_gpu_and_ml/speech-to-text/
streaming_kyutai_stt.py — websocket partial transcripts; cache_aware_
buffer.py — buffered incremental decoding over a window).

Whisper's encoder attends globally over its window, so a causal encoder
cache does not exist for it; the production streaming recipe
(whisper_streaming's LocalAgreement) is:

1. buffer incoming PCM; every ``hop_s`` seconds re-transcribe the current
   segment (audio since the last segment boundary);
2. emit only the STABLE prefix: tokens that two consecutive updates agree
   on (LocalAgreement-2) — later audio within the segment can no longer
   change them;
3. when the segment reaches ``window_s``, commit its full transcription
   and roll over to a fresh segment — per-update cost is bounded by the
   window, and token/audio alignment stays trivial (nothing ever slides
   out from under committed text).

TPU-first: every update transcribes ONE static mel shape (the segment is
padded to the full window), so the jitted encode+greedy-decode program
compiles once per transcriber, not per chunk length.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class StreamingResult:
    stable_text: str  # newly committed text this update ("" if none)
    partial_text: str  # best current guess past the committed point
    committed_text: str  # everything committed so far


class StreamingTranscriber:
    """Incremental transcription over window-bounded segments.

    feed() accepts arbitrary-size float32 PCM chunks (16 kHz mono) and
    returns a StreamingResult per update; flush() commits the tail.
    """

    def __init__(
        self,
        params,
        cfg,
        *,
        bos_id: int,
        eos_id: int,
        sample_rate: int = 16000,
        window_s: float = 8.0,
        hop_s: float = 1.0,
        max_tokens: int = 48,
        decode_text=None,  # token list -> str (defaults to chr() join)
    ):
        import jax

        from ..models import whisper
        from ..utils import audio

        self.params = params
        self.cfg = cfg
        self.bos_id, self.eos_id = bos_id, eos_id
        self.sr = sample_rate
        self.window = int(window_s * sample_rate)
        self.hop = int(hop_s * sample_rate)
        self.max_tokens = max_tokens
        self._decode_text = decode_text or (
            lambda toks: "".join(chr(t) for t in toks)
        )
        self._audio = audio

        self._segment = np.zeros((0,), np.float32)  # current segment's PCM
        self._pending = np.zeros((0,), np.float32)  # beyond the window cap
        self._since_update = 0
        self._committed: list[int] = []  # across all segments
        self._seg_committed = 0  # committed tokens in the CURRENT segment
        self._prev_tail: list[int] = []

        def transcribe(mel):
            return whisper.greedy_transcribe(
                params, mel, cfg, bos_id=bos_id, eos_id=eos_id,
                max_tokens=max_tokens,
            )

        self._transcribe = jax.jit(transcribe)

    # -- internals ----------------------------------------------------------

    def _segment_tokens(self) -> list[int]:
        """Transcribe the current segment padded to the full window."""
        pcm = self._segment
        if len(pcm) < self.window:
            pcm = np.concatenate(
                [pcm, np.zeros(self.window - len(pcm), np.float32)]
            )
        mel = self._audio.log_mel_spectrogram(
            pcm, n_mels=self.cfg.n_mels
        )[None]  # [1, T, n_mels]
        toks = np.asarray(self._transcribe(mel))[0]
        out = []
        for t in toks.tolist():
            if t == self.eos_id:
                break
            out.append(t)
        return out

    @staticmethod
    def _common_prefix(a: list[int], b: list[int]) -> int:
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        return n

    def _update(self) -> StreamingResult:
        toks = self._segment_tokens()
        # committed tokens stay at the front of the segment's output (the
        # segment never slides); later updates may "revise" them but commits
        # are final — the standard streaming contract
        tail = toks[self._seg_committed:]
        agree = self._common_prefix(self._prev_tail, tail)
        newly = tail[:agree]
        self._committed.extend(newly)
        self._seg_committed += agree
        self._prev_tail = tail[agree:]
        return StreamingResult(
            stable_text=self._decode_text(newly),
            partial_text=self._decode_text(self._prev_tail),
            committed_text=self._decode_text(self._committed),
        )

    def _rollover(self) -> StreamingResult:
        """Segment hit the window cap: commit its full transcription and
        start a fresh segment from the pending audio."""
        toks = self._segment_tokens()
        newly = toks[self._seg_committed:]
        self._committed.extend(newly)
        # the next segment is capped at the window too (one huge feed()
        # chunk can leave more than a window pending — it must not break
        # the one-static-mel-shape contract or chunk-size invariance)
        self._segment = self._pending[: self.window]
        self._pending = self._pending[self.window:]
        self._seg_committed = 0
        self._prev_tail = []
        return StreamingResult(
            stable_text=self._decode_text(newly),
            partial_text="",
            committed_text=self._decode_text(self._committed),
        )

    # -- public API ---------------------------------------------------------

    def feed(self, pcm: np.ndarray) -> StreamingResult | None:
        """Append a PCM chunk; runs an update every ``hop_s`` of audio.
        Returns None when not enough new audio has arrived yet."""
        pcm = np.asarray(pcm, np.float32).reshape(-1)
        room = self.window - len(self._segment)
        self._segment = np.concatenate([self._segment, pcm[:room]])
        if len(pcm) > room:
            self._pending = np.concatenate([self._pending, pcm[room:]])
        self._since_update += len(pcm)
        if len(self._segment) >= self.window:
            self._since_update = 0
            return self._rollover()
        if self._since_update < self.hop:
            return None
        self._since_update = 0
        return self._update()

    def flush(self) -> StreamingResult:
        """End of stream: commit every remaining segment in full. Empty
        segments are skipped — transcribing pure padding would commit the
        model's hallucination for silence (the classic Whisper failure)."""
        out = None
        while True:
            if len(self._segment) == 0:
                newly = []
            else:
                toks = self._segment_tokens()
                newly = toks[self._seg_committed:]
            self._committed.extend(newly)
            if len(self._pending) == 0:
                out = StreamingResult(
                    stable_text=self._decode_text(newly),
                    partial_text="",
                    committed_text=self._decode_text(self._committed),
                )
                self._segment = np.zeros((0,), np.float32)
                self._seg_committed = 0
                self._prev_tail = []
                return out
            self._segment = self._pending[: self.window]
            self._pending = self._pending[self.window:]
            self._seg_committed = 0
            self._prev_tail = []
