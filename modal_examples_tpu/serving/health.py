"""Progress-watermark health: gray-failure detection for the serving fleet
(docs/health.md).

Every fault path in the package so far triggers on a *terminal* signal — an
exception, a crashed scheduler thread, a stream that puts ``"error"``. A
replica that silently wedges or merely goes slow (a stuck decode tick, a
stalled mid-transfer chunk, an alive-but-degraded host) is invisible to the
binary ``healthy()`` probe, and its streams hang until a per-request
deadline fires, if one was set at all. This module closes that gap by
detecting failure from **progress**, not from errors:

- :class:`EngineWatermarks` — cheap monotonic watermarks the scheduler
  thread already owns publishes for free: a tick counter, the last
  decode-block dispatch time, the last accepted-token time. One attribute
  store per event; no locks, no allocation, nothing on the hot path.
- :class:`TransferWatermarks` — a registry of in-flight chunked KV
  transfers (``disagg/transport.py``) keyed by transfer id, advanced per
  chunk, so a transfer that stops between chunks without an error is
  visible as a stale sequence watermark.
- :func:`classify` — pure function from a watermark snapshot to
  ``healthy | degraded | wedged``: a replica with outstanding work whose
  mandatory progress signals are all fresh is healthy; a stale signal past
  ``degraded_after_s`` marks it degraded; past ``wedged_after_s`` it is
  wedged. Idle replicas are always healthy — staleness only matters while
  there is work the replica is failing to advance.
- :class:`ReplicaMonitor` — the per-replica state machine with hysteresis:
  downgrades are immediate (detect fast), upgrades need ``clear_ticks``
  consecutive healthy observations (recover slowly, so a flapping replica
  cannot oscillate the router's placement every poll).
- :class:`FleetWatchdog` — the supervisor thread that walks the escalating,
  journaled recovery ladder (docs/health.md#the-recovery-ladder):

  1. **degraded** → the router down-weights placement
     (:meth:`~..scheduling.router.PrefixAffinityRouter.set_health_weight`,
     the graded signal next to the binary ``healthy()``): new requests
     prefer other replicas, in-flight ones keep streaming.
  2. **wedged transfer** → the watchdog requests an abort through the
     transfer registry; the transfer loop raises ``TransportError`` between
     chunks and the coordinator takes the PR-6 unified fallback — the
     request completes token-identically on the decode side.
  3. **wedged scheduler** → ``engine.stop(reason="error")``: every live
     stream gets a terminal error marker and the PR-12 reactive failover
     resumes it token-identically on a healthy peer; the error-stop poisons
     the engine, so the router's re-probe cycle (``EngineReplica.probe``)
     revives and restarts it once ``reprobe_s`` passes.
  4. **repeated wedges** → quarantine for ``quarantine_s``: the replica is
     held out of placement (``probe()`` refuses while quarantined) and the
     fleet autoscaler replaces the lost capacity via a snapshot warm boot
     (the ``quarantine`` scale-up trigger, docs/fleet.md).

Every ladder decision appends to ``<state_dir>/watchdog.jsonl`` (the
journal pattern) and counts in the watchdog metric series
(``mtpu_watchdog_replica_state`` / ``mtpu_watchdog_progress_age_seconds``
/ ``mtpu_watchdog_transitions_total`` / ``mtpu_watchdog_recoveries_total``)
— surfaced by ``tpurun health`` and the gateway's ``/health`` route.

LAYERING: this module is production code (the engine, transport, router,
and fleet import it); it is import-light and never imports the chaos
driver. Consumers read watermarks ONLY through this API
(``tests/test_static.py`` bans ad-hoc timestamp pokes), so the watermark
model can evolve without silent readers going stale.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..observability import incident as _incident
from ..observability import metrics as _obs
from ..observability import reqtrace as _rt
from ..observability.journal import named_journal
from ..utils.log import get_logger

_log = get_logger("health")

#: the classifier's output states, in severity order (gauge label values)
STATES = ("healthy", "degraded", "wedged", "quarantined")

#: ladder actions recorded in ``mtpu_watchdog_recoveries_total{action}``
ACTIONS = (
    "down_weight", "restore_weight", "abort_transfer", "stop_revive",
    "quarantine", "unquarantine",
)


class EngineWatermarks:
    """Monotonic progress watermarks published by the scheduler thread.

    Writes are single attribute stores on threads that already exist — the
    scheduler notes a tick, a decode-block dispatch, an accepted token —
    so publishing costs nothing measurable. Reads go through
    :meth:`snapshot`, which converts the raw timestamps into AGES against
    the same (injectable) clock, the only form consumers see.
    """

    __slots__ = ("_clock", "tick_seq", "last_tick_at", "last_dispatch_at",
                 "last_accept_at")

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self.tick_seq = 0
        self.last_tick_at = self._clock()
        self.last_dispatch_at: float | None = None
        self.last_accept_at: float | None = None

    def note_start(self) -> None:
        """The scheduler (re)started: reset every watermark to fresh.
        Without this, a revived engine carries the stale ages of its
        PREVIOUS life into the window between ``start()`` and its first
        tick — and with resumed work already queued, the watchdog would
        read seconds-stale watermarks against outstanding>0 and falsely
        wedge (and poison) the engine it just finished recovering."""
        self.last_tick_at = self._clock()
        self.last_dispatch_at = None
        self.last_accept_at = None

    def note_tick(self) -> None:
        """One scheduler tick completed its top-of-loop service point."""
        self.tick_seq += 1
        self.last_tick_at = self._clock()

    def note_dispatch(self) -> None:
        """One decode block was dispatched to the device."""
        self.last_dispatch_at = self._clock()

    def note_accept(self) -> None:
        """One generated token was accepted (host-visible progress)."""
        self.last_accept_at = self._clock()

    def snapshot(self, now: float | None = None) -> dict:
        """Ages of every watermark against ``now`` (default: the same
        clock the notes used — watchdog and engine must share a clock
        domain for the ages to mean anything)."""
        now = self._clock() if now is None else now
        return {
            "tick_seq": self.tick_seq,
            "tick_age": max(0.0, now - self.last_tick_at),
            "dispatch_age": (
                max(0.0, now - self.last_dispatch_at)
                if self.last_dispatch_at is not None
                else None
            ),
            "accept_age": (
                max(0.0, now - self.last_accept_at)
                if self.last_accept_at is not None
                else None
            ),
        }


class TransferWatermarks:
    """In-flight chunked-transfer progress registry (one per process).

    ``disagg/transport.transfer`` registers each transfer, advances the
    sequence watermark per chunk sent, and checks :meth:`abort_requested`
    between chunks — so a transfer that silently stops (a stalled pipe, a
    peer that went quiet without an error) is visible as a stale watermark,
    and the watchdog can break it into the coordinator's unified fallback
    instead of letting the request hang to its deadline.
    """

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        #: transfer id -> {seq, at, abort}
        self._active: dict[str, dict] = {}

    def begin(self, transfer_id: str) -> None:
        with self._lock:
            self._active[transfer_id] = {
                "seq": -1, "at": self._clock(), "abort": False,
            }

    def progress(self, transfer_id: str, seq: int) -> None:
        with self._lock:
            entry = self._active.get(transfer_id)
            if entry is not None:
                entry["seq"] = int(seq)
                entry["at"] = self._clock()

    def end(self, transfer_id: str) -> None:
        with self._lock:
            self._active.pop(transfer_id, None)

    def request_abort(self, transfer_id: str) -> bool:
        """Ask the sending loop to abort (idempotent). Returns True when
        this call newly armed the abort — the watchdog journals once."""
        with self._lock:
            entry = self._active.get(transfer_id)
            if entry is None or entry["abort"]:
                return False
            entry["abort"] = True
            return True

    def abort_requested(self, transfer_id: str) -> bool:
        with self._lock:
            entry = self._active.get(transfer_id)
            return bool(entry and entry["abort"])

    def stalled(self, older_than_s: float, now: float | None = None) -> list:
        """Transfer ids with no chunk progress for ``older_than_s`` and no
        abort armed yet — the watchdog's wedged-transfer candidates."""
        now = self._clock() if now is None else now
        with self._lock:
            return [
                tid
                for tid, e in self._active.items()
                if not e["abort"] and now - e["at"] >= older_than_s
            ]

    def snapshot(self, now: float | None = None) -> list:
        now = self._clock() if now is None else now
        with self._lock:
            return [
                {
                    "transfer_id": tid,
                    "seq": e["seq"],
                    "age_s": round(max(0.0, now - e["at"]), 6),
                    "abort": e["abort"],
                }
                for tid, e in self._active.items()
            ]


#: THE process-wide transfer registry: the transport layer writes it, the
#: watchdog reads it (tests build private instances with fake clocks)
transfers = TransferWatermarks()


@dataclasses.dataclass
class WatchdogPolicy:
    """Classification thresholds + ladder tuning (docs/health.md)."""

    #: stale mandatory progress signal past this -> degraded
    degraded_after_s: float = 2.0
    #: stale mandatory progress signal past this -> wedged
    wedged_after_s: float = 10.0
    #: a queued request older than this (while the engine ticks) -> degraded
    queue_age_degraded_s: float = 10.0
    #: chunked transfer with no sequence progress past this -> abort it
    transfer_stall_s: float = 5.0
    #: consecutive healthy observations before an upgrade (flap damping)
    clear_ticks: int = 2
    #: wedge episodes within ``wedge_window_s`` before quarantine
    quarantine_after: int = 2
    wedge_window_s: float = 120.0
    #: how long a quarantined replica is held out of placement
    quarantine_s: float = 30.0
    #: router placement weight while degraded (1.0 = normal)
    degraded_weight: float = 0.25

    def __post_init__(self):
        if not (0.0 < self.degraded_after_s <= self.wedged_after_s):
            raise ValueError(
                "need 0 < degraded_after_s <= wedged_after_s, got "
                f"{self.degraded_after_s} / {self.wedged_after_s}"
            )
        if not (0.0 < self.degraded_weight <= 1.0):
            raise ValueError(
                f"degraded_weight must be in (0, 1], got {self.degraded_weight}"
            )


def replica_snapshot(replica, now: float | None = None) -> dict:
    """One replica's progress snapshot — THE read surface for watermarks.

    Consumers (watchdog, ``EngineReplica.stats``, CLI/gateway renderers)
    come through here rather than poking engine timestamps directly, so
    the watermark model stays swappable (guarded in tests/test_static.py).
    Slot rows read the per-request last-accepted-token time — the request
    object already records it for TPOT telemetry.
    """
    eng = replica.engine
    wm = getattr(eng, "watermarks", None)
    snap = wm.snapshot(now) if wm is not None else {}
    snap["running"] = bool(getattr(eng, "_running", False))
    snap["outstanding"] = int(replica.outstanding())
    decodable = 0
    slots = []
    clock = getattr(eng, "_clock", time.monotonic)
    t = clock() if now is None else now
    for i, s in enumerate(getattr(eng, "slots", ())):
        req = s.request
        if req is None:
            continue
        if s.decodable:
            decodable += 1
        slots.append({
            "slot": i,
            "request_id": req.request_id,
            "accept_age": (
                round(max(0.0, t - req.last_token_at), 6)
                if req.last_token_at is not None
                else None
            ),
            "generated": len(req.generated_tokens),
        })
    snap["decodable"] = decodable
    snap["slots"] = slots
    oldest = None
    policy = getattr(eng, "policy", None)
    if policy is not None:
        oldest = policy.oldest_enqueued_at()
    snap["queue_head_age"] = (
        max(0.0, t - oldest) if oldest is not None else None
    )
    return snap


def progress_age(snap: dict) -> float | None:
    """The WORST stale age among the snapshot's mandatory progress signals
    (what ``mtpu_watchdog_progress_age_seconds`` reports), or None while
    idle — staleness only means anything against outstanding work."""
    if snap.get("outstanding", 0) <= 0:
        return None
    ages = [snap.get("tick_age", 0.0)]
    if snap.get("decodable", 0) > 0:
        for key in ("dispatch_age", "accept_age"):
            if snap.get(key) is not None:
                ages.append(snap[key])
    return max(ages)


def classify(snap: dict, policy: WatchdogPolicy) -> str:
    """Pure classification of one snapshot: ``healthy | degraded |
    wedged``. Idle replicas are healthy by definition; with outstanding
    work, the mandatory signals are the scheduler tick always, plus
    dispatch and accept while decodable slots exist. A queued head older
    than ``queue_age_degraded_s`` while the engine still ticks is degraded
    only — it may be a legitimate pages-full wait, which the wedge of the
    replica HOLDING the pages will surface instead."""
    age = progress_age(snap)
    if age is None:
        return "healthy"
    if age >= policy.wedged_after_s:
        return "wedged"
    if age >= policy.degraded_after_s:
        return "degraded"
    qh = snap.get("queue_head_age")
    if qh is not None and qh >= policy.queue_age_degraded_s:
        return "degraded"
    return "healthy"


class ReplicaMonitor:
    """Per-replica classification state machine with hysteresis.

    Downgrades apply immediately — detection speed is the point — while
    upgrades require ``clear_ticks`` consecutive healthy raw observations,
    so a replica oscillating around a threshold holds its degraded state
    instead of flapping the router's placement weight every poll.
    """

    def __init__(self, name: str, policy: WatchdogPolicy):
        self.name = name
        self.policy = policy
        self.state = "healthy"
        self._healthy_streak = 0
        #: monotonic times of wedge transitions (quarantine trigger window)
        self.wedge_times: list[float] = []
        #: the watchdog saw this replica's engine stopped (our own stop, a
        #: fleet reap, an operator): the next running observation resets
        #: the state machine — a revived engine is a FRESH engine, and a
        #: re-wedge must be a new transition that fires the ladder again,
        #: not a continuation of the old wedge that nothing acts on
        self.saw_stopped = False

    def reset(self) -> None:
        """Back to healthy with no streak; the quarantine window's wedge
        history is deliberately KEPT — repeated wedges across revivals are
        exactly what quarantine exists to catch."""
        self.state = "healthy"
        self._healthy_streak = 0
        self.saw_stopped = False

    def observe(self, raw: str, now: float) -> tuple[str, bool]:
        """Fold one raw classification in; returns ``(state, changed)``."""
        prev = self.state
        if raw == "healthy":
            self._healthy_streak += 1
            if (
                self.state != "healthy"
                and self._healthy_streak >= self.policy.clear_ticks
            ):
                self.state = "healthy"
        else:
            self._healthy_streak = 0
            order = {"healthy": 0, "degraded": 1, "wedged": 2}
            # downgrades are immediate; a degraded observation while wedged
            # does not soften the state (only the healthy streak upgrades)
            if order[raw] > order.get(self.state, 0):
                self.state = raw
        if self.state == "wedged" and prev != "wedged":
            self.wedge_times.append(now)
            lo = now - self.policy.wedge_window_s
            self.wedge_times = [t for t in self.wedge_times if t >= lo]
        return self.state, self.state != prev

    def wedges_in_window(self, now: float) -> int:
        lo = now - self.policy.wedge_window_s
        return sum(1 for t in self.wedge_times if t >= lo)


class FleetWatchdog:
    """The fleet-level supervisor: poll replica watermarks, classify, and
    walk the escalating recovery ladder (module docstring; docs/health.md).

    ``router`` is duck-typed (``replicas`` / ``set_health_weight``);
    ``clock`` must share a domain with the engines' injectable clocks for
    the ages to be meaningful (production: ``time.monotonic`` everywhere).
    ``poll_once`` is the whole control loop — tests drive it directly with
    a fake clock; :meth:`start` runs it on a daemon thread.
    """

    def __init__(
        self,
        router,
        *,
        policy: WatchdogPolicy | None = None,
        poll_s: float = 0.5,
        clock=None,
        journal_path=None,
        transfer_watermarks: TransferWatermarks | None = None,
        registry=None,
    ):
        self.router = router
        self.policy = policy or WatchdogPolicy()
        self.poll_s = float(poll_s)
        self._clock = clock or time.monotonic
        self.journal = named_journal("watchdog", path=journal_path)
        self._transfers = (
            transfer_watermarks if transfer_watermarks is not None else transfers
        )
        self._registry = registry
        self._monitors: dict[str, ReplicaMonitor] = {}
        #: replica name -> quarantine expiry (this watchdog's clock)
        self._quarantined_until: dict[str, float] = {}
        self.events: list[dict] = []  # every ladder decision, newest last
        self._lock = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None

    # -- journal/metrics plumbing -------------------------------------------

    def _record(self, rec: dict) -> None:
        rec = {"at": time.time(), **rec}
        self.journal.record(rec)
        with self._lock:
            self.events.append(rec)
            del self.events[:-512]

    def _publish_state(self, name: str, state: str) -> None:
        for s in STATES:
            _obs.set_watchdog_state(
                name, s, s == state, registry=self._registry
            )

    # -- the control loop ----------------------------------------------------

    def poll_once(self) -> list[dict]:
        """One watchdog pass over transfers + replicas; returns the ladder
        actions taken (also journaled and appended to :attr:`events`)."""
        now = self._clock()
        actions: list[dict] = []
        actions += self._poll_transfers(now)
        live: set[str] = set()
        for replica in list(self.router.replicas):
            live.add(replica.name)
            if not getattr(replica, "serves_requests", True):
                # prefill-role replicas run no scheduler loop: their gray
                # failures surface as stalled transfers, handled above
                continue
            actions += self._poll_replica(replica, now)
        self._forget_removed(live)
        return actions

    def _forget_removed(self, live: set[str]) -> None:
        """Drop the monitor, quarantine entry, and gauge cells of every
        replica the fleet removed (scale-down, forced reap). Without this,
        ``tpurun health`` / ``/health`` / ``stats()`` report the ghost at
        its last state forever, and a replica removed mid-quarantine leaks
        its ``_quarantined_until`` entry."""
        with self._lock:
            stale = [n for n in self._monitors if n not in live]
            for name in stale:
                del self._monitors[name]
        for name in stale:
            self._quarantined_until.pop(name, None)
            # zero every cell (no Registry remove API): the surfaces keep
            # only replicas whose one-hot state reads >= 1
            for s in STATES:
                _obs.set_watchdog_state(name, s, False, registry=self._registry)
            _obs.set_watchdog_progress_age(name, 0.0, registry=self._registry)

    def _poll_transfers(self, now: float) -> list[dict]:
        out = []
        for tid in self._transfers.stalled(self.policy.transfer_stall_s, now):
            if not self._transfers.request_abort(tid):
                continue
            _obs.record_watchdog_recovery(
                "abort_transfer", registry=self._registry
            )
            rec = {
                "action": "abort_transfer",
                "transfer_id": tid,
                "stall_s": round(self.policy.transfer_stall_s, 3),
            }
            self._record(rec)
            _log.warning(
                "watchdog: aborting stalled transfer %s (no chunk progress "
                "for %.1fs); coordinator takes the unified fallback",
                tid, self.policy.transfer_stall_s,
            )
            out.append(rec)
        return out

    def _poll_replica(self, replica, now: float) -> list[dict]:
        name = replica.name
        out: list[dict] = []
        until = self._quarantined_until.get(name)
        if until is not None:
            if now >= until:
                self._quarantined_until.pop(name, None)
                replica.quarantined = False
                _obs.record_watchdog_recovery(
                    "unquarantine", registry=self._registry
                )
                rec = {"action": "unquarantine", "replica": name}
                self._record(rec)
                out.append(rec)
                # state stays wedged until real healthy observations clear
                # it through the normal streak — no shortcut
            else:
                self._publish_state(name, "quarantined")
                return out
        with self._lock:
            mon = self._monitors.get(name)
            if mon is None:
                mon = self._monitors[name] = ReplicaMonitor(
                    name, self.policy
                )
        if not getattr(replica.engine, "_running", False):
            # stopped engine (by us, by the fleet, or never started): the
            # router's health/probe cycle owns it — observing a stopped
            # scheduler as "wedged" would double-fire the ladder
            mon.saw_stopped = True
            self._publish_state(name, mon.state)
            return out
        if mon.saw_stopped:
            # the engine was stopped and is running again (probe revival):
            # reset the state machine so a RE-wedge of the fresh engine is
            # a new transition that fires the ladder — a monitor stuck
            # "wedged" across the revival would mask it (changed=False)
            # and hang the revived replica's streams forever
            was_degraded = mon.state == "degraded"
            mon.reset()
            if was_degraded:
                # the degraded rung's down-weight would otherwise outlive
                # the restart: reset() forces state healthy, so the next
                # healthy observation is changed=False and _act_recovered
                # never fires — the revived replica would compete at
                # degraded_weight forever
                out += self._act_recovered(replica)
        snap = replica_snapshot(replica, now)
        raw = classify(snap, self.policy)
        age = progress_age(snap)
        _obs.set_watchdog_progress_age(
            name, 0.0 if age is None else age, registry=self._registry
        )
        state, changed = mon.observe(raw, now)
        replica.health_state = state
        self._publish_state(name, state)
        if not changed:
            return out
        _obs.record_watchdog_transition(state, registry=self._registry)
        rec = {
            "action": "transition",
            "replica": name,
            "state": state,
            "raw": raw,
            "progress_age_s": round(age, 6) if age is not None else None,
            "tick_seq": snap.get("tick_seq"),
            "outstanding": snap.get("outstanding"),
            "decodable": snap.get("decodable"),
        }
        self._record(rec)
        out.append(rec)
        if state == "degraded":
            out += self._act_degraded(replica)
        elif state == "wedged":
            out += self._act_wedged(replica, mon, now, snap)
        elif state == "healthy":
            out += self._act_recovered(replica)
        return out

    # -- the ladder ----------------------------------------------------------

    def _set_weight(self, name: str, weight: float) -> bool:
        setter = getattr(self.router, "set_health_weight", None)
        if setter is None:
            return False
        setter(name, weight)
        return True

    def _act_degraded(self, replica) -> list[dict]:
        if not self._set_weight(replica.name, self.policy.degraded_weight):
            return []
        _obs.record_watchdog_recovery("down_weight", registry=self._registry)
        rec = {
            "action": "down_weight",
            "replica": replica.name,
            "weight": self.policy.degraded_weight,
        }
        self._record(rec)
        return [rec]

    def _act_recovered(self, replica) -> list[dict]:
        if not self._set_weight(replica.name, 1.0):
            return []
        _obs.record_watchdog_recovery(
            "restore_weight", registry=self._registry
        )
        rec = {"action": "restore_weight", "replica": replica.name}
        self._record(rec)
        return [rec]

    def _act_wedged(self, replica, mon, now: float, snap: dict) -> list[dict]:
        out: list[dict] = []
        # placement weight is moot once the ladder stops the engine; the
        # router's down/probe cycle takes over from here
        self._set_weight(replica.name, 1.0)
        quarantine = (
            mon.wedges_in_window(now) >= self.policy.quarantine_after
        )
        if quarantine:
            replica.quarantined = True
            self._quarantined_until[replica.name] = (
                now + self.policy.quarantine_s
            )
            self._publish_state(replica.name, "quarantined")
        action = "quarantine" if quarantine else "stop_revive"
        # mark live traced requests BEFORE the stop sweeps their spans:
        # the stitched timeline then shows the watchdog's intervention
        # between the hang and the failover seam
        eng = replica.engine
        for s in list(getattr(eng, "slots", ())):
            req = s.request
            if req is not None and req.trace is not None:
                _rt.event(
                    req.trace, "watchdog",
                    store=getattr(eng, "_trace_store", None),
                    replica=replica.name, state="wedged", action=action,
                )
        _log.warning(
            "watchdog: replica %s wedged (progress age %.2fs, tick_seq %s); "
            "%s — live streams take the reactive failover",
            replica.name, progress_age(snap) or -1.0,
            snap.get("tick_seq"), action,
        )
        # incident bundle BEFORE the error-stop sweeps the victim's slots:
        # the bundle's open-request traces (and the watchdog events just
        # marked on them) are the evidence of what was mid-flight when the
        # chip wedged (docs/observability.md#incident-bundles)
        _incident.capture(
            "watchdog_quarantine" if quarantine else "watchdog_wedge",
            reason=(
                f"progress age {progress_age(snap) or -1.0:.2f}s, "
                f"tick_seq {snap.get('tick_seq')}, "
                f"wedges_in_window {mon.wedges_in_window(now)}"
            ),
            replica=replica.name,
            registry=self._registry,
        )
        try:
            # error-stop: every live stream gets a terminal error (the
            # PR-12 reactive failover resumes it on a healthy peer) and
            # the engine is poisoned until the router's re-probe revives
            # and restarts it — or until quarantine lifts
            eng.stop(reason="error")
        except Exception:
            _log.exception(
                "watchdog: stop of wedged replica %s failed", replica.name
            )
        _obs.record_watchdog_recovery(action, registry=self._registry)
        rec = {
            "action": action,
            "replica": replica.name,
            "wedges_in_window": mon.wedges_in_window(now),
            **(
                {"quarantine_s": round(self.policy.quarantine_s, 3)}
                if quarantine
                else {}
            ),
        }
        self._record(rec)
        out.append(rec)
        return out

    # -- lifecycle / surfaces ------------------------------------------------

    def start(self) -> "FleetWatchdog":
        if self._running:
            return self
        self._running = True

        def loop():
            while self._running:
                try:
                    self.poll_once()
                except Exception:
                    _log.exception("watchdog poll failed")
                time.sleep(self.poll_s)

        self._thread = threading.Thread(
            target=loop, name="fleet-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def stats(self) -> dict:
        """Live snapshot (the half of ``/health`` that cannot be rebuilt
        from pushed metrics when the watchdog runs in-process)."""
        now = self._clock()
        with self._lock:
            events = list(self.events[-50:])
            monitors = dict(self._monitors)
        return {
            "replicas": {
                name: {
                    "state": mon.state,
                    "wedges_in_window": mon.wedges_in_window(now),
                    "quarantined_until": self._quarantined_until.get(name),
                }
                for name, mon in monitors.items()
            },
            "transfers": self._transfers.snapshot(now),
            "events": events,
        }


def decode_watchdog_series(registry) -> dict:
    """Decode the watchdog metric series back into plain dicts — the ONE
    decoder shared by every surface (``tpurun health``/``top``, the
    gateway ``/health`` view), so the series shape (one-hot state labels,
    per-replica age) can evolve without the renderers drifting apart.

    ``registry`` duck-types ``.series(name)``: the live default registry
    in-process, or a merged parsed exposition for pushed metrics. Returns
    ``{"states", "ages", "transitions", "recoveries"}``; ``states`` keeps
    only replicas whose one-hot cell reads active (zeroed ghosts drop out).
    """
    from ..observability import catalog as C

    return {
        "states": {
            lbls.get("replica", "?"): lbls.get("state", "?")
            for lbls, v in registry.series(C.WATCHDOG_REPLICA_STATE)
            if v >= 1
        },
        "ages": {
            lbls.get("replica", "?"): v
            for lbls, v in registry.series(C.WATCHDOG_PROGRESS_AGE_SECONDS)
        },
        "transitions": {
            lbls.get("state", "?"): v
            for lbls, v in registry.series(C.WATCHDOG_TRANSITIONS_TOTAL)
        },
        "recoveries": {
            lbls.get("action", "?"): v
            for lbls, v in registry.series(C.WATCHDOG_RECOVERIES_TOTAL)
        },
    }
