"""Continuous-batching LLM engine — the vLLM-engine replacement, TPU-first.

Implements the serving core behind the reference's north-star example
(vllm_inference.py: an OpenAI-compatible server wrapping an engine with
continuous batching, paged KV, streaming; SURVEY.md §3.2's HOT LOOP).

TPU-first architecture (vs vLLM's CUDA design):
- **static shapes everywhere**: the decode step is ONE jitted program over a
  fixed slot count; requests come and go by flipping an ``active`` mask and
  rewriting page tables — XLA never recompiles as batch composition changes.
- **prefill buckets**: prompts pad to the next bucket (128/256/.../max) so
  prefill compiles once per bucket, not per length (the retrace-thrash
  killer; SURVEY.md §7 hard part #5).
- **sampling fused into the decode program**: only the sampled token ids
  (max_slots x int32) cross the device->host boundary per step.
- **page cache donated** through the step so XLA updates KV in place.
- host side: admission (claim slot + pages), stop handling, incremental
  detokenization, per-request output queues. The scheduler favors admitting
  prefills as slots free up — the same continuous-batching policy vLLM's
  scheduler applies.
- **stall-free admission** (docs/scheduling.md): an optional per-tick
  prefill token budget slices chunked prefills across scheduler ticks and
  defers every prefill's first-token read until after the next decode
  dispatch, so a long-prompt arrival can never stall in-flight streams by
  more than ~one prefill chunk — prefill/decode interference becomes a
  scheduled property instead of an accident of arrival order.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from ..observability import incident as _incident
from ..observability import metrics as _obs
from ..observability import profiler as _profiler
from ..observability import reqtrace as _rt
from ..observability import timeseries as _ts
from ..observability import usage as _usage
from ..scheduling.admission import AdmissionController, ShedError
from ..scheduling.policy import (
    DEFAULT_CLASS,
    FairSharePolicy,
    ScheduledRequest,
    SchedulerPolicy,
    validate_class,
)
from ..faults import inject as _inject
from ..faults.inject import FaultError as _FaultError
from ..observability.canary import CANARY_TENANT as _CANARY_TENANT
from ..utils.log import get_logger
from .health import EngineWatermarks
from .kv_cache import OutOfPages, PagedKVCache
from .sampling import SamplingParams, sample
from . import spec_runtime as _spec_rt
from ..utils.tokenizer import load_tokenizer

_log = get_logger("engine")


def _tm(tick, phase: str) -> None:
    """Close the interval since the tick's last mark into ``phase`` — THE
    way scheduler code feeds the hot-path profiler (docs/observability.md).
    ``tick`` is None whenever profiling is off, so the disabled hot path is
    one branch: no timestamp, no allocation (the faults-gate zero-cost
    contract; tests/test_profiler.py pins this shape at the AST level, and
    tests/test_static.py pins the phase names to catalog.TICK_PHASES)."""
    if tick is not None:
        tick.mark(phase)


def _tm_device(tick, phase: str) -> None:
    """`_tm`, additionally counting the interval as DEVICE-blocked time (a
    blocking read of a device array) — the device half of the profiler's
    host-vs-device split behind ``mtpu_host_overhead_ratio``."""
    if tick is not None:
        tick.mark(phase, device=True)


@dataclasses.dataclass
class Request:
    prompt: str
    params: SamplingParams
    request_id: str = dataclasses.field(
        default_factory=lambda: f"req-{uuid.uuid4().hex[:12]}"
    )
    prompt_tokens: list[int] | None = None
    out_queue: queue.Queue = dataclasses.field(default_factory=queue.Queue)
    created: float = dataclasses.field(default_factory=time.monotonic)
    aborted: bool = False
    finish_reason: str | None = None  # set when the terminal marker arrives
    # token-level telemetry (monotonic clock): TTFT = first_token_at -
    # created; inter-token gaps feed the TPOT histogram. n_generated is the
    # request's own generated-token count (streaming usage reporting).
    first_token_at: float | None = None
    last_token_at: float | None = None
    n_generated: int = 0
    # failover state (serving/failover.py, docs/failover.md): the request
    # carries its OWN accepted-token history — the slot's ``generated``
    # list is this very object — so a decode checkpoint can be built from
    # the request alone after its replica died (the slot is recycled; the
    # request survives). ``emitted_len`` mirrors the slot's emitted-text
    # cursor for the same reason: a resumed stream continues emission from
    # exactly here, so the client never sees a duplicated or missing char.
    generated_tokens: list = dataclasses.field(default_factory=list)
    emitted_len: int = 0
    # engine-assigned when params.seed is None: sampling is derived from
    # (auto_seed, position) so outputs never depend on scheduler timing —
    # how many blocks/keys the engine happened to burn before this request.
    # Speculative mode included: temperature>0 lanes never speculate (the
    # fused round's γ=0 classic lane samples them with this very key;
    # docs/speculative.md#exactness).
    auto_seed: int | None = None
    # multimodal: preprocessed [S, S, 3] float image (models.vlm); its
    # n_image_tokens placeholder ids lead prompt_tokens
    image: object | None = None
    # prefix-cache keying sequence when it must differ from prompt_tokens:
    # multimodal requests key image positions by CONTENT-hash ids (outside
    # the vocab) so identical images share KV and different ones never do
    cache_key_tokens: list | None = None
    # scheduling (modal_examples_tpu/scheduling): priority class + tenant
    # drive the fair-share policy; deadline is ABSOLUTE in the engine's
    # clock domain (params.deadline_s resolved at submit). deadline_expired
    # marks an abort as a deadline miss so the stream finishes with
    # finish_reason="deadline" instead of "stop".
    priority: str = DEFAULT_CLASS
    tenant: str = "default"
    deadline: float | None = None
    deadline_expired: bool = False
    # prefix-cache accounting (observability/usage.py + the OpenAI usage
    # contract's prompt_tokens_details.cached_tokens): prompt tokens whose
    # KV came from already-cached pages (trie hits + tier promotions)
    # instead of being recomputed — set at page claim
    cached_prompt_tokens: int = 0
    # distributed request tracing (observability/reqtrace.py): the
    # RequestTraceContext minted at the entry point, or None when tracing
    # is disabled/sampled out — every trace touch point is None-safe
    trace: object | None = None


@dataclasses.dataclass
class _Slot:
    request: Request | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    trie_pages: list[int] = dataclasses.field(default_factory=list)  # release()
    private_pages: list[int] = dataclasses.field(default_factory=list)  # free()
    position: int = 0  # position of the NEXT token to decode
    last_token: int = 0
    fresh: bool = False  # just prefilled: first token rides the override lane
    generated: list[int] = dataclasses.field(default_factory=list)
    emitted_text_len: int = 0
    ngram: "_NgramIndex | None" = None  # prompt-lookup spec mode only
    #: pin this tenancy's speculation depth to 0 (draft mode only): set for
    #: failover-resumed/adopted installs whose draft cache has a
    #: generated-prefix KV hole — proposing against it would collapse
    #: acceptance. The lane rides the fused round's classic γ=0 path, so
    #: the stream stays token-identical either way (docs/speculative.md).
    spec_hold: bool = False
    #: resumable chunked-prefill state (stall-free admission): set while the
    #: slot's prompt KV is still being filled chunk-by-chunk across ticks
    prefill: "_PendingPrefill | None" = None
    #: prefill dispatched, first sampled token not yet harvested (it sits on
    #: the engine's pending-harvest queue as a device array)
    pending_first: bool = False
    #: monotonically increasing per-install id: in-flight block/harvest
    #: snapshots pin (request, tenancy), not request identity alone — a
    #: failover-resumed request is the SAME object re-admitted, and a stale
    #: block from its previous tenancy must not feed the new one
    tenancy: int = 0
    #: engine-clock timestamp of this tenancy's install — the usage meter
    #: charges the occupancy interval (device-seconds, KV page-seconds) to
    #: the tenant when the slot's pages release (observability/usage.py)
    claimed_at: float = 0.0

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def decodable(self) -> bool:
        """Admitted AND holding a first token to feed decode: slots whose
        prefill is mid-flight (sliced chunks pending, or first token not
        yet harvested) are excluded from decode dispatch."""
        return (
            self.request is not None
            and self.prefill is None
            and not self.pending_first
        )


@dataclasses.dataclass
class _PendingPrefill:
    """Per-slot resumable chunked-prefill state (stall-free admission):
    ``_admit`` advances at most a budget's worth of chunks per tick, so a
    decode dispatch always lands between chunks and the inter-token stall
    other streams see is bounded by ONE chunk, not the whole prompt."""

    req: Request
    table: object  # np page-table row shared with self._page_tables
    offset: int = 0  # token offset of the NEXT chunk to dispatch
    ticks: int = 0  # scheduler ticks that dispatched at least one chunk
    suspensions: int = 0  # times the budget paused this prefill mid-prompt
    logits: object | None = None  # last dispatched chunk's logits (device)
    t_start: float = 0.0  # monotonic, for the phase histogram
    t_wall: float = 0.0  # wall-clock, for trace spans


class _NgramIndex:
    """Incremental per-slot n-gram index for prompt-lookup speculation.

    Replaces the per-tick O(window x n) rescan of each slot's full history:
    the index is built ONCE per request from the prompt (O(prompt), off the
    decode hot path) and updated in O(1) per accepted token, so a proposal
    tick costs O(gamma) per slot. Semantics match the rescan exactly: the
    proposal is the continuation of the MOST RECENT occurrence of the
    trailing n-gram strictly before the tail itself, with the match start
    confined to the last ``lookback`` tokens (vLLM's prompt_lookup_max
    analog).
    """

    __slots__ = ("n", "lookback", "hist", "occ")

    def __init__(self, n: int, prompt: list[int], lookback: int):
        self.n = n
        self.lookback = lookback
        self.hist: list[int] = []
        #: n-gram tuple -> ascending start positions of its occurrences
        self.occ: dict[tuple, list[int]] = {}
        for tok in prompt:
            self.push(tok)

    def push(self, token: int) -> None:
        """Append one accepted token; records the n-gram it completes."""
        self.hist.append(token)
        start = len(self.hist) - self.n
        if start >= 0:
            gram = tuple(self.hist[start:])
            self.occ.setdefault(gram, []).append(start)

    def propose(self, gamma: int) -> list[int]:
        """Up to ``gamma`` continuation tokens after the most recent
        earlier occurrence of the current tail n-gram ([] = no proposal,
        which degrades that slot to one plain verify step)."""
        hist, n = self.hist, self.n
        if len(hist) <= n:
            return []
        tail_start = len(hist) - n
        occs = self.occ.get(tuple(hist[tail_start:]))
        if not occs:
            return []
        lo = max(0, len(hist) - self.lookback)
        # occs is ascending; the last entry is the tail itself (pushed when
        # its final token arrived), so scan backwards for the first start
        # strictly before it — and inside the lookback window
        for j in reversed(occs):
            if j < tail_start:
                if j < lo:
                    return []  # every earlier occurrence is older still
                return hist[j + n : j + n + gamma]
        return []


@dataclasses.dataclass
class EngineStats:
    prompt_tokens: int = 0
    generated_tokens: int = 0
    steps: int = 0
    spec_proposed: int = 0  # draft tokens proposed (speculative mode)
    spec_accepted: int = 0  # draft tokens accepted by the target
    started: float = dataclasses.field(default_factory=time.monotonic)

    def tokens_per_second(self) -> float:
        dt = time.monotonic() - self.started
        return self.generated_tokens / dt if dt > 0 else 0.0

    def acceptance_rate(self) -> float:
        return self.spec_accepted / self.spec_proposed if self.spec_proposed else 0.0


def _unstable_tail(text: str) -> bool:
    """True when the last char may still change as more tokens arrive: the
    replacement char (HF tokenizers mid-codepoint) or a surrogate-escaped
    byte (ByteTokenizer mid-codepoint) — either way, emitting it now would
    stream a char that the next token's re-decode replaces."""
    if not text:
        return False
    c = ord(text[-1])
    return c == 0xFFFD or 0xDC80 <= c <= 0xDCFF


def _stop_safe_len(text: str, stop: tuple[str, ...]) -> int:
    """Longest prefix of ``text`` that cannot be the start of a pending stop
    match: anything past it must be withheld until the stop either completes
    (then truncated) or can no longer match (then flushed)."""
    safe = len(text)
    for stop_s in stop:
        lo = max(0, len(text) - len(stop_s) + 1)
        for start in range(lo, len(text)):
            if stop_s.startswith(text[start:]):
                safe = min(safe, start)
                break
    return safe


class _Finish:
    """Terminal stream marker carrying the OpenAI finish_reason."""

    __slots__ = ("reason",)

    def __init__(self, reason: str = "stop"):
        self.reason = reason


_FINISH = _Finish("stop")


def _req_seed(req: "Request") -> int:
    """The seed sample() uses for this request's rows: the user's, else the
    engine-assigned auto_seed (-1 only if neither exists, e.g. warmup)."""
    if req.params.seed is not None:
        return req.params.seed
    return req.auto_seed if req.auto_seed is not None else -1


def _shard_params(params, cfg, mesh):
    """Place a llama param tree with its Megatron partition specs — one
    implementation for target and draft so the paths can't drift.

    Quantized trees shard too (vLLM serves quantized TP the same way):
    the int payload takes the weight's spec; the per-output-channel scale
    keeps the OUTPUT dim's sharding but never the contraction dim's (its
    contraction axis has size 1). layers.mm multiplies the scale after the
    dot, so row-parallel partial sums are all-reduced before rescaling —
    the math is exact under auto-partitioning.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..models.quantize import QuantizedWeight

    specs = llama.partition_specs(cfg)

    def place(p, s):
        if isinstance(p, QuantizedWeight):
            scale_spec = (
                P(*(tuple(s[:-2]) + (None, s[-1]))) if len(s) >= 2 else s
            )
            return QuantizedWeight(
                q=jax.device_put(p.q, NamedSharding(mesh, s)),
                scale=jax.device_put(p.scale, NamedSharding(mesh, scale_spec)),
            )
        return jax.device_put(p, NamedSharding(mesh, s))

    return jax.tree.map(
        place,
        params,
        specs,
        is_leaf=lambda x: isinstance(x, (P, QuantizedWeight)),
    )


#: the MODEL_NAME surface (vllm_inference.py:54-58) — shared by build_engine
#: and the speculative draft resolver so the two can never drift
MODEL_PRESETS = {
    "llama2-7b": llama.LlamaConfig.llama2_7b,
    "llama3-8b": llama.LlamaConfig.llama3_8b,
    "llama3.1-8b": llama.LlamaConfig.llama31_8b,
    "llama3.2-1b": llama.LlamaConfig.llama32_1b,
    "mistral-7b": llama.LlamaConfig.mistral_7b,
    "mixtral-8x7b": llama.LlamaConfig.mixtral_8x7b,
    "tiny": llama.LlamaConfig.tiny,
    "tiny-moe": llama.LlamaConfig.tiny_moe,
}


class LLMEngine:
    #: every scheduler-loop traceback from ANY engine in this process,
    #: recorded eagerly (survives engine GC) — the test suite's session-end
    #: sentinel asserts this stays empty, so a swallowed scheduler
    #: exception anywhere is a loud failure. Capped at 50.
    _error_reports: list = []

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params=None,
        *,
        model_dir: str | None = None,
        max_slots: int = 16,
        page_size: int = 16,
        max_model_len: int = 1024,
        n_pages: int | None = None,
        prefill_buckets: tuple[int, ...] = (128, 256, 512, 1024, 2048),
        prefill_batch: int = 4,  # the one compiled prefill batch shape
        enable_prefix_cache: bool = True,
        quantization: str | None = None,  # "int8": weight-only quant serving
        seed: int = 0,
        # page-cache dtype: "int8" = quantized KV (half the decode HBM
        # traffic + residency; tolerance-based accuracy, docs/kv_cache.md),
        # a jnp dtype, or None -> MTPU_KV_DTYPE env -> bfloat16
        kv_dtype=None,
        speculative: tuple | None = None,  # (draft preset|LlamaConfig, gamma)
        draft_params=None,
        draft_model_dir: str | None = None,
        # adaptive speculation depth (docs/speculative.md#gamma-schedule):
        # the per-request EWMA/pressure controller that shrinks γ toward 0
        # when acceptance drops or the batch fills. None resolves
        # MTPU_SPEC_ADAPTIVE once (the knob rule); True/False override.
        # Runtime-mutable (self.spec_adaptive, like self.spec_depth), so
        # benches A/B fixed-vs-adaptive on a live engine.
        spec_adaptive: bool | None = None,
        decode_block: int = 8,  # decode steps rolled into one dispatch
        # macro-step decode (docs/multistep.md): N decode+sample steps
        # fused into ONE jitted program per dispatch, with device-side
        # stop-token/length early exit and per-slot validity masks. None
        # resolves MTPU_DECODE_STEPS once (the knob rule); 1 = the classic
        # pipelined block path, byte-identical fall-through. Runtime-
        # mutable like prefill_budget (read once per dispatch), so benches
        # A/B it on a live engine.
        decode_steps: int | None = None,
        # stall-free admission (docs/scheduling.md): max prompt tokens the
        # scheduler may convert into prefill work per tick. None resolves
        # through MTPU_PREFILL_BUDGET (empty env = unlimited); an explicit
        # 0 forces UNLIMITED, env ignored — the classic admit-everything
        # behavior, and what bench children pass. With a budget, chunked
        # prefills slice across ticks and short-prompt admissions stop once
        # the budget is spent, so a decode dispatch lands between chunks
        # and in-flight streams never stall behind a whole long prompt.
        # Disagg prefill-role replicas run unbudgeted by construction:
        # prefill_sync never takes the budgeted _admit path, and
        # EngineReplica(role="prefill") zeroes the budget explicitly.
        max_prefill_tokens_per_tick: int | None = None,
        mesh=None,  # jax Mesh with a "tensor" axis: tensor-parallel serving
        paged_impl: str | None = None,  # decode structure; None: env/default
        scatter_impl: str | None = None,  # KV scatter; None: env/default
        vision: tuple | None = None,  # (models.vlm.VLMConfig, vision_params)
        policy: SchedulerPolicy | None = None,  # waiting-set ordering
        admission: AdmissionController | None = None,  # shed/deadline gate
        clock=None,  # injectable monotonic clock (fake-clock scheduling tests)
        # hot-path profiler (observability/profiler.py): None resolves
        # MTPU_PROFILE once (the MTPU_KV_DTYPE rule); True/False override.
        # Off = self.profiler stays None and the scheduler tick takes ZERO
        # new timestamps, so chaos/loadgen runs can't silently pay
        # profiling cost; bench configs opt in explicitly.
        profile=None,
        # tiered prefix cache (docs/disagg.md): True for env-default sizing,
        # or a dict of TieredPrefixCache kwargs (host_bytes=, volume=);
        # evicted prefix pages spill HBM -> host RAM -> Volume and promote
        # back on the next shared-prefix prompt
        tiered_prefix=None,
        # request tracing: where THIS replica's spans land (default: the
        # process-wide store). A per-replica store still stitches — the
        # trace id is the request id, and reqtrace.read_trace merges
        trace_store=None,
    ):
        import os as _os

        from ..utils.compile_cache import enable_compile_cache

        enable_compile_cache()  # warm restarts hit disk, not the compiler
        # resolved ONCE here and passed explicitly into every jitted decode:
        # the env vars are not part of any jit cache key (ADVICE r3)
        self.paged_impl = paged_impl or _os.environ.get("MTPU_PAGED_IMPL", "xla")
        _known_impls = ("xla", "pallas", "xla-writeback", "pallas-writeback")
        if self.paged_impl not in _known_impls:
            raise ValueError(
                f"unknown paged_impl {self.paged_impl!r}; known: {_known_impls}"
            )
        self.scatter_impl = scatter_impl or _os.environ.get(
            "MTPU_SCATTER_IMPL", "xla"
        )
        if self.scatter_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown scatter_impl {self.scatter_impl!r} "
                "(arg or MTPU_SCATTER_IMPL); known: xla, pallas"
            )
        # per-tick prefill token budget, same resolve-once rule: explicit
        # arg beats MTPU_PREFILL_BUDGET beats unlimited (0). Mutable at
        # runtime (an int read once per _admit) so benches can A/B it.
        if max_prefill_tokens_per_tick is None:
            _raw_budget = _os.environ.get("MTPU_PREFILL_BUDGET", "")
            max_prefill_tokens_per_tick = int(_raw_budget) if _raw_budget else 0
        self.prefill_budget = max(0, int(max_prefill_tokens_per_tick))
        # cache dtype, same resolve-once rule as the impls: explicit arg
        # beats MTPU_KV_DTYPE beats the bf16 default ("int8" = quantized
        # pages + scale arrays, the 4-leaf cache)
        from ..ops.kv_quant import resolve_kv_dtype

        if kv_dtype is None:
            kv_dtype = _os.environ.get("MTPU_KV_DTYPE") or jnp.bfloat16
        kv_dtype = resolve_kv_dtype(kv_dtype)
        self.kv_dtype = "int8" if kv_dtype == "int8" else str(kv_dtype)
        self.cfg = cfg
        self.tokenizer = load_tokenizer(model_dir)
        from ..models.quantize import SUPPORTED as _QUANT_MODES

        if quantization not in _QUANT_MODES:
            raise ValueError(
                f"unknown quantization {quantization!r}; "
                f"supported: {_QUANT_MODES}"
            )
        if params is None:
            if model_dir is not None:
                # checkpoint loads quantize on the HOST (the bf16 tensors
                # never reach the device: ~7 GB HBM for a 7B int8 model,
                # ~3.5 GB int4)
                params = llama.load_hf_weights(
                    model_dir, cfg, quantization=quantization
                )
            elif quantization is not None:
                # init+quantize fused into ONE program so the bf16 tree is
                # an XLA-internal temporary, not a 13.5 GB resident peak
                from ..models.quantize import bits_of, init_quantized_llama

                params = init_quantized_llama(
                    jax.random.PRNGKey(seed), cfg, bits=bits_of(quantization)
                )
            else:
                params = llama.init_params(jax.random.PRNGKey(seed), cfg)
        elif quantization is not None:
            from ..models.quantize import bits_of, quantize_llama

            params = quantize_llama(params, bits=bits_of(quantization))

        # tensor parallelism is ONE ENGINE FLAG, not a separate code path
        # (matching vllm_inference.py:180's --tensor-parallel-size): weights
        # get the Megatron partition specs, the paged KV cache shards by kv
        # head, and the same jitted prefill/decode/spec programs run under
        # auto-partitioning — XLA inserts the ICI all-reduces. The Pallas
        # fast paths (flash prefill, ragged decode, scatter) keep running:
        # each kernel is dispatched through ops.sharded's shard_map wrappers
        # over the kv-head axis, so every device runs the unmodified Mosaic
        # kernel on its local head shard (the old mesh×pallas ValueError is
        # gone — round 7, ROADMAP open item #2).
        from ..ops import mesh_tp_degree

        self.mesh = mesh
        self.tp = mesh_tp_degree(mesh)
        self._attn_impl = "flash"
        if mesh is not None:
            if self.tp > 1 and (
                cfg.n_kv_heads % self.tp or cfg.n_heads % self.tp
            ):
                # the KV cache itself shards on the kv-head axis
                # (_shard_cache): a non-divisible head count cannot even be
                # placed, so fail with the real constraint up front
                raise ValueError(
                    f"n_kv_heads={cfg.n_kv_heads} / n_heads={cfg.n_heads} "
                    f"must be divisible by the tensor axis size {self.tp} "
                    "for kv-head-sharded TP serving"
                )
            params = _shard_params(params, cfg, mesh)
        self.params = params
        self.max_slots = max_slots
        self.max_model_len = max_model_len
        self.pages_per_slot = (max_model_len + page_size - 1) // page_size
        if n_pages is None:
            n_pages = 1 + max_slots * self.pages_per_slot
        self.cache = PagedKVCache.create(
            n_layers=cfg.n_layers,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
            n_pages=n_pages,
            page_size=page_size,
            kv_dtype=kv_dtype,
        )
        if mesh is not None:
            self._shard_cache(self.cache)
        # what will ACTUALLY run for these shapes on this backend — a
        # requested pallas impl can be shape-downgraded (sub-128 head_dim /
        # unaligned page_size; GQA runs the "grouped" ragged variant since
        # round 5), and the kv dtype changes the flat-variant legality —
        # record it so benches/metrics report the real path instead of the
        # requested one (ADVICE r4)
        self.impl_plan = llama.paged_impl_plan(
            cfg, page_size, self.paged_impl, self.scatter_impl,
            kv_dtype=self.kv_dtype, mesh=mesh,
        )
        _obs.set_decode_impl(self.impl_plan)
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_model_len
        ) or (max_model_len,)
        self.prefill_batch = max(1, min(prefill_batch, max_slots))
        from .prefix_cache import PrefixCache

        self.prefix_cache = (
            PrefixCache(self.cache.allocator, page_size)
            if enable_prefix_cache
            else None
        )
        # tiered prefix cache: wraps the trie with host-RAM/Volume spill
        # tiers riding the disagg page-(de)serialization machinery
        self.tiered = None
        if tiered_prefix and self.prefix_cache is not None:
            from .disagg.tiered_cache import TieredPrefixCache

            kw = dict(tiered_prefix) if isinstance(tiered_prefix, dict) else {}
            self.tiered = TieredPrefixCache(
                self.cache, self.prefix_cache, **kw
            )
            self.prefix_cache.spill = self.tiered.spill_pages

        # multimodal serving (models.vlm; the reference's sglang_vlm.py
        # workload): image requests prefill with the vision tower's
        # projected patch embeddings as the first n_image_tokens positions.
        self.vision_cfg = None
        self.vision_params = None
        if vision is not None:
            self.vision_cfg, self.vision_params = vision
            if self.vision_cfg.llm_dim != cfg.dim:
                raise ValueError(
                    f"vision projector dim {self.vision_cfg.llm_dim} != "
                    f"model dim {cfg.dim}"
                )
            if self.vision_cfg.n_image_tokens >= self.prefill_buckets[-1]:
                raise ValueError(
                    f"n_image_tokens {self.vision_cfg.n_image_tokens} must "
                    f"be < the largest prefill bucket "
                    f"{self.prefill_buckets[-1]} (multimodal prompts do not "
                    "chunk)"
                )
            if mesh is not None:
                # TP × vision (sglang_vlm.py serves VLMs with --tp-size):
                # image tokens are ordinary KV entries, so decode needs
                # nothing; the ViT tower is einsum-only (partitionable) and
                # small, so its weights replicate over the mesh and every
                # chip encodes the (shared) image — the LLM prefill behind
                # it runs sharded exactly like the text path.
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                rep = NamedSharding(mesh, P())
                self.vision_params = jax.tree.map(
                    lambda x: jax.device_put(x, rep), self.vision_params
                )
            if speculative is not None:
                raise ValueError(
                    "vision= with speculative= is not supported: the draft "
                    "model's cache would miss the image-token KV"
                )
        self._prefill_mm_jits: dict[object, object] = {}

        self.slots = [_Slot() for _ in range(max_slots)]
        # per-install tenancy ids (see _Slot.tenancy); bumped only on the
        # scheduler thread, where every install happens
        self._tenancy_seq = 0
        # scheduling: the waiting set is a pluggable SchedulerPolicy (PR 4;
        # replaces the single unbounded FIFO queue) — priority classes +
        # tenant fair share by default — gated by cost-aware admission
        # control (bounded per-class queues, KV-pressure shedding,
        # deadlines). A plain FIFO is one `policy=FIFOPolicy()` away.
        self._clock = clock or time.monotonic
        # progress watermarks (serving/health.py, docs/health.md): the
        # scheduler thread notes ticks/dispatches/accepts for free; the
        # fleet watchdog classifies gray failures from their ages. Shares
        # the engine's injectable clock so fake-clock tests see real ages.
        self.watermarks = EngineWatermarks(clock=self._clock)
        # hot-path profiler (docs/observability.md#hot-path-profiling):
        # resolved ONCE — explicit arg beats MTPU_PROFILE beats off. The
        # lazy name callable picks up the fleet's trace_name assignment.
        self.profiler = (
            _profiler.HotPathProfiler(
                clock=self._clock, name=lambda: self.trace_name
            )
            if _profiler.profiling_enabled(profile)
            else None
        )
        self._tick = None  # the in-flight TickProfile (None = off/idle)
        # flight recorder (docs/observability.md#metrics-history): MTPU_TSDB=1
        # starts the process-wide tsdb sampler ONCE (idempotent; its whole
        # cost is one locked registry pass per interval off the hot path —
        # the same zero-cost-when-off rule as the profiler above), and the
        # incident collector learns about this engine so a capture can
        # snapshot its watermarks / impl plan / open requests
        _ts.ensure_sampler()
        _incident.register_engine(self)
        self.policy: SchedulerPolicy = policy or FairSharePolicy(
            clock=self._clock
        )
        self.admission = admission or AdmissionController(clock=self._clock)
        # replica identity on request-trace spans ("engine" until an
        # EngineReplica adopts this engine under its fleet name)
        self.trace_name = "engine"
        self._trace_store = (
            trace_store if trace_store is not None else _rt.default_store
        )
        if trace_store is not None:
            _rt.register_store(self._trace_store)
        self.stats = EngineStats()
        # hardware-utilization accounting (observability/usage.py,
        # docs/observability.md#roofline-and-usage-accounting): the
        # analytic work model is frozen HERE — parameter count from the
        # config, true weight HBM bytes from the loaded tree, dtype-aware
        # KV bytes/token from the cache's own accounting — and the meter
        # shares the engine's injectable clock, so fake-clock runs meter
        # bit-reproducible MFU/MBU. Always on: the per-token cost is a few
        # integer adds (no extra timestamps), unlike the profiler.
        from ..models.quantize import param_bytes

        self.usage = _usage.EngineUsage(
            _usage.WorkModel.from_engine(
                cfg, cache=self.cache,
                weight_bytes=param_bytes(self.params),
            ),
            clock=self._clock,
            name=lambda: self.trace_name,
            chips=int(self.impl_plan.get("tp", 1) or 1),
        )
        # admission sheds are charged to the shedding tenant/class
        self.admission.usage = self.usage
        self.error_log: list[str] = []  # recent scheduler tracebacks
        self.error_count = 0  # monotonic (error_log is capped at 20)
        # MTPU_ENGINE_STRICT=1 (the test suite's default, conftest.py): a
        # scheduler-loop exception STOPS the engine and releases callers
        # with finish_reason="error" instead of being swallowed — closing
        # the round-2 "intermittent flake consistent with a swallowed
        # scheduler exception" loop (NOTES.md). Production default keeps
        # the loop alive (availability) but still records + counts.
        self.strict = _os.environ.get("MTPU_ENGINE_STRICT", "") not in ("", "0")
        self._stopped_on_error = False
        self._metrics_wall = 0.0  # last gauge refresh (throttled in step())
        # last stats totals flushed into the prometheus token counters
        # (counters take deltas; EngineStats holds the running totals)
        self._counter_flush = {"prompt": 0, "generated": 0, "steps": 0}
        self._key = jax.random.PRNGKey(seed)
        self._seed_base = int(seed)
        self._submit_seq = 0  # feeds auto_seed: deterministic per submission
        self._lock = threading.Lock()
        # serializes slot-free prefill_sync callers (disagg prefill role):
        # the prefill jits donate the cache arrays, so two server threads
        # must never run them concurrently. The pending count is the
        # prefill replica's load signal (EngineReplica.outstanding).
        self._prefill_sync_lock = threading.Lock()
        self._prefill_sync_pending = 0
        self._running = False
        self._thread: threading.Thread | None = None

        # host mirrors of device slot state
        self._page_tables = np.zeros((max_slots, self.pages_per_slot), np.int32)
        self._positions = np.zeros((max_slots,), np.int32)
        self._active = np.zeros((max_slots,), bool)
        self._tokens = np.zeros((max_slots,), np.int32)
        self._temps = np.ones((max_slots,), np.float32)
        self._top_ps = np.ones((max_slots,), np.float32)
        self._top_ks = np.zeros((max_slots,), np.int32)
        self._seeds = np.full((max_slots,), -1, np.int32)

        # pipelined multi-step decode (the dispatch-latency killer: one
        # blocking read per `decode_block` tokens, and the next block is
        # already queued on-device while the host reads the previous one —
        # measured 79 ms per blocking round trip on a tunneled v5e vs 1.5 ms
        # async-chained; vLLM's async scheduling solves the same problem)
        self.decode_block = max(1, int(decode_block))
        self._device_tokens = None  # [max_slots] device int32: last sampled
        self._opt_positions = np.zeros((max_slots,), np.int32)  # dispatch-side
        self._override = np.zeros((max_slots,), np.int32)
        self._override_mask = np.zeros((max_slots,), bool)
        import collections

        self._inflight = collections.deque()  # (tokens [K, B] device, snapshot)
        # stall-free admission state: finished prefills whose sampled first
        # token is still a device array — the blocking read is deferred
        # until AFTER the decode block for already-running slots has been
        # dispatched (entries: (tokens, rows, meta); rows pin request
        # identity like _inflight's snapshots)
        self._pending_harvest = collections.deque()
        # scheduler-thread control queue (serving/failover.py): operations
        # that must run next to the decode jits — live-migration checkpoint
        # extraction releases slot pages the in-flight blocks still
        # reference — enqueue (fn, result_queue) here and step() services
        # them at the top of each tick (_run_on_scheduler)
        self._ctrl = collections.deque()
        # last decode-block dispatch (monotonic); None while no decodable
        # slot exists — feeds mtpu_decode_stall_seconds
        self._last_dispatch_at: float | None = None

        self._block_jit = jax.jit(self._decode_block_fn, donate_argnums=(1, 2))
        # macro-step decode runtime (serving/multistep, docs/multistep.md)
        from .multistep.runtime import resolve_decode_steps

        self.decode_steps = resolve_decode_steps(decode_steps)
        self._multistep_jits: dict[int, object] = {}  # keyed by N
        self._detok = None  # lazy DetokWorker (first routed token)
        # tokens-per-dispatch accounting (harvest-side; feeds the
        # catalog MULTISTEP_* gauges through _refresh_gauges' throttle)
        self._ms_dispatches = 0
        self._ms_tokens = 0
        self._ms_flush = {"dispatches": 0, "tokens": 0}
        self._ms_tpd = 0.0
        self._prefill_jits: dict[int, object] = {}
        self._chunk_jits: dict[int, object] = {}  # keyed by chunk q_offset

        # speculative decoding (the engine-side flag the reference exposes:
        # vllm_inference.py:196-205), as a first-class scheduler decode
        # mode (docs/speculative.md): one fused round program per dispatch
        # — draft-propose(γ) on masked_scan + one ragged target verify +
        # accept in-graph (serving/spec_runtime/runtime.py) — emitting the
        # multistep harvest plane, so spec rounds and macro-step blocks
        # share ONE harvest site. The draft keeps its own paged KV cache
        # ADDRESSED BY THE SAME page ids/tables as the target's, so
        # allocation, prefix sharing, and slot recycling are managed once.
        self.spec_gamma = 0
        self.spec_mode: str | None = None  # "draft" | "ngram"
        self.draft_cfg = None
        if speculative is not None:
            draft, gamma = speculative
            self.spec_gamma = int(gamma)
            if self.spec_gamma < 1:
                raise ValueError("speculative gamma must be >= 1")
            if draft == "ngram":
                # prompt-lookup decoding (vLLM's --speculative-model
                # [ngram] analog): proposals come from matching the
                # sequence's trailing n-gram against its OWN history — no
                # second model, no draft HBM, no draft cache. The target
                # verifies the proposed continuation in one pass exactly
                # like draft-model mode.
                if draft_model_dir is not None or draft_params is not None:
                    raise ValueError(
                        "draft_model_dir/draft_params are incompatible with "
                        "speculative=('ngram', ...): prompt lookup uses no "
                        "draft model — drop them or pick a draft preset"
                    )
                self.spec_mode = "ngram"
                self.ngram_n = 2  # trailing-bigram lookup (prompt-lookup)
                self._ngram_jit = jax.jit(
                    _spec_rt.build_ngram_round_fn(cfg, gamma=self.spec_gamma),
                    donate_argnums=(1, 2),
                )
            else:
                if isinstance(draft, str):
                    if draft not in MODEL_PRESETS:
                        raise ValueError(
                            f"unknown draft preset {draft!r}; "
                            f"known: {sorted(MODEL_PRESETS)} (or 'ngram')"
                        )
                    draft = MODEL_PRESETS[draft]()
                if draft_model_dir is not None:
                    # the checkout's own config describes the draft weights
                    # (the preset name is then just a default for when no
                    # dir is given)
                    draft = llama.LlamaConfig.from_hf_config(
                        f"{draft_model_dir}/config.json"
                    )
                self.spec_mode = "draft"
                self.draft_cfg = draft
                if draft.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        f"draft vocab_size {draft.vocab_size} != target "
                        f"{cfg.vocab_size}: speculative accept/reject "
                        "compares token distributions and requires a shared "
                        "vocabulary"
                    )
                if draft_params is None:
                    if draft_model_dir is not None:
                        draft_params = llama.load_hf_weights(
                            model_dir=draft_model_dir, cfg=draft
                        )
                    else:
                        draft_params = llama.init_params(
                            jax.random.PRNGKey(seed + 1), draft
                        )
                if mesh is not None:
                    draft_params = _shard_params(draft_params, draft, mesh)
                self.draft_params = draft_params
                self.draft_cache = PagedKVCache.create(
                    n_layers=draft.n_layers,
                    n_kv_heads=draft.n_kv_heads,
                    head_dim=draft.head_dim,
                    n_pages=n_pages,
                    page_size=page_size,
                    kv_dtype=kv_dtype,
                    prefer_native=False,  # page ids from the target's allocator
                )
                if mesh is not None:
                    self._shard_cache(self.draft_cache)
                self._spec_jit = jax.jit(
                    _spec_rt.build_spec_round_fn(
                        cfg,
                        draft,
                        paged_impl=self.paged_impl,
                        scatter_impl=self.scatter_impl,
                        mesh=mesh,
                        gamma=self.spec_gamma,
                    ),
                    donate_argnums=(2, 3, 4, 5),
                )
                self._draft_prefill_jits: dict[object, object] = {}
        # adaptive γ (docs/speculative.md#gamma-schedule): both knobs are
        # runtime-mutable — spec_depth caps per-round proposal budgets
        # (0 = spec fully off, every round falls through to the classic
        # block program), spec_adaptive switches the per-request controller
        # on/off — so benches A/B off/fixed/adaptive on one live engine.
        self.spec_depth = self.spec_gamma
        self.spec_adaptive = _spec_rt.resolve_spec_adaptive(spec_adaptive)
        self._spec_ctrl = (
            _spec_rt.AdaptiveGammaController(self.spec_gamma)
            if self.spec_gamma
            else None
        )
        # spec round accounting (harvest-side; feeds the SPEC_* gauges
        # through _refresh_gauges' throttle — the _ms_* delta pattern)
        self._spec_rounds = 0
        self._spec_round_tokens = 0
        self._spec_fallbacks = 0
        self._spec_flush = {"rounds": 0, "tokens": 0, "fallbacks": 0}
        self._spec_tpd = 0.0
        self._spec_gamma_window: list[int] = []  # dispatched per-slot γs
        self._spec_gamma_p50 = 0.0

    def _shard_cache(self, cache) -> None:
        """Shard page arrays [L, P, ps, Hkv, D] by kv head over ``tensor`` —
        every cache byte and its attention math stay on the chip owning the
        head; page tables/ids remain host-global. int8 caches shard the
        [L, P, ps, Hkv] f32 scale arrays WITH their pages on the same Hkv
        axis, so dequant never crosses chips. The placement rule itself
        lives in ops.sharded.shard_cache_pages (shared with the TP
        microbench)."""
        from ..ops import shard_cache_pages

        cache.k_pages, cache.v_pages = shard_cache_pages(
            self.mesh, cache.k_pages, cache.v_pages
        )

    # -- jitted programs ----------------------------------------------------

    def _profiled(self, program: str, shape_key, fn):
        """THE compile-telemetry chokepoint (docs/observability.md): every
        jitted-program dispatch site wraps its callable here. Profiling
        off: returns ``fn`` untouched — no wrapper, no allocation (the
        zero-cost gate, AST-pinned in tests/test_profiler.py). On: the
        first dispatch of each (program, shape_key) is timed into
        ``mtpu_compile_seconds{program}`` and the compiles.jsonl ledger
        (begin event BEFORE the build, so a mid-compile crash/hang still
        names its program — the ≥40-slot ceiling diagnosis); later
        dispatches count as ``mtpu_compiles_total{cache="hit"}``."""
        prof = self.profiler
        if prof is None:
            return fn

        def run(*args, **kwargs):
            t0 = prof.compile_begin(program, shape_key)
            try:
                out = fn(*args, **kwargs)
            except BaseException:
                if t0 is not None:
                    # the build raised: forget the key so a retry is timed
                    # as a fresh miss, not misreported as a cache hit
                    prof.compile_abort(program, shape_key)
                raise
            prof.compile_end(program, shape_key, t0)
            return out

        return run

    def _decode_block_fn(
        self, params, k_pages, v_pages, prev_tokens, override, override_mask,
        positions, page_tables, active, key, temps, top_ps, top_ks, seeds,
    ):
        """`decode_block` decode+sample steps in one program: tokens feed
        forward in-graph (lax.scan), so nothing crosses the host boundary
        between steps. ``prev_tokens`` is the previous block's device-resident
        output; freshly prefilled slots merge their host-known first token via
        (override, override_mask). Returns (tokens [K, B], last [B], caches).
        """
        tok0 = jnp.where(override_mask, override, prev_tokens)

        def body(carry, k_i):
            tok, pos, kp, vp = carry
            logits, kp, vp = llama.decode_step(
                params, tok, pos, kp, vp, page_tables, active, self.cfg,
                impl=self.paged_impl, scatter_impl=self.scatter_impl,
                mesh=self.mesh,
            )
            nxt = sample(
                logits, k_i, temps, top_ps, top_ks, seeds=seeds, step_ids=pos
            )
            nxt = jnp.where(active, nxt, tok)  # dead slots hold steady
            return (nxt, pos + 1, kp, vp), nxt

        (last, _, k_pages, v_pages), toks = jax.lax.scan(
            body,
            (tok0, positions, k_pages, v_pages),
            jax.random.split(key, self.decode_block),
        )
        return toks, last, k_pages, v_pages

    def _multistep_jit(self, n: int):
        """The N-step macro decode program (serving/multistep/runtime.py),
        built lazily per N — the knob is runtime-mutable, and each value
        is its own compiled program (shape key ``s{slots}n{N}``)."""
        jit = self._multistep_jits.get(n)
        if jit is None:
            from .multistep.runtime import build_multistep_fn

            fn = build_multistep_fn(
                self.cfg,
                paged_impl=self.paged_impl,
                scatter_impl=self.scatter_impl,
                mesh=self.mesh,
                eos_id=self.tokenizer.eos_id,
                n_steps=n,
            )
            jit = self._multistep_jits[n] = jax.jit(
                fn, donate_argnums=(1, 2)
            )
        return jit

    def _ensure_detok(self):
        """The lazy detokenization worker (serving/multistep/detok.py). A
        dead worker is replaced — owned streams re-register from their
        ``req.emitted_len`` cursor on the next accepted token."""
        w = self._detok
        if w is None or not w.alive:
            from .multistep.detok import DetokWorker

            w = DetokWorker(
                tokenizer=self.tokenizer,
                deliver=self._deliver_finish,
                safe_len=_stop_safe_len,
                unstable_tail=_unstable_tail,
                name=self.trace_name,
            )
            self._detok = w
        return w

    def _prefill_and_sample(
        self, params, k_pages, v_pages, tokens, page_tables, seq_lens, key,
        temps, top_ps, top_ks, seeds,
    ):
        logits, k_pages, v_pages = llama.prefill(
            params, tokens, k_pages, v_pages, page_tables, seq_lens, self.cfg,
            attn_impl=self._attn_impl, mesh=self.mesh,
        )
        next_tokens = sample(
            logits, key, temps, top_ps, top_ks, seeds=seeds, step_ids=seq_lens
        )
        return next_tokens, k_pages, v_pages

    def _prefill_jit(self, bucket: int):
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_and_sample, donate_argnums=(1, 2))
            self._prefill_jits[bucket] = fn
        return fn

    def _prefill_and_sample_mm(
        self, params, vparams, k_pages, v_pages, images, tokens, page_tables,
        seq_lens, key, temps, top_ps, top_ks, seeds,
    ):
        """Multimodal prefill: vision encode fused into the prefill program
        (one dispatch); projected patch embeddings occupy the first
        n_image_tokens positions via llama.prefill(input_embeds=...)."""
        from ..models import vlm

        embeds = vlm.encode_image(vparams, images, self.vision_cfg)
        logits, k_pages, v_pages = llama.prefill(
            params, tokens, k_pages, v_pages, page_tables, seq_lens, self.cfg,
            attn_impl=self._attn_impl, input_embeds=embeds, mesh=self.mesh,
        )
        next_tokens = sample(
            logits, key, temps, top_ps, top_ks, seeds=seeds, step_ids=seq_lens
        )
        return next_tokens, k_pages, v_pages

    def _prefill_mm_jit(self, bucket_key):
        fn = self._prefill_mm_jits.get(bucket_key)
        if fn is None:
            fn = jax.jit(self._prefill_and_sample_mm, donate_argnums=(2, 3))
            self._prefill_mm_jits[bucket_key] = fn
        return fn

    def _draft_prefill_jit(self, key):
        fn = self._draft_prefill_jits.get(key)
        if fn is None:
            dcfg = self.draft_cfg

            def run(params, k_pages, v_pages, tokens, tables, seq_lens):
                return llama.prefill(
                    params, tokens, k_pages, v_pages, tables, seq_lens, dcfg,
                    attn_impl=self._attn_impl, mesh=self.mesh,
                )

            fn = jax.jit(run, donate_argnums=(1, 2))
            self._draft_prefill_jits[key] = fn
        return fn

    # the fused speculative round programs (propose+verify+accept and the
    # shared accept/reject math) live in serving/spec_runtime/runtime.py —
    # built per-config in __init__ and dispatched from _spec_round

    #: host-side lookup window per tick (prompt_lookup_max analog)
    NGRAM_LOOKBACK = 1024

    def _ngram_proposals(self, gammas):
        """Host-side prompt lookup: match each slot's trailing n-gram
        against its own prompt+generation history; propose the tokens that
        followed the MOST RECENT earlier occurrence. Each slot's
        ``_NgramIndex`` (built at prefill, pushed per accepted token) makes
        this O(gamma) per slot per tick — the old full-history rescan was
        O(window x n) on the host critical path every tick. ``gammas``
        carries the per-slot proposal budgets (the adaptive controller's
        output): a 0-budget lane proposes nothing and takes the classic
        lane inside the fused round."""
        gamma = self.spec_gamma
        props = np.zeros((self.max_slots, gamma), np.int32)
        n_prop = np.zeros((self.max_slots,), np.int32)
        for i, s in enumerate(self.slots):
            if s.free or s.ngram is None:
                continue
            budget = min(int(gammas[i]), gamma)
            if budget <= 0:
                continue
            cont = s.ngram.propose(budget)
            if cont:
                props[i, : len(cont)] = cont
                n_prop[i] = len(cont)
        return props, n_prop

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- public API ---------------------------------------------------------

    def validate_params(self, params: SamplingParams) -> None:
        """Raise ValueError for parameter combinations this engine rejects —
        servers call this up front so a bad request becomes a 400, not a
        dropped connection. Speculative engines now accept the FULL
        sampling surface (docs/speculative.md#exactness): temperature>0 /
        top_p / top_k / seed= lanes never speculate — they ride the fused
        round's γ=0 classic lane, whose token is drawn by the very same
        (seed, position)-keyed ``sample`` call the block program makes —
        so nothing is rejected engine-wide today."""
        del params

    def make_request(
        self,
        prompt: str,
        params: SamplingParams | None = None,
        image=None,  # PIL image or [H, W, 3] array: multimodal request
        *,
        priority: str = DEFAULT_CLASS,
        tenant: str = "default",
        # entry-minted RequestTraceContext; None = the entry point already
        # SAMPLED THIS REQUEST OUT (don't re-roll); UNSET = no entry point
        # upstream, mint here
        trace=_rt.UNSET,
    ) -> Request:
        """Build (but do not enqueue) one validated, tokenized request.

        The first half of :meth:`submit`, exposed so the disaggregation
        coordinator can hold a request OBJECT through prefill + page
        migration before it ever enters this engine's admission path — the
        deadline arms here, so migration time counts against it."""
        req = Request(
            prompt=prompt,
            params=params or SamplingParams(),
            priority=validate_class(priority),
            tenant=tenant,
        )
        self.validate_params(req.params)
        if trace is _rt.UNSET:
            req.trace = _rt.start_request_trace(
                req.request_id, entry=self.trace_name,
                store=self._trace_store,
                priority=req.priority, tenant=req.tenant,
            )
        elif trace is not None:
            # entry-point-minted context: the request ADOPTS the trace id
            # as its id, so trace id == request id holds fleet-wide
            req.request_id = trace.trace_id
            req.trace = trace
        # else: the entry point decided (sampled out) — stay untraced
        if req.params.seed is None:
            with self._lock:
                self._submit_seq += 1
                req.auto_seed = (
                    self._seed_base * 1_000_003 + self._submit_seq
                ) % (2**31 - 1)
        if image is not None:
            if self.vision_cfg is None:
                raise ValueError(
                    "engine was built without vision=; cannot take images"
                )
            from ..models import vlm

            req.image = vlm.preprocess_image(
                image, self.vision_cfg.vision.image_size
            )
            n_img = self.vision_cfg.n_image_tokens
            # image tokens lead; text budget = largest bucket minus them
            # (multimodal prompts do not take the chunked-prefill path)
            text_budget = min(
                self.prefill_buckets[-1] - n_img, self.max_model_len - 1 - n_img
            )
            text = self.tokenizer.encode(prompt)[:text_budget]
            pad = self.tokenizer.pad_id % self.cfg.vocab_size
            req.prompt_tokens = [pad] * n_img + text
            if self.prefix_cache is not None:
                # content-derived trie key for the image positions: one id
                # repeated (trie depth already encodes position), offset by
                # vocab_size so it can never collide with text keys
                import hashlib as _hashlib

                digest = _hashlib.sha256(
                    np.asarray(req.image).tobytes()
                ).digest()
                base = self.cfg.vocab_size + int.from_bytes(
                    digest[:8], "little"
                )
                req.cache_key_tokens = [base] * n_img + text
        else:
            # prompts longer than the largest bucket prefill in chunks; the
            # hard cap is the model length (minus >=1 decode slot)
            req.prompt_tokens = self.tokenizer.encode(prompt)[
                : self.max_model_len - 1
            ]
        if req.params.deadline_s is not None:
            req.deadline = self._clock() + float(req.params.deadline_s)
        return req

    def request_cost(self, req: Request) -> int:
        """Estimated KV-page cost of ``req`` on THIS engine (admission's
        reservation unit): pages for the full prompt + generation budget."""
        max_total = min(
            len(req.prompt_tokens) + req.params.max_tokens, self.max_model_len
        )
        return self.cache.pages_for(max_total)

    def submit_request(self, req: Request) -> Request:
        """Enqueue a :meth:`make_request`-built request through admission
        control (the second half of :meth:`submit`)."""
        now = self._clock()
        entry = ScheduledRequest(
            payload=req,
            priority=req.priority,
            tenant=req.tenant,
            cost=self.request_cost(req),
            deadline=req.deadline,
            enqueued_at=now,
        )
        occ = self.cache.occupancy()
        # admit-then-enqueue (raises ShedError; reservation taken on admit):
        # the depth read and the enqueue are not one atomic step, so bounds
        # are approximate by up to the number of racing submitters — fine
        # for overload control, which only needs to stop unbounded growth
        try:
            self.admission.admit(
                entry,
                depths=self.policy.depths(),
                pages_used=occ["pages_used"],
                pages_total=occ["pages_total"],
            )
        except ShedError as e:
            _rt.event(
                req.trace, "shed", store=self._trace_store,
                replica=self.trace_name, reason=e.reason,
            )
            _rt.finish_root(
                req.trace, "shed", store=self._trace_store,
                finish_reason="shed",
            )
            raise
        req._sched_entry = entry
        req._queue_span = _rt.begin(
            req.trace, "queue", replica=self.trace_name,
            priority=req.priority, tenant=req.tenant,
        )
        self.policy.submit(entry)
        return req

    def submit(
        self,
        prompt: str,
        params: SamplingParams | None = None,
        image=None,  # PIL image or [H, W, 3] array: multimodal request
        *,
        priority: str = DEFAULT_CLASS,
        tenant: str = "default",
        trace=_rt.UNSET,
    ) -> Request:
        """Enqueue one request through admission control.

        ``priority`` (interactive|default|batch) and ``tenant`` drive the
        fair-share policy; ``params.deadline_s`` arms a deadline. Raises
        :class:`~modal_examples_tpu.scheduling.admission.ShedError` when
        admission rejects the request (servers surface it as HTTP 429)."""
        req = self.make_request(
            prompt, params, image, priority=priority, tenant=tenant,
            trace=trace,
        )
        return self.submit_request(req)

    def generate(self, prompt: str, params: SamplingParams | None = None) -> str:
        """Blocking convenience: submit and collect the full completion."""
        req = self.submit(prompt, params)
        out = []
        for piece in self.stream(req):
            out.append(piece)
        return "".join(out)

    def stream(self, req: Request):
        """Yield text pieces as they decode (SSE-shaped; streaming.py:38-45)."""
        if not self._running:
            self.start()
        while True:
            item = req.out_queue.get()
            if isinstance(item, _Finish):
                req.finish_reason = item.reason
                return
            yield item

    def warmup(self, buckets: tuple[int, ...] | None = None) -> float:
        """Pre-compile the decode step and prefill buckets against trash
        pages (no allocator state touched) — the FAST_BOOT-style cold-start
        control (vllm_inference.py:85-101): pay compiles at boot, not on the
        first user request. Returns seconds spent."""
        if self._running:
            # the scheduler thread donates the same cache buffers; racing it
            # would pass deleted arrays. Warmup is a boot-time API.
            raise RuntimeError("call warmup() before start()")
        t0 = time.monotonic()
        for bucket in buckets or self.prefill_buckets:
            B = self.prefill_batch
            # warmup shares the dispatch sites' (program, shape_key) space:
            # boot-time builds land in the compile ledger once, and the
            # live path then records cache hits instead of re-timing
            _tok, self.cache.k_pages, self.cache.v_pages = self._profiled(
                "prefill", f"b{bucket}x{B}", self._prefill_jit((bucket, B))
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.zeros((B, bucket), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.ones((B,), jnp.int32),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
            )
        if self.vision_cfg is not None:
            # one compiled multimodal prefill shape: the bucket that fits
            # image tokens + text (bigger buckets compile on first use)
            S = self.vision_cfg.vision.image_size
            B = self.prefill_batch
            mm_bucket = self._bucket_for(self.vision_cfg.n_image_tokens + 1)
            _tok, self.cache.k_pages, self.cache.v_pages = self._profiled(
                "prefill_mm", f"b{mm_bucket}x{B}",
                self._prefill_mm_jit((mm_bucket, B)),
            )(
                self.params,
                self.vision_params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.zeros((B, S, S, 3), jnp.float32),
                jnp.zeros((B, mm_bucket), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.full((B,), self.vision_cfg.n_image_tokens + 1, jnp.int32),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
            )
        B = self.max_slots
        # the block program warms for EVERY engine: spec engines run it
        # too — whole-round γ=0 fallbacks (pressure/collapse) and the
        # failover replay path both dispatch it
        _toks, _last, self.cache.k_pages, self.cache.v_pages = self._profiled(
            "block", f"s{self.max_slots}k{self.decode_block}",
            self._block_jit,
        )(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), bool),
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B, self.pages_per_slot), jnp.int32),
            jnp.zeros((B,), bool),
            self._next_key(),
            jnp.ones((B,), jnp.float32),
            jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), -1, jnp.int32),
        )
        n_ms = max(1, int(self.decode_steps))
        if n_ms > 1:
            # macro-step program (docs/multistep.md): warmed at the
            # configured N; other N values compile on first dispatch
            # (runtime knob flips are a bench/test affair)
            (
                _toks, _valid, _last,
                self.cache.k_pages, self.cache.v_pages,
            ) = self._profiled(
                "multistep", f"s{self.max_slots}n{n_ms}",
                self._multistep_jit(n_ms),
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), bool),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.zeros((B,), bool),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
                jnp.ones((B,), jnp.int32),
            )
        if self.spec_mode == "ngram":
            B = self.max_slots
            (
                _, _, _, self.cache.k_pages, self.cache.v_pages,
            ) = self._profiled(
                "ngram_verify", f"s{self.max_slots}g{self.spec_gamma}",
                self._ngram_jit,
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.zeros((B, self.spec_gamma), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.zeros((B,), bool),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
            )
        if self.spec_mode == "draft":
            for bucket in buckets or self.prefill_buckets:
                B = self.prefill_batch
                _, self.draft_cache.k_pages, self.draft_cache.v_pages = (
                    self._profiled(
                        "draft_prefill", f"b{bucket}x{B}",
                        self._draft_prefill_jit((bucket, B)),
                    )(
                        self.draft_params,
                        self.draft_cache.k_pages,
                        self.draft_cache.v_pages,
                        jnp.zeros((B, bucket), jnp.int32),
                        jnp.zeros((B, self.pages_per_slot), jnp.int32),
                        jnp.ones((B,), jnp.int32),
                    )
                )
            B = self.max_slots
            (
                _,
                _,
                _,
                self.cache.k_pages,
                self.cache.v_pages,
                self.draft_cache.k_pages,
                self.draft_cache.v_pages,
            ) = self._profiled(
                "spec_verify", f"s{self.max_slots}g{self.spec_gamma}",
                self._spec_jit,
            )(
                self.params,
                self.draft_params,
                self.cache.k_pages,
                self.cache.v_pages,
                self.draft_cache.k_pages,
                self.draft_cache.v_pages,
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.zeros((B,), bool),
                jnp.zeros((B,), jnp.int32),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
            )
        from ..utils.sync import force

        force(self.cache.k_pages)  # block_until_ready is a no-op on axon
        return time.monotonic() - t0

    def _finish_stream(self, req: Request, marker: "_Finish") -> None:
        """THE terminal routing point: every ``_Finish`` put in this
        engine goes through here. Streams the detok worker owns get their
        marker enqueued BEHIND any pending text (the FIFO ordering
        contract, docs/multistep.md) — the worker then runs
        :meth:`_deliver_finish`; everything else delivers directly."""
        w = self._detok
        if w is not None and w.alive and w.owns(req):
            w.finish(req, marker)
            return
        self._deliver_finish(req, marker)

    def _deliver_finish(self, req: Request, marker: "_Finish") -> None:
        """THE terminal delivery: close the request's trace (sweeping any
        still-open spans — queue, decode — so no failure path can leak a
        dangling span) and only then release the caller's stream."""
        _rt.finish_request(req, marker.reason, store=self._trace_store)
        # per-request usage record (usage.jsonl): journaled at the SAME
        # terminal point that releases the stream, with the ACCOUNTED
        # token counts — Σ journal == the engine's counters by structure
        self.usage.note_finish(req, marker.reason)
        req.out_queue.put(marker)

    def _close_queue_span(self, req: Request) -> None:
        """Close the admission-queue span when the scheduler pops the
        request for a slot (the one non-terminal close; terminal paths
        sweep it in ``_finish_stream`` instead). ``wait_s`` comes from the
        span's OWN start — for adopted (disagg) requests the sched entry's
        ``enqueued_at`` predates the whole migration, which is the migrate
        span's story, not this queue's."""
        sp = getattr(req, "_queue_span", None)
        if sp is not None:
            req._queue_span = None
            _rt.finish(
                req.trace, sp, store=self._trace_store,
                wait_s=round(max(0.0, time.time() - sp.start), 6),
            )

    def abort(self, request: Request) -> None:
        """Cancel a request (the engine-abort surface vLLM exposes for
        client disconnects). Queued (never-scheduled) ones are removed from
        the policy HERE — releasing their admission page reservation and
        per-class depth immediately, and finishing the caller's stream even
        if the scheduler thread never runs. Active ones finish at the next
        scheduler tick and free their slot/pages."""
        request.aborted = True
        entry = getattr(request, "_sched_entry", None)
        if entry is not None and self.policy.remove(entry):
            # was still queued: nothing on a slot, nothing in flight —
            # reservation back to the pool, caller released now
            self.admission.release(entry)
            _obs.set_sched_queue_depths(self.policy.depths())
            self._finish_stream(request, _FINISH)

    # -- disaggregated prefill/decode (serving/disagg, docs/disagg.md) -------

    def prefill_sync(self, req: Request) -> dict:
        """Run ``req``'s prefill WITHOUT taking a decode slot: claim pages,
        fill their KV (bucketed or chunked path), sample the first token,
        and return the claim + sampler state for page extraction — the
        prefill-replica half of disaggregated serving.

        Only legal while the scheduler loop is NOT running: the loop and
        this method donate the same cache buffers through their jits, and
        racing that donation would pass deleted arrays. Prefill-role
        replicas never ``start()`` their engine; concurrent server threads
        serialize on an internal lock."""
        if req.image is not None:
            raise ValueError(
                "multimodal requests do not take the disagg prefill path "
                "(image-token KV keys by content hash, not position)"
            )
        self._prefill_sync_pending += 1
        try:
            return self._prefill_sync_locked(req)
        finally:
            self._prefill_sync_pending -= 1

    def _prefill_sync_locked(self, req: Request) -> dict:
        with self._prefill_sync_lock:
            if self._running:
                raise RuntimeError(
                    "prefill_sync requires a stopped engine: prefill-role "
                    "replicas never start their scheduler loop"
                )
            claim = self._claim_pages(req)
            if claim is None:
                raise OutOfPages(
                    f"prefill replica out of KV pages for {req.request_id}"
                )
            t_start = time.monotonic()
            t_wall = time.time()
            u_start = self._clock()  # usage meter: engine-clock domain
            try:
                first = self._prefill_pages(req, claim)
            except Exception:
                # same contract as _fail_claims: a failed prefill must not
                # leak the claim or poison the trie with unwritten pages
                self.release_claim(claim, valid=False)
                raise
            self.stats.prompt_tokens += claim["n_prompt"]
            self.usage.note_prompt(req, claim["n_prompt"])
            self.usage.note_phase_seconds("prefill", self._clock() - u_start)
            _obs.record_engine_phase("prefill", time.monotonic() - t_start)
            _rt.record_span(
                req.trace, "prefill", start=t_wall,
                parent=getattr(req, "_trace_parent", None),
                store=self._trace_store, replica=self.trace_name,
                n_prompt=claim["n_prompt"],
            )
            return {
                "claim": claim,
                "position": claim["n_prompt"],
                "first_token": first,
                # only pages holding real prompt KV ship; decode growth
                # pages are allocated (empty) on the decode side
                "n_kv_pages": self.cache.pages_for(claim["n_prompt"]),
            }

    def release_claim(self, claim: dict, *, valid: bool = True) -> None:
        """Free a slot-less page claim (the disagg mirror of
        ``_release_slot_pages``/``_fail_claims``). ``valid=True``: the pages
        hold real KV — trie refs release but stay cached, keeping the
        prefill replica's prefix cache warm for the next shared-prefix
        prompt; private pages free. ``valid=False``: the prefill never
        completed — trie pages invalidate so no later request shares
        never-written KV."""
        if valid and self.prefix_cache is not None:
            self.prefix_cache.release(claim["trie_pages"])
            self.cache.allocator.free(claim["private_pages"])
        elif valid:
            self.cache.allocator.free(claim["pages"])
        else:
            self._unwind_claim(claim)

    def _unwind_claim(self, claim: dict) -> None:
        """Invalidate + free a claim whose pages never received valid KV —
        the ONE ownership rule shared by the slot failure path
        (``_fail_claims``) and the slot-free one (``release_claim``): trie
        pages another live request still holds stay theirs; everything this
        claim exclusively owns goes back to the allocator."""
        if self.prefix_cache is not None:
            self.prefix_cache.invalidate(claim["trie_pages"])
            owned = list(claim["private_pages"]) + [
                p for p in claim["trie_pages"]
                if p not in self.prefix_cache._by_page
            ]
            self.cache.allocator.free(owned)
        else:
            self.cache.allocator.free(claim["pages"])

    def extract_request_pages(self, req: Request, state: dict):
        """Pull the prefilled pages of a :meth:`prefill_sync` result off the
        device as a wire-ready :class:`~.disagg.transport.PageBlock` (page
        data + every other cache leaf, block hashes, sampler meta)."""
        from .disagg.transport import chain_hashes, extract_pages

        claim = state["claim"]
        used = claim["pages"][: state["n_kv_pages"]]
        return extract_pages(
            self.cache,
            used,
            block_hashes=chain_hashes(
                req.cache_key_tokens or req.prompt_tokens,
                self.cache.page_size,
            ),
            meta={
                "request_id": req.request_id,
                "prompt_tokens": [int(t) for t in req.prompt_tokens],
                "position": int(state["position"]),
                "first_token": int(state["first_token"]),
                "auto_seed": req.auto_seed,
                # the trace context rides the MTKV1 envelope: a decode
                # replica in ANOTHER process reconstructs it from here
                # (reqtrace.from_wire) and keeps stitching the same trace
                "trace": _rt.wire(
                    req.trace, parent=getattr(req, "_trace_parent", None)
                ),
            },
        )

    def submit_adopted(self, req: Request, entry, block) -> Request:
        """Enqueue a request whose prompt KV was prefilled elsewhere.

        ``block`` (a deserialized ``PageBlock``) is adopted into this cache
        at admission ON the scheduler thread — the only thread that may
        touch the cache arrays alongside the decode jits — and decode
        continues from the migrated position with the migrated first token
        riding the fresh-slot override lane, exactly like a local prefill's
        first sample. ``entry`` is the migration's admission reservation,
        taken by the coordinator BEFORE any byte moved so decode-side KV
        headroom was guaranteed while the transfer was in flight."""
        if block.kv_dtype != self.cache.kv_dtype:
            raise ValueError(
                f"migrated block is {block.kv_dtype}, this cache is "
                f"{self.cache.kv_dtype}: disagg peers must share a kv_dtype"
            )
        req._adopted_state = {
            "block": block,
            "position": int(block.meta["position"]),
            "first_token": int(block.meta["first_token"]),
            # decode-state leg (docs/failover.md): present on live-migrated
            # mid-decode blocks, absent on plain PR-6 first-token blocks —
            # the envelope extension is purely additive meta, so either
            # side of the wire may predate the other
            "resume": block.meta.get("resume"),
        }
        req._sched_entry = entry
        req._queue_span = _rt.begin(
            req.trace, "queue", replica=self.trace_name,
            priority=req.priority, tenant=req.tenant,
        )
        self.policy.submit(entry)
        return req

    # -- in-flight request failover (serving/failover.py, docs/failover.md) --

    def submit_resumed(
        self, req: Request, *, prompt_tokens, generated, emitted_len: int = 0
    ) -> Request:
        """Enqueue a request resumed from a decode checkpoint: ``req``'s
        stream continues on THIS engine, token-identical to the
        uninterrupted run.

        ``prompt_tokens`` is the ORIGINAL prompt's token ids, ``generated``
        the tokens accepted before the failure. The engine re-prefills the
        ORIGINAL prompt (the same bucket/path — bitwise the original
        prompt KV, and cheap when the prefix cache still holds the
        blocks), teacher-forces ``generated[:-1]`` through THE decode
        block program (``_replay_decode_prefix`` — the same compiled body
        the dead replica ran, so the rebuilt KV is bit-identical; a
        prefill recompute of those positions drifts by a bf16 rounding
        asymmetry and flips greedy argmaxes), then feeds ``generated[-1]``
        — the last token the client already has — at its original
        position through the fresh-slot override lane. Sampling is keyed
        ``(seed, position)`` (the resumed request keeps its original
        seed/auto_seed), so every token from there on reproduces the
        uninterrupted stream exactly; the emitted-text cursor resumes at
        ``emitted_len`` so no char is duplicated or lost. Empty
        ``generated`` degrades to a plain resubmission. The same ``req``
        object (same id, same out_queue, same trace id) rides through, so
        a blocked ``stream()`` consumer continues without reconnecting."""
        if req.image is not None:
            raise ValueError(
                "multimodal requests do not take the failover resume path"
            )
        if req.aborted:
            # a client abort landed during the failover window: honor it —
            # resurrecting an abandoned request would decode to max_tokens
            # for nobody (the abort flag is never reset here)
            self._finish_stream(
                req,
                _Finish("deadline" if req.deadline_expired else "stop"),
            )
            return req
        req.finish_reason = None
        base = [int(t) for t in prompt_tokens]
        gen = [int(t) for t in generated]
        # pin the ORIGINAL prompt for any later checkpoint: resumption
        # must never compound (prompt_tokens is reset to the base here,
        # but the explicit record keeps that invariant checkable)
        req._orig_prompt_tokens = base
        req.generated_tokens = gen
        req.emitted_len = int(emitted_len)
        req.n_generated = max(req.n_generated, len(gen))
        req.cache_key_tokens = None
        req.created = time.monotonic()
        if gen and (
            len(gen) >= req.params.max_tokens
            or len(base) + len(gen) >= self.max_model_len
        ):
            # nothing left to decode (the failure landed on the final
            # token): deliver the terminal marker without taking a slot
            self._finish_stream(req, _Finish("length"))
            return req
        req.prompt_tokens = list(base)
        # the generated prefix is REPLAYED through the decode program at
        # harvest, not re-prefilled: same compiled body, same inputs, same
        # bits (the prompt claim is therefore identical to the original
        # request's — same pages, same trie sharing)
        req._resume_state = {"replay": gen} if gen else None
        return self.submit_request(req)

    def migrate_out(self, req: Request, *, timeout: float = 30.0):
        """Detach ``req`` from this engine for a proactive live migration
        (fleet drain / coordinator rebalancing — docs/failover.md). Runs on
        the scheduler thread (the only one that may read cache arrays next
        to the decode jits). Returns one of:

        - ``("block", PageBlock)`` — the request was mid-decode: its KV
          pages ([0, position)) are extracted with the decode-state leg in
          the MTKV1 meta, the slot is released (trie pages stay cached),
          and the caller adopts the block on the target via
          :meth:`submit_adopted`;
        - ``("requeue", None)`` — still queued, or mid-prefill with no
          token accepted yet: nothing to ship, the caller resubmits the
          prompt fresh on the target (token-identical — the stream never
          emitted);
        - ``("gone", None)`` — already finished or aborted; nothing to do.

        Raises when the scheduler loop is stopped or unresponsive — the
        caller falls back to the reactive (checkpoint-only) resume."""
        return self._run_on_scheduler(
            lambda: self._migrate_out_on_sched(req), timeout
        )

    def _migrate_out_on_sched(self, req: Request):
        from .disagg.transport import chain_hashes, extract_pages

        entry = getattr(req, "_sched_entry", None)
        if entry is not None and self.policy.remove(entry):
            # still queued: reservation back, caller resubmits elsewhere
            self.admission.release(entry)
            _obs.set_sched_queue_depths(self.policy.depths())
            self._close_queue_span(req)
            return ("requeue", None)
        for i, s in enumerate(self.slots):
            if s.request is not req:
                continue
            if req.aborted:
                return ("gone", None)
            if s.prefill is not None or s.pending_first:
                # mid-prefill: partial KV must not ship or stay cached —
                # unwind; nothing was emitted, so a fresh resubmission on
                # the target is token-identical
                self._unwind_slot(s)
                s.request = None
                self._active[i] = False
                return ("requeue", None)
            # mid-decode: KV for [0, position) is complete (every accepted
            # token's predecessor was fed through a finished block); later
            # positions an in-flight block may have written are masked by
            # position-bounded attention and overwritten on resume. The
            # same harvest-boundary argument covers mid-MACRO-step
            # migration (docs/multistep.md): un-harvested device tokens
            # are simply never accepted — the checkpoint carries only
            # committed state, and the peer regenerates the rest
            # token-identically from the (seed, position) keying.
            if self._detok is not None and self._detok.owns(req):
                # drain pending text first: req.emitted_len below must be
                # the FINAL emitted cursor or the resumed stream would
                # duplicate/lose chars
                self._detok.flush(timeout=5.0)
            n_kv = self.cache.pages_for(s.position)
            # the ORIGINAL prompt (explicit on resumed requests); the
            # pages hold KV for base + generated[:-1], which keys their
            # chained hashes
            base = getattr(req, "_orig_prompt_tokens", None)
            if base is None:
                base = req.prompt_tokens
            covered = list(base) + [int(t) for t in req.generated_tokens[:-1]]
            block = extract_pages(
                self.cache,
                s.pages[:n_kv],
                block_hashes=chain_hashes(covered, self.cache.page_size),
                meta={
                    "request_id": req.request_id,
                    "prompt_tokens": [int(t) for t in base],
                    "position": int(s.position),
                    "first_token": int(s.last_token),
                    "auto_seed": req.auto_seed,
                    # the decode-state leg: everything past first-token
                    # adoption that a mid-decode takeover needs
                    "resume": {
                        "generated": [int(t) for t in req.generated_tokens],
                        "emitted_len": int(req.emitted_len),
                    },
                    "trace": _rt.wire(req.trace),
                },
            )
            sp = getattr(req, "_decode_span", None)
            if sp is not None:
                req._decode_span = None
                _rt.finish(req.trace, sp, store=self._trace_store)
            # valid KV: trie pages stay cached (warm for a later reactive
            # re-prefill), private pages free — the normal-finish release
            self._release_slot_pages(s)
            s.request = None
            self._active[i] = False
            return ("block", block)
        return ("gone", None)

    def start(self) -> "LLMEngine":
        with self._lock:
            if self._stopped_on_error:
                raise RuntimeError(
                    "engine stopped after a scheduler error (strict mode); "
                    f"last traceback:\n{(self.error_log or ['?'])[-1]}"
                )
            if self._running:
                return self
            # starting IS progress: a revived engine must not present its
            # previous life's stale watermark ages to the watchdog in the
            # window before its first tick (serving/health.py)
            self.watermarks.note_start()
            self._running = True
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def revive(self) -> "LLMEngine":
        """Clear the stopped-on-error poison so :meth:`start` may run again
        — the router's re-probe re-admission path (docs/faults.md;
        ``EngineReplica.probe``). Safe because stopping on error already
        released every caller and freed every slot (``_release_all``): a
        revived engine starts empty. ``error_log`` survives for diagnosis;
        without an explicit revive, one scheduler error removed a replica
        from the fleet forever."""
        with self._lock:
            self._stopped_on_error = False
        return self

    def stop(self, *, reason: str = "stop") -> None:
        """Stop the scheduler and release every caller: in-flight and queued
        requests get their terminal marker so stream()/generate() return
        (partial output for in-flight ones) instead of blocking forever.
        ``reason="error"`` marks the release as a failure — the fleet's
        forced reap and the gray-failure watchdog use it so still-live
        streams take the router-level reactive failover instead of ending
        as a silently truncated "stop" (docs/failover.md). An error-stop
        also POISONS the engine like a strict-mode scheduler crash: the
        router must not place new work on it until ``probe()`` revives and
        restarts it (the watchdog's stop -> revive -> re-probe ladder leg,
        docs/health.md)."""
        if reason == "error":
            self._stopped_on_error = True
        self._running = False
        if self._thread and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
        if self._detok is not None:
            # drain held text BEFORE the release sweep: its direct markers
            # must land behind every chunk the worker still owes
            self._detok.stop()
        self._release_all(_FINISH if reason == "stop" else _Finish(reason))
        self._flush_token_counters()
        self.usage.flush()  # unthrottled: the final window reaches pushes
        if self.profiler is not None:
            self.profiler.flush()

    # -- scheduler loop ------------------------------------------------------

    def _loop(self) -> None:
        import traceback

        try:
            while self._running:
                try:
                    worked = self.step()
                except _FaultError:
                    # Injected scheduler-thread crash (faults/inject.py):
                    # fail in-flight AND queued requests LOUDLY — every
                    # caller's stream terminates with finish_reason="error"
                    # instead of wedging — then keep the loop alive. An
                    # injected fault is not a scheduler-logic bug, so it
                    # neither poisons the engine (strict mode) nor trips
                    # the _error_reports session sentinel.
                    _log.warning(
                        "injected scheduler crash: releasing all callers"
                    )
                    # the crash hits every in-flight request: mark each
                    # traced one before the release sweep closes its spans
                    for s in self.slots:
                        if s.request is not None:
                            _rt.event(
                                s.request.trace, "fault",
                                store=self._trace_store,
                                replica=self.trace_name,
                                point="engine.scheduler_crash",
                            )
                    self._release_all(_Finish("error"))
                    worked = False
                except Exception:
                    # Per-REQUEST failures never reach here: bad params are
                    # rejected at submit() and failed prefills unwind their
                    # claims inside _admit (_fail_claims). Anything caught
                    # here is a scheduler-logic error. Keep the traceback on
                    # the engine so it is diagnosable after the fact
                    # (surfaced in /metrics as mtpu_scheduler_errors_total).
                    tb = traceback.format_exc()
                    self.error_log.append(tb)
                    self.error_count += 1
                    del self.error_log[:-20]
                    LLMEngine._error_reports.append(tb[-800:])
                    del LLMEngine._error_reports[:-50]
                    _obs.record_scheduler_error()
                    _log.error("scheduler-loop exception:\n%s", tb)
                    if self.strict:
                        # tests must fail loudly, not generate corrupt
                        # output: poison the engine (start() refuses to
                        # resurrect it — a racing stream() would otherwise
                        # spawn a second scheduler thread mid-teardown),
                        # then release callers
                        self._stopped_on_error = True
                        self._running = False
                        # capture BEFORE the release sweep frees the slots:
                        # the bundle's open-request traces are the victims
                        _incident.capture(
                            "scheduler_crash",
                            reason=tb.strip().splitlines()[-1] if tb else "",
                            replica=self.trace_name,
                        )
                        self._release_all(_Finish("error"))
                        return
                    worked = False
                if not worked:
                    time.sleep(0.002)
        finally:
            if self._running:
                # The thread is dying WITHOUT stop() — a BaseException, or
                # a bug in the error handling above. Before this guard,
                # every in-flight stream() would block forever on a queue
                # nothing will ever feed; now the crash is loud: callers
                # get finish_reason="error" and the engine is poisoned
                # until revive() (docs/faults.md: no request may wedge).
                self._running = False
                self._stopped_on_error = True
                _incident.capture(
                    "scheduler_crash",
                    reason="scheduler thread died without stop()",
                    replica=self.trace_name,
                )
                self._release_all(_Finish("error"))

    def _drain_ctrl(self) -> None:
        """Service scheduler-thread control commands (live-migration
        checkpoint extraction — serving/failover.py). Each command's
        result/exception goes back to the waiting caller thread."""
        while self._ctrl:
            fn, out_q = self._ctrl.popleft()
            try:
                out_q.put(("ok", fn()))
            except Exception as e:  # the caller re-raises; the loop lives
                out_q.put(("err", e))

    def _run_on_scheduler(self, fn, timeout: float = 30.0):
        """Run ``fn`` on the scheduler thread (the only thread that may
        touch cache arrays next to the decode jits) and return its result.
        Raises RuntimeError when the loop is not running and TimeoutError
        when it stops servicing commands — callers fall back to the
        reactive (checkpoint-only) path either way."""
        if not self._running:
            raise RuntimeError("engine scheduler is not running")
        out_q: queue.Queue = queue.Queue()
        self._ctrl.append((fn, out_q))
        try:
            status, val = out_q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"scheduler did not service the control command in {timeout}s"
            ) from None
        if status == "err":
            raise val
        return val

    def _release_all(self, marker: "_Finish") -> None:
        while self._ctrl:
            # a stopping/crashed engine must not wedge a migration caller
            fn, out_q = self._ctrl.popleft()
            out_q.put(("err", RuntimeError("engine released all requests")))
        self._inflight.clear()
        self._pending_harvest.clear()
        self._device_tokens = None
        self._last_dispatch_at = None
        # queue BEFORE slots: delivering an in-flight marker wakes that
        # caller, and a caller that immediately resubmits must not have
        # its fresh request reaped by the tail of this same sweep (the
        # surviving-loop crash path keeps serving — a post-release
        # submission stays queued for the next tick instead)
        for entry in self.policy.drain():
            self.admission.release(entry)
            self._finish_stream(entry.payload, marker)
        for slot in self.slots:
            if not slot.free:
                self._finish_stream(slot.request, marker)
                if slot.prefill is not None or slot.pending_first:
                    # stopping mid-prefill: pages may hold partial KV —
                    # invalidate, don't cache (a revived engine must not
                    # share them)
                    self._unwind_slot(slot)
                else:
                    self._release_slot_pages(slot)
                slot.request = None

    def step(self) -> bool:
        """One scheduler tick: expire deadlines -> admit -> decode -> emit.
        Returns True if any work happened.

        Tick anatomy (docs/observability.md#hot-path-profiling): with the
        profiler on, the tick's host time is partitioned into the
        catalog.TICK_PHASES via sequential ``_tm`` marks here and in the
        helpers this calls; idle ticks record nothing."""
        # fault point (docs/faults.md): a scheduler-thread crash. _loop
        # catches the FaultError, fails every caller loudly, and survives.
        _inject.check("engine.scheduler_crash")
        prof = self.profiler
        tick = None if prof is None else prof.begin_tick()
        self._tick = tick
        # fault point (docs/health.md): a SILENT scheduler freeze — the
        # thread stays alive, healthy() stays true, but no tick, dispatch,
        # or accept ever lands again. Nothing inside the engine ends it;
        # only stop() (the watchdog's wedged-scheduler recovery, or an
        # operator) lifts the hold — exactly the gray failure the
        # progress-watermark watchdog exists to detect.
        if _inject.fire("engine.scheduler_freeze"):
            _log.warning("injected scheduler freeze: holding the loop")
            for s in self.slots:
                if s.request is not None:
                    _rt.event(
                        s.request.trace, "fault", store=self._trace_store,
                        replica=self.trace_name,
                        point="engine.scheduler_freeze",
                    )
            while self._running:
                time.sleep(0.005)
            return False
        self.watermarks.note_tick()
        self._drain_ctrl()
        _tm(tick, "ctrl")
        self._expire_deadlines()
        _tm(tick, "policy")
        admitted = self._admit()
        decoded = self._decode_tick()
        self._refresh_gauges()
        _tm(tick, "policy")
        if tick is not None:
            self._tick = None
            prof.end_tick(tick, worked=admitted or decoded)
        return admitted or decoded

    def _expire_deadlines(self) -> None:
        """Deadline enforcement, both stages: queued work past its deadline
        is cancelled before ever taking a slot (its page reservation goes
        back to the pool); in-flight work is aborted so the next decode
        tick reaps the slot and frees its pages."""
        now = self._clock()
        for entry in self.policy.expired(now):
            self.admission.release(entry)
            req = entry.payload
            req.deadline_expired = True
            _obs.record_deadline_miss("queued")
            self._finish_stream(req, _Finish("deadline"))
        for s in self.slots:
            req = s.request
            if (
                req is not None
                and req.deadline is not None
                and not req.aborted
                and now >= req.deadline
            ):
                req.deadline_expired = True
                req.aborted = True  # reaped (pages freed) in _decode_tick
                _obs.record_deadline_miss(
                    # a sliced prefill can now outlive a deadline mid-fill:
                    # its own stage label (the reap unwinds the claim)
                    "prefill"
                    if s.prefill is not None or s.pending_first
                    else "inflight"
                )

    def _refresh_gauges(self) -> None:
        """Engine-load gauges (queue depth, active slots, tokens/s), KV/
        prefix-cache occupancy, and prefill-vs-decode token-counter deltas
        into the process registry — throttled so the hot loop never pays
        more than a few dict writes per second."""
        now = time.monotonic()
        if now - self._metrics_wall < 0.25:
            return
        self._metrics_wall = now
        depths = self.policy.depths()
        _obs.set_engine_gauges(
            waiting=sum(depths.values()),
            active_slots=sum(1 for s in self.slots if not s.free),
            tokens_per_second=self.stats.tokens_per_second(),
        )
        _obs.set_sched_queue_depths(depths)
        # occupancy via the cache helper: covers the native allocator, which
        # has no gauge hooks of its own (the python allocator's alloc/free
        # hooks write the same series — idempotent, last-writer-wins)
        occ = self.cache.occupancy()
        _obs.set_kv_occupancy(
            used=occ["pages_used"],
            free=occ["pages_free"],
            total_usable=occ["pages_total"],
        )
        # dtype-aware footprint: the same page count pins half the HBM at
        # kv_dtype="int8", and this gauge is where that shows up
        _obs.set_kv_cache_bytes(occ["bytes_total"], self.cache.kv_dtype)
        if self.prefix_cache is not None:
            _obs.set_prefix_cache_pages(self.prefix_cache.cached_pages)
        # sliced-prefill remainder: tokens admitted to slots whose chunked
        # prefill the budget is still metering out
        backlog = 0
        for s in self.slots:
            if s.prefill is not None and s.request is not None:
                backlog += max(
                    0, len(s.request.prompt_tokens) - s.prefill.offset
                )
        _obs.set_prefill_backlog(backlog)
        # macro-step decode gauges (docs/multistep.md): configured N, the
        # harvested tokens-per-dispatch over the window since the last
        # refresh (held when idle), and the detok worker's queue depth
        d = self._ms_dispatches - self._ms_flush["dispatches"]
        if d > 0:
            self._ms_tpd = (
                self._ms_tokens - self._ms_flush["tokens"]
            ) / d
            self._ms_flush = {
                "dispatches": self._ms_dispatches,
                "tokens": self._ms_tokens,
            }
        _obs.set_multistep_gauges(
            decode_steps=max(1, int(self.decode_steps)),
            tokens_per_dispatch=self._ms_tpd,
            detok_queue_depth=(
                self._detok.queue_depth() if self._detok is not None else 0
            ),
        )
        # speculative gauges (docs/speculative.md#series): dispatched-γ
        # p50 over the window since the last refresh, harvested tokens per
        # spec round (held when idle), lifetime acceptance, and the
        # fallback-round counter delta
        if self.spec_gamma:
            d = self._spec_rounds - self._spec_flush["rounds"]
            if d > 0:
                self._spec_tpd = (
                    self._spec_round_tokens - self._spec_flush["tokens"]
                ) / d
            fb = self._spec_fallbacks - self._spec_flush["fallbacks"]
            if d > 0 or fb > 0:
                self._spec_flush = {
                    "rounds": self._spec_rounds,
                    "tokens": self._spec_round_tokens,
                    "fallbacks": self._spec_fallbacks,
                }
            gw = self._spec_gamma_window
            if gw:
                self._spec_gamma_p50 = float(np.median(gw))
                del gw[:]
            _obs.set_spec_gauges(
                gamma=self._spec_gamma_p50,
                tokens_per_dispatch=self._spec_tpd,
                acceptance_rate=self.stats.acceptance_rate(),
            )
            if fb > 0:
                _obs.record_spec_fallback(fb)
        self._flush_token_counters()
        # per-tenant usage deltas + roofline MFU/MBU gauges ride the same
        # throttle (the flight recorder's tsdb sampler sees them for free)
        self.usage.flush()

    def _flush_token_counters(self) -> None:
        """Push the stats deltas accumulated since the last flush into the
        prometheus token counters (also called unthrottled from stop(), so
        the final sub-throttle window is never lost from a pushed
        exposition)."""
        s, last = self.stats, self._counter_flush
        _obs.record_token_totals(
            prompt=s.prompt_tokens - last["prompt"],
            generated=s.generated_tokens - last["generated"],
            steps=s.steps - last["steps"],
        )
        self._counter_flush = {
            "prompt": s.prompt_tokens,
            "generated": s.generated_tokens,
            "steps": s.steps,
        }

    def _admit(self) -> bool:
        """Claim slots+pages for policy-selected requests, then prefill each
        bucket's admissions as ONE batched jitted call (compile shapes:
        bucket x pow2-padded batch — continuous batching on the prefill side
        too). The pop order is the SchedulerPolicy's (priority classes +
        tenant fair share by default), not submission order.

        Stall-free admission (docs/scheduling.md): ``prefill_budget`` caps
        the prompt tokens converted into prefill work per tick (0 =
        unlimited). In-flight sliced prefills resume FIRST — their pages
        are already held, and finishing them frees capacity — then new
        entries convert while budget remains; the remainder goes back to
        the front of its queues through the preemption-safe requeue, its
        reservations untouched. Every prefill dispatched here is ASYNC:
        the sampled first tokens park on the pending-harvest queue and are
        read only after ``_decode_tick`` has dispatched the next decode
        block, so in-flight streams never wait on a prefill round trip."""
        tick = self._tick
        budget = self.prefill_budget or None  # None/0 = unlimited
        spent = self._advance_pending_prefills(budget, 0)
        _tm(tick, "prefill_resume")
        assignments: list[tuple[int, "Request", dict]] = []  # (slot, req, claim)
        free_slots = [i for i, s in enumerate(self.slots) if s.free]
        entries = (
            self.policy.next_batch(len(free_slots))
            if free_slots and (budget is None or spent < budget)
            else []
        )
        now = self._clock()
        taken = 0  # free_slots consumed (grouped prefills + adoptions)
        adopted_any = False
        for pos, entry in enumerate(entries):
            req: Request = entry.payload
            if (
                budget is not None
                and spent >= budget
                and not req.aborted
                and getattr(req, "_adopted_state", None) is None
            ):
                # budget spent: stop converting queue entries. This entry
                # and the not-yet-examined rest still hold their admission
                # reservations (nothing was released for them), so the
                # preemption-safe front-requeue is all that's needed.
                # Aborted entries still drain (they cost no prefill) and
                # adopted blocks ship ready-made KV — cost 0 tokens.
                self.policy.requeue(entries[pos:])
                break
            # popped = the reservation converts into a real page claim (or
            # is dropped with the request); either way it's off the books
            self.admission.release(entry)
            if req.aborted:
                self._finish_stream(
                    req,
                    _Finish("deadline") if req.deadline_expired else _FINISH,
                )
                continue
            adopted = getattr(req, "_adopted_state", None)
            if adopted is not None:
                # migrated request (disagg): its prompt KV arrives as a wire
                # block, not a prompt to prefill — adopt on THIS thread, the
                # only one that may write cache arrays next to the decode jits
                status = self._admit_adopted(
                    free_slots[taken], req, adopted, entry, now
                )
                if status == "retry":
                    self.admission.reserve(entry)
                    self.policy.requeue(entries[pos:])
                    break
                if status == "ok":
                    taken += 1
                    adopted_any = True
                continue
            claim = self._claim_pages(req)
            if claim is None:
                # no KV room: preemption-safe requeue — this entry and every
                # not-yet-examined one go back to the FRONT of their queues
                # in original order (reservations re-taken), and admission
                # waits for a completion to free pages
                rest = entries[pos:]
                # only THIS entry's reservation was released above; the
                # not-yet-examined rest still hold theirs
                self.admission.reserve(entry)
                self.policy.requeue(rest)
                break
            _obs.record_sched_queue_wait(
                entry.priority, max(0.0, now - entry.enqueued_at)
            )
            self._close_queue_span(req)
            assignments.append((free_slots[taken], req, claim))
            taken += 1
            if (
                claim["n_prompt"] <= self.prefill_buckets[-1]
                or req.image is not None
            ):
                # short (bucketed) prompts prefill atomically, so they
                # charge the budget up front; long ones charge per chunk
                # as their state machine advances below
                spent += claim["n_prompt"]

        _tm(tick, "admit")
        long_ones: list[tuple] = []
        grouped: list[tuple] = []
        for a in assignments:
            # one-pass split on the prompt-length predicate (the old
            # `a not in long_ones` filter re-scanned a list of tuples
            # holding dict claims — O(n^2) equality over page lists)
            if (
                a[2]["n_prompt"] > self.prefill_buckets[-1]
                and a[1].image is None  # mm prompts are capped at submit()
            ):
                long_ones.append(a)
            else:
                grouped.append(a)
        by_bucket: dict[tuple, list] = {}
        for a in grouped:
            key = (self._bucket_for(a[2]["n_prompt"]), a[1].image is not None)
            by_bucket.setdefault(key, []).append(a)
        for (bucket, is_mm), group in by_bucket.items():
            # chunk to the ONE compiled batch shape per bucket
            for i in range(0, len(group), self.prefill_batch):
                chunk = group[i : i + self.prefill_batch]
                try:
                    self._prefill_group(bucket, chunk, is_mm=is_mm)
                except Exception:
                    # a failed prefill must not leak claims, hang callers, or
                    # leave never-written KV pages in the prefix trie
                    import traceback

                    traceback.print_exc()
                    self._fail_claims(chunk)
        for a in long_ones:
            try:
                self._prefill_long(*a)
            except Exception:
                # same contract as the grouped path: a failed chunked prefill
                # must not leave a half-initialized slot (next decode tick
                # would read uninitialized KV), leak its page claim, or poison
                # the prefix trie with partially-written pages
                import traceback

                traceback.print_exc()
                self._fail_claims([a])
        _tm(tick, "prefill_dispatch")
        if long_ones:
            # newly admitted long prompts advance with what remains of this
            # tick's budget (at least one chunk fires when nothing else
            # did: the progress guarantee)
            spent = self._advance_pending_prefills(budget, spent)
            _tm(tick, "prefill_resume")
        return bool(assignments) or adopted_any or spent > 0

    def _admit_adopted(
        self, slot_idx: int, req: Request, state: dict, entry, now: float
    ) -> str:
        """Install a migrated request into a slot: allocate its full page
        budget, adopt the shipped KV block into the leading pages, and
        start decode from the migrated position. Returns ``"ok"``,
        ``"retry"`` (no pages free — caller requeues, preemption-safe), or
        ``"failed"`` (corrupt/incompatible block — the caller's stream ends
        with finish_reason="error" and no slot is consumed)."""
        from .disagg.transport import TransportError, adopt_pages

        block = state["block"]
        n_pages = self.request_cost(req)
        try:
            pages = self.cache.allocator.alloc(n_pages)
        except OutOfPages:
            if self.prefix_cache is not None:
                self.prefix_cache.evict(n_pages)
                try:
                    pages = self.cache.allocator.alloc(n_pages)
                except OutOfPages:
                    return "retry"
            else:
                return "retry"
        if req.trace is None:
            # cross-process migration: the context rides the MTKV1 meta —
            # reconstruct it so decode-side spans keep stitching
            req.trace = _rt.from_wire(
                block.meta.get("trace"), store=self._trace_store
            )
        t_wall = time.time()
        try:
            adopt_pages(self.cache, block, pages[: block.n_pages])
        except TransportError as e:
            self.cache.allocator.free(pages)
            _log.error(
                "adopting migrated pages for %s failed: %s", req.request_id, e
            )
            _rt.record_span(
                req.trace, "adopt", start=t_wall, status="error",
                parent=getattr(req, "_trace_parent", None),
                store=self._trace_store, replica=self.trace_name,
            )
            self._finish_stream(req, _Finish("error"))
            return "failed"
        _rt.record_span(
            req.trace, "adopt", start=t_wall,
            parent=getattr(req, "_trace_parent", None),
            store=self._trace_store, replica=self.trace_name,
            pages=block.n_pages,
        )
        slot = self.slots[slot_idx]
        slot.request = req
        self._tenancy_seq += 1
        slot.tenancy = self._tenancy_seq
        slot.claimed_at = self._clock()
        # adopted pages are all privately owned: this replica's prefix trie
        # never saw them (tier/trie integration is the PREFILL side's job)
        slot.pages = list(pages)
        slot.trie_pages = []
        slot.private_pages = list(pages)
        # mid-decode adoption (the decode-state leg of the MTKV1 envelope,
        # docs/failover.md): a live-migrated request arrives with its
        # accepted-token history and emitted-text cursor — seed both so
        # detokenization, stop handling, and max_tokens continue exactly
        # where the source replica left off. Absent (a plain first-token
        # block) everything below degrades to the PR-6 behavior.
        resume = state.get("resume")
        if resume:
            req.generated_tokens = [int(t) for t in resume["generated"]]
            req.emitted_len = int(resume.get("emitted_len", 0))
            req.n_generated = max(req.n_generated, len(req.generated_tokens))
        slot.generated = req.generated_tokens
        slot.emitted_text_len = req.emitted_len
        slot.prefill = None
        slot.pending_first = False
        table = np.zeros((self.pages_per_slot,), np.int32)
        table[: len(pages)] = pages
        self._page_tables[slot_idx] = table
        slot.position = state["position"]
        slot.last_token = state["first_token"]
        slot.fresh = True  # first token rides the override lane, like prefill
        # speculative engines adopt migrated work too
        # (docs/speculative.md#failure-boundaries): ngram mode rebuilds its
        # prompt-lookup index from the history that rode the wire; draft
        # mode pins γ=0 for this tenancy (spec_hold) — the draft cache's KV
        # never crossed the wire, and the classic lane inside the fused
        # round keeps the stream token-identical regardless
        slot.spec_hold = self.spec_mode == "draft"
        if self.spec_mode == "ngram":
            slot.ngram = _NgramIndex(
                self.ngram_n,
                list(req.prompt_tokens or [])
                + [int(t) for t in req.generated_tokens],
                self.NGRAM_LOOKBACK,
            )
        _obs.record_sched_queue_wait(
            entry.priority, max(0.0, now - entry.enqueued_at)
        )
        self._close_queue_span(req)
        req._decode_span = _rt.begin(
            req.trace, "decode", replica=self.trace_name,
            spec_mode=self.spec_mode or "-",
        )
        if resume:
            # the migrated token was accepted (and its text emitted) on the
            # source replica before the checkpoint: feed it through the
            # override lane without re-accepting — decode continues with
            # the NEXT sampled token, token-identical to no migration
            pass
        else:
            self._accept_token(slot_idx, state["first_token"])
        return "ok"

    def _fail_claims(self, chunk: list) -> None:
        """Unwind failed prefill claims: invalidate trie pages, free privately
        owned pages, clear the slot, and release the caller."""
        for slot_idx, req, claim in chunk:
            self._unwind_claim(claim)
            slot = self.slots[slot_idx]
            slot.request = None
            slot.pages = slot.trie_pages = slot.private_pages = []
            slot.ngram = None
            slot.prefill = None
            slot.pending_first = False
            self._active[slot_idx] = False
            self._finish_stream(req, _Finish("error"))

    def _claim_pages(self, req: Request) -> dict | None:
        """Slot page claim with prefix-cache sharing + eviction pressure.
        Runs under the request's ambient trace frame so fault firings in
        here (allocator exhaustion, tier corruption) land as ``fault``
        events on this request."""
        with _rt.active(req.trace, replica=self.trace_name):
            return self._claim_pages_traced(req)

    def _claim_pages_traced(self, req: Request) -> dict | None:
        # fault point (docs/faults.md): allocator exhaustion. The slot path
        # takes the preemption-safe requeue; the disagg prefill_sync path
        # raises OutOfPages and the coordinator falls back to unified.
        if _inject.fire("engine.out_of_pages"):
            return None
        n_prompt = len(req.prompt_tokens)
        max_total = min(n_prompt + req.params.max_tokens, self.max_model_len)
        n_pages = self.cache.pages_for(max_total)
        # multimodal requests key the trie by image-CONTENT hash ids
        # (req.cache_key_tokens) instead of their placeholder prompt ids —
        # identical images share their KV pages, different images land in
        # different trie branches (round 5; vLLM's mm prefix caching works
        # the same way: content-addressed image keys)
        pc = self.prefix_cache
        key_tokens = req.cache_key_tokens or req.prompt_tokens
        shared: list[int] = []
        promoted: list[int] = []
        if pc is not None:
            shared, _ = pc.acquire(key_tokens)
            if self.tiered is not None and shared:
                # per-PAGE units, matching the host/volume counts promote
                # records — the three tiers' hit counters are comparable
                _obs.record_tier_hit("hbm", n=len(shared))
            if self.tiered is not None:
                # lower-tier promotion: consecutive full-prompt pages past
                # the HBM trie hit, restored from host RAM / Volume with
                # their content pre-written — they join the trie as fresh
                # inserts below (refcount 1 via insert)
                promoted = self.tiered.promote(
                    key_tokens, n_have=len(shared)
                )
        need = n_pages - len(shared) - len(promoted)
        try:
            fresh = self.cache.allocator.alloc(need)
        except OutOfPages:
            if pc is not None:
                pc.evict(need)  # reclaim zero-ref cached pages and retry
                try:
                    fresh = self.cache.allocator.alloc(need)
                except OutOfPages:
                    pc.release(shared)
                    self.cache.allocator.free(promoted)
                    return None
            else:
                return None
        pages = shared + promoted + fresh
        # prefix-cache usage accounting (the OpenAI contract's
        # prompt_tokens_details.cached_tokens): prompt tokens served from
        # already-cached KV — trie hits + tier promotions — clamped to the
        # prompt (the last shared page may cover growth positions too)
        req.cached_prompt_tokens = min(
            n_prompt, (len(shared) + len(promoted)) * self.cache.page_size
        )
        trie_pages, private_pages = list(shared), list(promoted) + list(fresh)
        if pc is not None:
            pc.hits += bool(shared)
            pc.misses += not shared
            n_full = n_prompt // self.cache.page_size
            final, displaced = pc.insert(
                key_tokens, pages[:n_full], len(shared)
            )
            self.cache.allocator.free(displaced)
            trie_pages = list(final)
            private_pages = pages[n_full:]  # everything past the full-prompt
            pages = final + private_pages   # pages is trie-tracked
            if self.tiered is not None:
                self.tiered.register(key_tokens, final)
        return {
            "pages": pages,
            "trie_pages": trie_pages,
            "private_pages": private_pages,
            "n_prompt": n_prompt,
        }

    def _charge_slot_usage(self, slot: _Slot) -> None:
        """Charge the ending tenancy's occupancy interval to its tenant
        (device-seconds + KV page-seconds) — from BOTH release paths, with
        ``claimed_at`` zeroed so no path can double-charge."""
        req = slot.request
        if req is not None and slot.claimed_at > 0:
            self.usage.note_slot_release(
                req,
                pages=len(slot.pages),
                held_s=self._clock() - slot.claimed_at,
            )
        slot.claimed_at = 0.0

    def _release_slot_pages(self, slot: _Slot) -> None:
        self._charge_slot_usage(slot)
        if self.prefix_cache is not None:
            self.prefix_cache.release(slot.trie_pages)
            self.cache.allocator.free(slot.private_pages)
        else:
            self.cache.allocator.free(slot.pages)
        slot.pages, slot.trie_pages, slot.private_pages = [], [], []
        slot.ngram = None
        slot.prefill = None
        slot.pending_first = False
        slot.spec_hold = False
        if self._spec_ctrl is not None and slot.request is not None:
            # the controller's acceptance EWMA is per-request state: drop
            # it with the tenancy (both release paths call here or
            # _unwind_slot, so nothing leaks)
            self._spec_ctrl.forget(slot.request.request_id)

    def _dispatch_prefill_chunk(
        self, prompt_tokens: list, table, offset: int
    ) -> "jax.Array":
        """Dispatch ONE bucket-sized prefill chunk (async — the logits come
        back as a device future, nothing blocks the host): the unit both
        the atomic loop (``_run_prefill_chunks``) and the budgeted state
        machine (``_advance_pending_prefills``) advance by, so the two
        paths can never drift."""
        import functools

        C = self.prefill_buckets[-1]
        pad_tok = self.tokenizer.pad_id % self.cfg.vocab_size
        chunk = prompt_tokens[offset : offset + C]
        toks = np.full((1, C), pad_tok, np.int32)
        toks[0, : len(chunk)] = chunk
        fn = self._chunk_jits.get(offset)
        if fn is None:
            fn = jax.jit(
                functools.partial(
                    llama.prefill_chunk, q_offset=offset,
                    attn_impl=self._attn_impl, mesh=self.mesh,
                ),
                static_argnames=("cfg",),
                donate_argnums=(2, 3),
            )
            self._chunk_jits[offset] = fn
        logits, self.cache.k_pages, self.cache.v_pages = self._profiled(
            "prefill_chunk", f"off{offset}", fn
        )(
            self.params,
            jnp.asarray(toks),
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(table[None, :]),
            jnp.asarray([len(chunk)], np.int32),
            cfg=self.cfg,
        )
        if self.spec_mode == "draft":
            # the same cached jit serves the draft: cfg is a static call
            # argument, so target and draft get separate compile-cache
            # entries under one callable
            _, self.draft_cache.k_pages, self.draft_cache.v_pages = self._profiled(
                "draft_prefill", f"chunk-off{offset}", fn
            )(
                self.draft_params,
                jnp.asarray(toks),
                self.draft_cache.k_pages,
                self.draft_cache.v_pages,
                jnp.asarray(table[None, :]),
                jnp.asarray([len(chunk)], np.int32),
                cfg=self.draft_cfg,
            )
        return logits

    def _run_prefill_chunks(self, prompt_tokens: list, table) -> "jax.Array":
        """The atomic chunked-prefill loop (every chunk in one call), used
        by the slot-free disagg path (``_prefill_pages``) — the slot path
        runs the same chunks through the resumable state machine instead.
        Returns the final chunk's last-token logits."""
        n_prompt = len(prompt_tokens)
        C = self.prefill_buckets[-1]
        logits = None
        for offset in range(0, n_prompt, C):
            logits = self._dispatch_prefill_chunk(prompt_tokens, table, offset)
        return logits

    def _prefill_pages(self, req: Request, claim: dict) -> int:
        """Fill ``claim``'s pages with ``req``'s prompt KV and sample the
        first token — no slot touched (the disagg prefill path). Reuses the
        engine's compiled prefill shapes: short prompts ride row 0 of the
        ``(bucket, prefill_batch)`` program, long prompts take the chunked
        path. Returns the first sampled token."""
        pages, n_prompt = claim["pages"], claim["n_prompt"]
        table = np.zeros((self.pages_per_slot,), np.int32)
        table[: len(pages)] = pages
        p = req.params
        if n_prompt > self.prefill_buckets[-1]:
            logits = self._run_prefill_chunks(req.prompt_tokens, table)
            # the ops-level first-token helper: eager sample() builds its
            # own small compiled programs — report them through the same
            # chokepoint as the big jits
            first = self._profiled("sample", "first_token", sample)(
                logits,
                self._next_key(),
                jnp.asarray([p.temperature], np.float32),
                jnp.asarray([p.top_p], np.float32),
                jnp.asarray([p.top_k], np.int32),
                seeds=jnp.asarray([_req_seed(req)], np.int32),
                step_ids=jnp.asarray([n_prompt], np.int32),
            )
            return int(np.asarray(first)[0])
        bucket = self._bucket_for(n_prompt)
        B = self.prefill_batch
        pad_tok = self.tokenizer.pad_id % self.cfg.vocab_size
        tokens = np.full((B, bucket), pad_tok, np.int32)
        tokens[0, :n_prompt] = req.prompt_tokens
        tables = np.zeros((B, self.pages_per_slot), np.int32)
        tables[0] = table
        seq_lens = np.ones((B,), np.int32)
        seq_lens[0] = n_prompt
        temps = np.ones((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        temps[0], top_ps[0], top_ks[0] = p.temperature, p.top_p, p.top_k
        seeds[0] = _req_seed(req)
        next_tok, self.cache.k_pages, self.cache.v_pages = self._profiled(
            "prefill", f"b{bucket}x{B}", self._prefill_jit((bucket, B))
        )(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(tokens),
            jnp.asarray(tables),
            jnp.asarray(seq_lens),
            self._next_key(),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            jnp.asarray(seeds),
        )
        return int(np.asarray(next_tok)[0])

    def _prefill_long(self, slot_idx: int, req: Request, claim: dict) -> None:
        """Begin a chunked prefill (prompts beyond the largest bucket) as a
        RESUMABLE per-slot state machine: bucket-sized chunks attend to the
        cached prefix via the rectangular flash kernel (llama.prefill_chunk
        — bounded VMEM at any prompt length), and
        ``_advance_pending_prefills`` dispatches at most a budget's worth
        of chunks per tick, so the decode stall other streams see is
        bounded by ONE chunk instead of the whole prompt. Unbudgeted
        engines dispatch every chunk in one tick — but the first-token
        read still defers to the harvest queue, behind the decode
        dispatch."""
        t_start = time.monotonic()
        _obs.record_engine_queue_wait(t_start - req.created)
        pages = claim["pages"]
        slot = self.slots[slot_idx]
        slot.request = req
        self._tenancy_seq += 1
        slot.tenancy = self._tenancy_seq
        slot.claimed_at = self._clock()
        slot.pages = pages
        slot.trie_pages = claim["trie_pages"]
        slot.private_pages = claim["private_pages"]
        # the slot's generated list IS the request's own history (failover
        # checkpoints are built from the request after the slot is gone);
        # a resumed request arrives with both pre-seeded (docs/failover.md)
        slot.generated = req.generated_tokens
        slot.emitted_text_len = req.emitted_len
        slot.pending_first = False
        if self.spec_mode == "ngram":
            slot.ngram = _NgramIndex(
                self.ngram_n, req.prompt_tokens or [], self.NGRAM_LOOKBACK
            )
        table = np.zeros((self.pages_per_slot,), np.int32)
        table[: len(pages)] = pages
        self._page_tables[slot_idx] = table
        slot.prefill = _PendingPrefill(
            req=req, table=table, t_start=t_start, t_wall=time.time()
        )

    def _advance_pending_prefills(self, budget: int | None, spent: int) -> int:
        """Advance every mid-flight sliced prefill chunk by chunk until
        ``budget`` prompt tokens have been dispatched this tick (None =
        unlimited). The first chunk of an otherwise-idle tick always
        dispatches, so a budget smaller than one chunk still makes
        progress; slots advance in index order, so earlier admissions
        finish first. Returns the updated token spend."""
        C = self.prefill_buckets[-1]
        for i, s in enumerate(self.slots):
            pp = s.prefill
            if pp is None or s.request is None or s.request.aborted:
                continue  # aborted mid-prefill: the decode-tick reap unwinds
            n_prompt = len(pp.req.prompt_tokens)
            advanced = False
            try:
                while pp.offset < n_prompt and (
                    budget is None or spent == 0 or spent < budget
                ):
                    pp.logits = self._dispatch_prefill_chunk(
                        pp.req.prompt_tokens, pp.table, pp.offset
                    )
                    step = min(C, n_prompt - pp.offset)
                    pp.offset += step
                    spent += step
                    advanced = True
                if advanced:
                    pp.ticks += 1
                if pp.offset >= n_prompt:
                    self._finish_sliced_prefill(i, s, pp)
                elif advanced:
                    # paused mid-prompt: the next decode block dispatches
                    # BETWEEN this prompt's chunks — the slice the budget
                    # exists to cut
                    pp.suspensions += 1
                    _obs.record_prefill_sliced()
            except Exception:
                # same contract as the grouped path: a failed chunk must not
                # leave a half-initialized slot, leak its page claim, or
                # poison the trie with partially-written pages
                import traceback

                traceback.print_exc()
                self._fail_slot(i, s.request)
        return spent

    def _finish_sliced_prefill(
        self, slot_idx: int, slot: _Slot, pp: _PendingPrefill
    ) -> None:
        """Every chunk dispatched: sample the first token (async, seeded by
        (request seed, position) so slicing can never change it) and park
        it on the harvest queue — the blocking read happens after the next
        decode dispatch, exactly like a grouped prefill's."""
        req = pp.req
        p = req.params
        n_prompt = len(req.prompt_tokens)
        first = self._profiled("sample", "first_token", sample)(
            pp.logits,
            self._next_key(),
            jnp.asarray([p.temperature], np.float32),
            jnp.asarray([p.top_p], np.float32),
            jnp.asarray([p.top_k], np.int32),
            seeds=jnp.asarray([_req_seed(req)], np.int32),
            step_ids=jnp.asarray([n_prompt], np.int32),
        )
        slot.prefill = None
        slot.pending_first = True
        self._pending_harvest.append((
            first,
            [(slot_idx, req, 0, n_prompt, slot.tenancy)],
            {
                "phase": "prefill_chunked",
                "t_start": pp.t_start,
                "t_wall": pp.t_wall,
                "chunks": -(-n_prompt // self.prefill_buckets[-1]),
                "ticks": pp.ticks,
            },
        ))

    def _harvest_prefills(self) -> bool:
        """Materialize parked first tokens (the ONE blocking read per
        prefill dispatch, now overlapping the decode block already queued
        on device) and light their slots up through the fresh-slot
        override lane. Slots recycled while the prefill was in flight
        (abort/deadline unwound them) are skipped by request identity,
        like ``_process_block``'s snapshots."""
        tick = self._tick
        worked = False
        while self._pending_harvest:
            next_tok, rows, meta = self._pending_harvest.popleft()
            u_start = self._clock()  # usage meter: engine-clock domain
            try:
                next_np = np.asarray(next_tok)
                _tm_device(tick, "harvest")
            except Exception:
                # a prefill that failed ON DEVICE (materialization error):
                # unwind every still-owned slot and release the callers —
                # the no-hang contract of _fail_claims, post-dispatch
                import traceback

                traceback.print_exc()
                for slot_idx, req, _row, _n, tenancy in rows:
                    s = self.slots[slot_idx]
                    if s.request is req and s.tenancy == tenancy:
                        self._fail_slot(slot_idx, req)
                continue
            _obs.record_engine_phase(
                meta["phase"], time.monotonic() - meta["t_start"]
            )
            # roofline prefill seconds: the blocking-read interval on the
            # injectable clock (the dispatch itself is async; this is
            # where the host actually waits on prefill device work)
            self.usage.note_phase_seconds("prefill", self._clock() - u_start)
            u_calls = 1  # one dispatched program per harvest entry
            for slot_idx, req, row, n_prompt, tenancy in rows:
                s = self.slots[slot_idx]
                if s.request is not req or s.tenancy != tenancy or req.aborted:
                    # recycled or aborted while the prefill was in flight:
                    # the reap (this tick or the next) owns the unwind —
                    # same identity rule as _process_block's snapshots
                    continue
                s.pending_first = False
                self.stats.prompt_tokens += n_prompt
                # batched admissions share ONE weight stream: the first
                # accounted row carries the program's weight-read bytes
                self.usage.note_prompt(req, n_prompt, calls=u_calls)
                u_calls = 0
                s.position = n_prompt
                # failover resume (docs/failover.md): replay the accepted
                # generated prefix through the decode block program
                # (bit-identical KV), then feed the LAST accepted token —
                # which the client already has — through the override lane
                # instead of the prefill's sampled token, so the next
                # sampled token carries the same (seed, position) key as
                # the uninterrupted run and the stream continues
                # identically
                rs = getattr(req, "_resume_state", None)
                if rs is not None:
                    replay = rs["replay"]
                    self._replay_decode_prefix(slot_idx, replay)
                    s.position = n_prompt + len(replay) - 1
                    s.last_token = int(replay[-1])
                    if self.spec_mode == "draft":
                        # the replay rebuilt TARGET KV only: the draft
                        # cache has a generated-prefix hole, so this
                        # tenancy never proposes (γ pinned 0 — the fused
                        # round's classic lane; token-identical either way)
                        s.spec_hold = True
                else:
                    s.last_token = int(next_np[row])
                s.fresh = True
                worked = True
                if meta["phase"] == "prefill_chunked":
                    sliced = meta["ticks"] > 1
                    _rt.record_span(
                        req.trace, "prefill", start=meta["t_wall"],
                        store=self._trace_store, replica=self.trace_name,
                        n_prompt=n_prompt, chunked=True,
                        chunks=meta["chunks"], sliced=sliced,
                        budget=self.prefill_budget,
                    )
                    if sliced:
                        _rt.record_span(
                            req.trace, "prefill_wait", start=meta["t_wall"],
                            store=self._trace_store, replica=self.trace_name,
                            ticks=meta["ticks"], chunks=meta["chunks"],
                        )
                else:
                    _rt.record_span(
                        req.trace, "prefill", start=meta["t_wall"],
                        store=self._trace_store, replica=self.trace_name,
                        n_prompt=n_prompt, bucket=meta["bucket"],
                    )
                req._decode_span = _rt.begin(
                    req.trace, "decode", replica=self.trace_name,
                    spec_mode=self.spec_mode or "-",
                )
                if rs is not None:
                    # resumed: the fed token was already accepted and its
                    # text emitted before the failure (slot.generated /
                    # emitted_text_len carry the history from the install)
                    req._resume_state = None
                else:
                    self._accept_token(slot_idx, s.last_token)
            _tm(tick, "accept")
        return worked

    def _replay_decode_prefix(self, slot_idx: int, replay: list) -> None:
        """Teacher-forced KV rebuild for a failover-resumed request
        (docs/failover.md): feed each already-accepted token except the
        last through THE decode block program — the override lane, only
        this slot active — one token per dispatch. Because it is the same
        compiled body the original run executed, with the same carry
        inputs (attention is position-bounded, so the block's trailing
        sampled-garbage writes at positions not yet fed are invisible and
        overwritten when those positions ARE fed), the rebuilt KV is
        BIT-IDENTICAL to what the dead replica's decode wrote — a prefill
        recompute of the same positions drifts by a bf16 rounding
        asymmetry (prefill attends over unrounded in-graph k/v; decode
        reads the rounded cache) and deterministically flips greedy
        argmaxes at unlucky margins. All dispatches are async: the replay
        queues device work without blocking the scheduler thread."""
        if len(replay) <= 1:
            return  # generated[-1] rides the override lane of live decode
        base_pos = self.slots[slot_idx].position
        B = self.max_slots
        active = np.zeros((B,), bool)
        active[slot_idx] = True
        mask = np.zeros((B,), bool)
        mask[slot_idx] = True
        override = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        prev = jnp.zeros((B,), jnp.int32)
        # sampling args are irrelevant to the KV writes (the scatter uses
        # the FED token; sampled outputs are discarded) — defaults keep
        # sample() off its expensive filter path
        ones = jnp.ones((B,), jnp.float32)
        zeros_i = jnp.zeros((B,), jnp.int32)
        no_seed = jnp.full((B,), -1, jnp.int32)
        tables = jnp.asarray(self._page_tables.copy())
        for i, tok in enumerate(replay[:-1]):
            override[slot_idx] = int(tok)
            positions[slot_idx] = base_pos + i
            _toks, _last, self.cache.k_pages, self.cache.v_pages = (
                self._profiled(
                    "block", f"s{self.max_slots}k{self.decode_block}",
                    self._block_jit,
                )(
                    self.params,
                    self.cache.k_pages,
                    self.cache.v_pages,
                    prev,
                    jnp.asarray(override.copy()),
                    jnp.asarray(mask.copy()),
                    jnp.asarray(positions.copy()),
                    tables,
                    jnp.asarray(active.copy()),
                    self._next_key(),
                    ones,
                    ones,
                    zeros_i,
                    no_seed,
                )
            )

    def _fail_slot(self, slot_idx: int, req: Request) -> None:
        """Release one mid-prefill slot whose work failed AFTER dispatch
        (chunk advance or harvest): unwind from the slot's own page lists
        and fail the caller loudly — the one sequence shared by every
        post-dispatch prefill failure path."""
        s = self.slots[slot_idx]
        self._unwind_slot(s)
        s.request = None
        self._active[slot_idx] = False
        self._finish_stream(req, _Finish("error"))

    def _unwind_slot(self, slot: _Slot) -> None:
        """Unwind a slot whose prefill never completed (abort, deadline, or
        failure mid-chunk / pre-harvest): the ``_fail_claims`` ownership
        rule, reconstructed from the slot's own page lists — trie pages
        invalidated so no later request can share never-/partially-written
        KV, exclusively-owned pages freed."""
        self._charge_slot_usage(slot)
        self._unwind_claim({
            "pages": slot.pages,
            "trie_pages": slot.trie_pages,
            "private_pages": slot.private_pages,
        })
        slot.pages, slot.trie_pages, slot.private_pages = [], [], []
        slot.prefill = None
        slot.pending_first = False
        slot.ngram = None
        slot.spec_hold = False
        if self._spec_ctrl is not None and slot.request is not None:
            self._spec_ctrl.forget(slot.request.request_id)

    def _prefill_group(self, bucket: int, group: list, is_mm: bool = False) -> None:
        t_start = time.monotonic()
        t_wall = time.time()  # span timestamps are wall-clock
        for _slot_idx, req, _claim in group:
            _obs.record_engine_queue_wait(t_start - req.created)
        B = self.prefill_batch  # fixed compile shape; short groups pad
        pad_tok = self.tokenizer.pad_id % self.cfg.vocab_size
        tokens = np.full((B, bucket), pad_tok, np.int32)
        tables = np.zeros((B, self.pages_per_slot), np.int32)  # pad rows: trash
        seq_lens = np.ones((B,), np.int32)
        temps = np.ones((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        images = None
        if is_mm:
            S = self.vision_cfg.vision.image_size
            images = np.zeros((B, S, S, 3), np.float32)
        for i, (slot_idx, req, claim) in enumerate(group):
            pages, n_prompt = claim["pages"], claim["n_prompt"]
            slot = self.slots[slot_idx]
            slot.request = req
            self._tenancy_seq += 1
            slot.tenancy = self._tenancy_seq
            slot.claimed_at = self._clock()
            slot.pages = pages
            slot.trie_pages = claim["trie_pages"]
            slot.private_pages = claim["private_pages"]
            slot.generated = req.generated_tokens  # request-owned history
            slot.emitted_text_len = req.emitted_len
            slot.prefill = None
            slot.spec_hold = False
            if self.spec_mode == "ngram":
                slot.ngram = _NgramIndex(
                    self.ngram_n, req.prompt_tokens or [], self.NGRAM_LOOKBACK
                )
                for t in req.generated_tokens:
                    # failover-resumed requests arrive with accepted
                    # history (replayed at harvest): the lookup index must
                    # match an uninterrupted run's
                    slot.ngram.push(int(t))
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[: len(pages)] = pages
            self._page_tables[slot_idx] = table
            tokens[i, :n_prompt] = req.prompt_tokens
            tables[i] = table
            seq_lens[i] = n_prompt
            p = req.params
            temps[i], top_ps[i], top_ks[i] = p.temperature, p.top_p, p.top_k
            seeds[i] = _req_seed(req)
            if is_mm:
                images[i] = req.image

        if is_mm:
            next_tok, self.cache.k_pages, self.cache.v_pages = (
                self._profiled(
                    "prefill_mm", f"b{bucket}x{B}",
                    self._prefill_mm_jit((bucket, B)),
                )(
                    self.params,
                    self.vision_params,
                    self.cache.k_pages,
                    self.cache.v_pages,
                    jnp.asarray(images),
                    jnp.asarray(tokens),
                    jnp.asarray(tables),
                    jnp.asarray(seq_lens),
                    self._next_key(),
                    jnp.asarray(temps),
                    jnp.asarray(top_ps),
                    jnp.asarray(top_ks),
                    jnp.asarray(seeds),
                )
            )
        else:
            next_tok, self.cache.k_pages, self.cache.v_pages = self._profiled(
                "prefill", f"b{bucket}x{B}", self._prefill_jit((bucket, B))
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(tokens),
                jnp.asarray(tables),
                jnp.asarray(seq_lens),
                self._next_key(),
                jnp.asarray(temps),
                jnp.asarray(top_ps),
                jnp.asarray(top_ks),
                jnp.asarray(seeds),
            )
        if self.spec_mode == "draft":
            # fill the draft model's cache over the same pages (same tables:
            # page ids are shared between the two caches)
            _, self.draft_cache.k_pages, self.draft_cache.v_pages = (
                self._profiled(
                    "draft_prefill", f"b{bucket}x{B}",
                    self._draft_prefill_jit((bucket, B)),
                )(
                    self.draft_params,
                    self.draft_cache.k_pages,
                    self.draft_cache.v_pages,
                    jnp.asarray(tokens),
                    jnp.asarray(tables),
                    jnp.asarray(seq_lens),
                )
            )
        # first tokens stay ON DEVICE: park (next_tok, group) for harvest
        # after the next decode dispatch — the host never blocks on a
        # prefill read here, so already-running streams keep their cadence
        # (this used to be a blocking np.asarray that stalled every
        # in-flight stream for the whole prefill duration)
        rows = []
        for i, (slot_idx, req, claim) in enumerate(group):
            self.slots[slot_idx].pending_first = True
            rows.append((
                slot_idx, req, i, claim["n_prompt"],
                self.slots[slot_idx].tenancy,
            ))
        self._pending_harvest.append((
            next_tok,
            rows,
            {
                "phase": "prefill",
                "t_start": t_start,
                "t_wall": t_wall,
                "bucket": bucket,
            },
        ))

    def _decode_tick(self) -> bool:
        tick = self._tick
        # fault point (docs/faults.md): one stalled decode tick — a slow
        # collective, a preempted host thread. Latency only; the tick then
        # proceeds normally and requests still terminate.
        if _inject.fire("engine.slow_decode"):
            for s in self.slots:
                if s.request is not None:
                    _rt.event(
                        s.request.trace, "fault", store=self._trace_store,
                        replica=self.trace_name, point="engine.slow_decode",
                    )
            time.sleep(0.05)
        # reap aborted slots before spending a step on them (deadline-
        # expired aborts finish with their own reason, not a fake "stop")
        for i, s in enumerate(self.slots):
            if not s.free and s.request.aborted:
                req = s.request
                self._finish_stream(
                    req,
                    _Finish("deadline") if req.deadline_expired else _FINISH,
                )
                if s.prefill is not None or s.pending_first:
                    # the abort landed mid-prefill (sliced chunks pending,
                    # or first token unharvested): pages may hold PARTIAL
                    # KV — unwind the claim (trie pages invalidated) rather
                    # than releasing them as valid, shareable prefix KV
                    self._unwind_slot(s)
                else:
                    self._release_slot_pages(s)
                s.request = None
                self._active[i] = False
        _tm(tick, "policy")
        live = [i for i, s in enumerate(self.slots) if s.decodable]

        if self.spec_gamma:
            # no pipelined dispatch to protect in spec mode: harvest first
            # so freshly prefilled slots join this very tick
            worked = self._harvest_prefills()
            live = [i for i, s in enumerate(self.slots) if s.decodable]
            if not live:
                return worked
            self._active[:] = False
            # reset dead-slot sampling params (same rationale as
            # _dispatch_block: stale top_p/top_k keeps sample()'s runtime
            # lax.cond on the expensive sort path)
            self._temps[:] = 1.0
            self._top_ps[:] = 1.0
            self._top_ks[:] = 0
            self._seeds[:] = -1
            gammas = np.zeros((self.max_slots,), np.int32)
            batch_fill = len(live) / max(1, self.max_slots)
            # prefill-budget contention (docs/scheduling.md): chunked
            # prefills mid-slice or first tokens parked unharvested mean
            # admission cadence is live — long speculative rounds would
            # stretch the tick it rides on
            prefill_pressure = bool(self._pending_harvest) or any(
                s.prefill is not None for s in self.slots
            )
            for i in live:
                s = self.slots[i]
                self._active[i] = True
                self._tokens[i] = s.last_token
                self._positions[i] = s.position
                s.fresh = False  # spec rounds feed host tokens directly
                p = s.request.params
                self._temps[i] = p.temperature
                self._top_ps[i] = p.top_p
                self._top_ks[i] = p.top_k
                self._seeds[i] = _req_seed(s.request)
                gammas[i] = self._slot_gamma(s, batch_fill, prefill_pressure)
            ngram_props = None
            if self.spec_mode == "ngram":
                # proposal availability is host-known BEFORE dispatch: a
                # lane whose trailing-ngram lookup comes up empty has
                # nothing to verify, so its γ drops to 0 (the fused
                # program's classic lane) — and an all-empty round falls
                # through to the strictly-cheaper block program below
                # instead of paying a 1-token spec round. No controller
                # involvement: an empty lookup is absence of evidence,
                # not rejection evidence (docs/speculative.md#gamma-
                # schedule).
                ngram_props = self._ngram_proposals(gammas)
                gammas = np.minimum(
                    gammas, ngram_props[1].astype(np.int32)
                )
            _tm(tick, "admit")  # spec batch staging: slot-state bookkeeping
            if not any(gammas[i] for i in live):
                # whole-round fallback: nobody speculates this round
                # (pressure, collapse, or sampling lanes only) — the
                # classic block program is strictly cheaper than a
                # γ-shaped verify pass, so spec can never COST latency
                self._spec_fallbacks += 1
                for i in live:
                    # re-enter the block program through the override lane
                    # (spec rounds end on host-known tokens, not
                    # device-resident ones)
                    self.slots[i].fresh = True
                self._dispatch_block(live)
                worked = self._harvest_prefills() or True
                return self._process_block() or worked
            return self._spec_round(live, gammas, ngram_props) or worked

        # pipelined path: keep one decode block in flight ahead of the one
        # being read, so the device never waits on the host round trip
        worked = False
        if live:
            self._dispatch_block(live)
            worked = True
        else:
            # no decodable slots: a dispatch gap from here on is idleness
            # or prefill ramp-up, not a stall against live streams
            self._last_dispatch_at = None
        # harvest AFTER the dispatch: the blocking first-token reads overlap
        # the decode block already queued on device — the deferral that
        # makes admission stall-free
        worked = self._harvest_prefills() or worked
        if self._inflight and (len(self._inflight) >= 2 or not live):
            worked = self._process_block() or worked
        return worked

    def _dispatch_block(self, live: list[int]) -> None:
        """Queue one decode block (async — returns before it runs).

        Slot-state lag safety: a slot that finishes (eos/stop/length) while
        an already-dispatched block still decodes it only ever writes
        generated-position KV, i.e. its own private pages; if those pages are
        freed and reclaimed, the reclaimer's prefill is dispatched AFTER this
        block (device program order) and overwrites the stale writes. The
        per-block snapshot pins request identity so the host drops output
        rows whose slot was recycled.
        """
        tick = self._tick
        now = time.monotonic()
        if self._last_dispatch_at is not None:
            # dispatch-to-dispatch gap while decodable slots existed the
            # whole time: the stall the prefill budget bounds to ~one chunk
            _obs.record_decode_stall(now - self._last_dispatch_at)
        self._last_dispatch_at = now
        self.watermarks.note_dispatch()
        _obs.record_engine_batch(len(live))
        self._active[:] = False
        self._override_mask[:] = False
        # reset dead-slot sampling params to the no-filter defaults: a stale
        # top_p/top_k from a finished request would keep sample()'s runtime
        # lax.cond on the expensive sort path for every later block
        self._temps[:] = 1.0
        self._top_ps[:] = 1.0
        self._top_ks[:] = 0
        self._seeds[:] = -1
        for i in live:
            s = self.slots[i]
            self._active[i] = True
            if s.fresh:
                # freshly prefilled: first token is host-known (sampled by
                # the prefill program); continuing slots feed the previous
                # block's device-resident token
                self._override[i] = s.last_token
                self._override_mask[i] = True
                self._opt_positions[i] = s.position
                s.fresh = False
            self._positions[i] = self._opt_positions[i]
            p = s.request.params
            self._temps[i] = p.temperature
            self._top_ps[i] = p.top_p
            self._top_ks[i] = p.top_k
            self._seeds[i] = _req_seed(s.request)
        prev = self._device_tokens
        if prev is None:
            prev = jnp.zeros((self.max_slots,), jnp.int32)
        n = max(1, int(self.decode_steps))  # runtime-mutable: read ONCE
        if n <= 1:
            # classic pipelined block: byte-identical fall-through
            toks, last, self.cache.k_pages, self.cache.v_pages = self._profiled(
                "block", f"s{self.max_slots}k{self.decode_block}",
                self._block_jit,
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                prev,
                jnp.asarray(self._override.copy()),
                jnp.asarray(self._override_mask.copy()),
                jnp.asarray(self._positions.copy()),
                jnp.asarray(self._page_tables.copy()),
                jnp.asarray(self._active.copy()),
                self._next_key(),
                jnp.asarray(self._temps.copy()),
                jnp.asarray(self._top_ps.copy()),
                jnp.asarray(self._top_ks.copy()),
                jnp.asarray(self._seeds.copy()),
            )
            valid = None
            n = self.decode_block
        else:
            # macro-step program (docs/multistep.md): per-slot budgets let
            # the device die at exactly the token the host would finish on
            # — remaining max_tokens (counting in-flight un-harvested
            # tokens) and remaining context, whichever is tighter
            budgets = np.ones((self.max_slots,), np.int32)
            for i in live:
                s = self.slots[i]
                p = s.request.params
                g_opt = len(s.generated) + (
                    int(self._opt_positions[i]) - s.position
                )
                budgets[i] = max(1, min(
                    p.max_tokens - g_opt,
                    (self.max_model_len - 1) - int(self._opt_positions[i]),
                ))
            (
                toks, valid, last, self.cache.k_pages, self.cache.v_pages,
            ) = self._profiled(
                "multistep", f"s{self.max_slots}n{n}", self._multistep_jit(n)
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                prev,
                jnp.asarray(self._override.copy()),
                jnp.asarray(self._override_mask.copy()),
                jnp.asarray(self._positions.copy()),
                jnp.asarray(self._page_tables.copy()),
                jnp.asarray(self._active.copy()),
                self._next_key(),
                jnp.asarray(self._temps.copy()),
                jnp.asarray(self._top_ps.copy()),
                jnp.asarray(self._top_ks.copy()),
                jnp.asarray(self._seeds.copy()),
                jnp.asarray(budgets),
            )
        self._device_tokens = last
        # snapshot pins (slot, request, tenancy): request identity alone is
        # not enough — a failover-resumed request is the same object back
        # in a NEW tenancy, and this block belongs to its old one
        self._inflight.append((
            toks,
            valid,
            [
                (i, self.slots[i].request, self.slots[i].tenancy)
                for i in live
            ],
            None,  # spec_meta: classic/macro-step blocks carry none
        ))
        for i in live:
            self._opt_positions[i] += n
        _tm(tick, "decode_dispatch")

    def _process_block(self) -> bool:
        tick = self._tick
        toks, valid, snapshot, spec_meta = self._inflight.popleft()
        t_wait = time.monotonic()
        u_start = self._clock()  # usage meter: engine-clock domain
        toks_np = np.asarray(toks)  # [K, B] — the ONE blocking read per block
        # the macro-step harvest plane (docs/multistep.md): the validity
        # mask rides the SAME round trip as the tokens — per-slot accept
        # stops at the first invalid row (the lane died at its stop token
        # or length budget on-device; in a spec round, at its accept cut)
        valid_np = None if valid is None else np.asarray(valid)
        _obs.record_engine_phase("decode_wait", time.monotonic() - t_wait)
        self.usage.note_phase_seconds("decode", self._clock() - u_start)
        _tm_device(tick, "harvest")
        n_steps = int(toks_np.shape[0])
        # only steps with a live lane executed (masked_scan's cond skips
        # the rest once every lane died): count the truth, not the
        # program length. A spec round is ONE verify pass regardless of
        # how many chain rows it emitted.
        executed = (
            n_steps if valid_np is None
            else int(valid_np.any(axis=1).sum())
        )
        self.stats.steps += 1 if spec_meta is not None else executed
        worked = False
        accepted = 0
        for i, req, tenancy in snapshot:
            s = self.slots[i]
            if s.request is not req or s.tenancy != tenancy or req.aborted:
                continue  # slot finished/recycled while the block was in flight
            taken = 0
            for k in range(n_steps):
                if s.request is not req or s.tenancy != tenancy:
                    break  # finished mid-block
                if valid_np is not None and not valid_np[k, i]:
                    break  # lane died on-device: the tail rows are holds
                s.position += 1
                s.last_token = int(toks_np[k, i])
                self._accept_token(i, s.last_token)
                taken += 1
                worked = True
            accepted += taken
            if spec_meta is not None:
                n_p = int(spec_meta["proposed"][i])
                acc = max(0, taken - 1)
                self.stats.spec_proposed += n_p
                self.stats.spec_accepted += acc
                if req.trace is not None:
                    _rt.event(
                        req.trace, "spec_verify",
                        store=self._trace_store, replica=self.trace_name,
                        proposed=n_p, accepted=acc,
                        gamma=int(spec_meta["gammas"][i]),
                    )
                if s.request is req and s.tenancy == tenancy:
                    if self._spec_ctrl is not None and n_p > 0:
                        # the controller sees exactly what the host
                        # accepted (stop/length cuts included): its EWMA
                        # tracks USEFUL acceptance, not device acceptance
                        self._spec_ctrl.observe(req.request_id, n_p, acc)
                    # the round ended on a host-known token: the next
                    # dispatch (spec or classic fallback) re-feeds it
                    # through the fresh-slot override lane
                    s.fresh = True
            elif (
                valid_np is not None
                and taken < n_steps
                and s.request is req
                and s.tenancy == tenancy
            ):
                # the device retired this lane early but the host did NOT
                # finish the request (a budget/position desync — should
                # not happen; self-heal rather than diverge): resync the
                # slot through the fresh-slot override lane, which re-feeds
                # the last ACCEPTED token at the host-known position
                s.fresh = True
        if spec_meta is None:
            # tokens-per-dispatch accounting covers classic AND macro-step
            # (N=1 included): the A/B lever the bench reads is one series
            self._ms_dispatches += 1
            self._ms_tokens += accepted
            _obs.record_multistep_dispatch(
                tokens=accepted, steps_saved=n_steps - executed
            )
            prof = self.profiler
            if prof is not None:
                prof.note_dispatch_tokens(
                    accepted, steps=int(self.decode_steps)
                )
        else:
            # spec rounds keep their own tokens-per-dispatch plane
            # (docs/speculative.md#series): γ=0 fallback ROUNDS are counted
            # in _decode_tick, not here — this is a dispatched spec round
            self._spec_rounds += 1
            self._spec_round_tokens += accepted
            gw = self._spec_gamma_window
            for i, _req, _tenancy in snapshot:
                gw.append(int(spec_meta["gammas"][i]))
            if len(gw) > 4096:
                del gw[: len(gw) - 4096]
            prof = self.profiler
            if prof is not None:
                prof.note_dispatch_tokens(accepted, steps=1)
        _tm(tick, "accept")
        return worked

    def _slot_gamma(
        self, s: _Slot, batch_fill: float, prefill_pressure: bool
    ) -> int:
        """Per-slot proposal budget for the next fused round
        (docs/speculative.md#gamma-schedule). 0 = the classic lane inside
        the same program. Sampling lanes (temperature > 0) never
        speculate — the spec accept path is not (seed, position)-keyed,
        and the classic lane keeps them token-identical to a non-spec
        engine; ``spec_hold`` pins resumed/adopted draft-mode tenancies
        whose draft cache has a KV hole."""
        p = s.request.params
        if p.temperature > 0 or s.spec_hold:
            return 0
        cap = max(0, min(int(self.spec_depth), self.spec_gamma))
        if self.spec_adaptive and self._spec_ctrl is not None:
            g = self._spec_ctrl.gamma_for(
                s.request.request_id,
                gamma_cap=cap,
                batch_fill=batch_fill,
                prefill_pressure=prefill_pressure,
            )
        else:
            g = cap
        # never propose past the request's own stopping point: tokens
        # beyond max_tokens / context length would be verified, then
        # discarded by the host accept loop — pure wasted verify flops
        room = min(
            p.max_tokens - len(s.generated) - 1,
            (self.max_model_len - 1) - s.position - 1,
        )
        return max(0, min(g, room))

    def _spec_round(self, live: list[int], gammas, ngram_props=None) -> bool:
        """One fused speculative round (docs/speculative.md#program-shape):
        propose(γ) + verify + accept in ONE dispatch, harvested through
        the SAME ``_process_block`` site as macro-step blocks (the [N, B]
        validity plane). Spec rounds never pipeline — the next round's
        positions depend on this round's acceptance — so the block is
        processed immediately after dispatch."""
        tick = self._tick
        now = time.monotonic()
        if self._last_dispatch_at is not None:
            _obs.record_decode_stall(now - self._last_dispatch_at)
        self._last_dispatch_at = now
        self.watermarks.note_dispatch()
        _obs.record_engine_batch(len(live))
        gam = jnp.asarray(gammas)
        if self.spec_mode == "ngram":
            # _decode_tick already ran the lookup to γ-clamp empty lanes
            props, n_prop = (
                ngram_props
                if ngram_props is not None
                else self._ngram_proposals(gammas)
            )
            (
                toks, valid, last, self.cache.k_pages, self.cache.v_pages,
            ) = self._profiled(
                "ngram_verify", f"s{self.max_slots}g{self.spec_gamma}",
                self._ngram_jit,
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(props),
                jnp.asarray(n_prop),
                gam,
                jnp.asarray(self._tokens.copy()),
                jnp.asarray(self._positions.copy()),
                jnp.asarray(self._page_tables.copy()),
                jnp.asarray(self._active.copy()),
                self._next_key(),
                jnp.asarray(self._temps.copy()),
                jnp.asarray(self._top_ps.copy()),
                jnp.asarray(self._top_ks.copy()),
                jnp.asarray(self._seeds.copy()),
            )
            proposed = n_prop
        else:
            (
                toks, valid, last,
                self.cache.k_pages, self.cache.v_pages,
                self.draft_cache.k_pages, self.draft_cache.v_pages,
            ) = self._profiled(
                "spec_verify", f"s{self.max_slots}g{self.spec_gamma}",
                self._spec_jit,
            )(
                self.params,
                self.draft_params,
                self.cache.k_pages,
                self.cache.v_pages,
                self.draft_cache.k_pages,
                self.draft_cache.v_pages,
                jnp.asarray(self._tokens.copy()),
                jnp.asarray(self._positions.copy()),
                jnp.asarray(self._page_tables.copy()),
                jnp.asarray(self._active.copy()),
                gam,
                self._next_key(),
                jnp.asarray(self._temps.copy()),
                jnp.asarray(self._top_ps.copy()),
                jnp.asarray(self._top_ks.copy()),
                jnp.asarray(self._seeds.copy()),
            )
            # the draft proposes its full budget in-graph (capacity-died
            # lanes are masked by prop_valid and never accepted, but they
            # were still paid for — count them as proposed)
            proposed = gammas
        del last  # spec rounds end on host-known tokens (fresh resync)
        self._device_tokens = None
        self._inflight.append((
            toks,
            valid,
            [
                (i, self.slots[i].request, self.slots[i].tenancy)
                for i in live
            ],
            {"gammas": gammas, "proposed": proposed},
        ))
        _tm(tick, "decode_dispatch")
        return self._process_block()

    def _accept_token(self, slot_idx: int, token: int) -> None:
        slot = self.slots[slot_idx]
        req = slot.request
        # canary drift injection: deterministically flip ONE accepted token,
        # gated on the synthetic probe tenant so user-visible streams (and
        # the chaos harness's token-identity invariant) are never corrupted —
        # only the golden-set comparison sees the flip
        if req.tenant == _CANARY_TENANT and _inject.fire(
            "engine.canary_token_corrupt"
        ):
            token = (token + 1) % self.cfg.vocab_size
        self.stats.generated_tokens += 1
        # usage meter: same site as the stats counter (conservation is
        # structural); slot.position is the context the decode attended over
        self.usage.note_token(req, slot.position)
        self.watermarks.note_accept()
        # token-level latency: TTFT on the request's first token, the
        # inter-token gap (TPOT) on every later one. Honest wall-clock from
        # the client's seat: pipelined blocks emit in bursts, and the
        # histogram shows exactly that.
        now = time.monotonic()
        # canary probes keep their first/last-token bookkeeping (the prober
        # measures client-side) but must NOT feed the unlabeled TTFT/TPOT
        # histograms: those drive the SLO burn gauges and the autoscaler,
        # and synthetic probes would pollute both. Canary latency lands in
        # the dedicated canary histograms instead.
        if req.first_token_at is None:
            req.first_token_at = now
            if req.tenant != _CANARY_TENANT:
                _obs.record_ttft(now - req.created)
            if req.trace is not None:
                req.trace.root.attrs["ttft_s"] = round(now - req.created, 6)
        elif req.tenant != _CANARY_TENANT:
            _obs.record_tpot(now - req.last_token_at)
        req.last_token_at = now
        req.n_generated += 1
        finished = False
        reason = None
        appended = token != self.tokenizer.eos_id
        if not appended:
            finished, reason = True, "stop"
        else:
            slot.generated.append(token)
            if slot.ngram is not None:
                slot.ngram.push(token)  # O(1) prompt-lookup index update
            if len(slot.generated) >= req.params.max_tokens:
                finished, reason = True, "length"
            elif slot.position + 1 >= self.max_model_len:
                finished, reason = True, "length"

        # macro-step path (docs/multistep.md): token-level bookkeeping
        # above stays on the scheduler thread — the harvest boundary — but
        # detokenization, stop-string scanning, and emission move to the
        # DetokWorker. Streams the worker already owns keep routing even
        # after the knob drops back to 1 (ordering), and a dead worker
        # falls through to the inline path below.
        w = self._detok
        if (
            self.decode_steps > 1
            or self.spec_gamma > 0
            or (w is not None and w.owns(req))
        ):
            if w is None or not w.alive:
                w = self._ensure_detok()
            if w.alive:
                tick = self._tick
                _tm(tick, "accept")
                if not w.owns(req):
                    prior = (
                        slot.generated[:-1] if appended
                        else list(slot.generated)
                    )
                    w.register(
                        req, prior,
                        max(slot.emitted_text_len, req.emitted_len),
                    )
                if appended:
                    w.feed(req, token)
                # enqueue cost only: the decode itself runs off-thread
                _tm(tick, "detokenize")
                if finished:
                    # release BEFORE the finish marker is enqueued: the
                    # worker thread can deliver it (and wake the client)
                    # ahead of the scheduler's next bytecode, and a
                    # client-visible finish must imply pages/slot freed
                    self._release_slot_pages(slot)
                    slot.request = None
                    self._active[slot_idx] = False
                    self._finish_stream(req, _Finish(reason))
                return

        # incremental detokenization: emit the stable new suffix. Profiled
        # as its own phase (the ROADMAP #3 "move detokenization off the
        # scheduler thread" candidate needs its cost attributed first):
        # everything since the last mark is accept bookkeeping, the decode
        # call itself is detokenize.
        tick = self._tick
        _tm(tick, "accept")
        text = self.tokenizer.decode(slot.generated)
        _tm(tick, "detokenize")
        if req.params.stop:
            for stop_s in req.params.stop:
                idx = text.find(stop_s)
                if idx >= 0:
                    text = text[:idx]
                    finished, reason = True, "stop"
                    break
        # hold back any trailing text that is still a prefix of a stop string
        # (OpenAI/vLLM contract: stop='END' arriving as 'E','N','D' must not
        # leak 'EN' into the stream before the match completes)
        safe_len = (
            len(text)
            if finished
            else _stop_safe_len(text, req.params.stop)
        )
        new = text[slot.emitted_text_len : safe_len]
        if new and (finished or not _unstable_tail(new)):
            req.out_queue.put(new)
            slot.emitted_text_len = slot.emitted_text_len + len(new)
            # mirror onto the request: a failover checkpoint taken after
            # this replica dies resumes emission from exactly this cursor
            req.emitted_len = slot.emitted_text_len
        if finished:
            # same release-before-finish ordering as the worker branch
            # above: a client that wakes on the marker must observe the
            # slot and its pages already freed
            self._release_slot_pages(slot)
            slot.request = None
            self._active[slot_idx] = False
            self._finish_stream(req, _Finish(reason))


def build_engine(
    model: str = "llama2-7b",
    model_dir: str | None = None,
    **engine_kw,
) -> LLMEngine:
    """Factory mirroring the reference's MODEL_NAME/engine-flags surface
    (vllm_inference.py:54-58,168-209)."""
    if model_dir is not None:
        cfg = llama.LlamaConfig.from_hf_config(f"{model_dir}/config.json")
    else:
        if model not in MODEL_PRESETS:
            raise ValueError(
                f"unknown model preset {model!r}; known: {sorted(MODEL_PRESETS)}"
            )
        cfg = MODEL_PRESETS[model]()
    return LLMEngine(cfg, model_dir=model_dir, **engine_kw)
