"""Continuous-batching LLM engine — the vLLM-engine replacement, TPU-first.

Implements the serving core behind the reference's north-star example
(vllm_inference.py: an OpenAI-compatible server wrapping an engine with
continuous batching, paged KV, streaming; SURVEY.md §3.2's HOT LOOP).

TPU-first architecture (vs vLLM's CUDA design):
- **static shapes everywhere**: the decode step is ONE jitted program over a
  fixed slot count; requests come and go by flipping an ``active`` mask and
  rewriting page tables — XLA never recompiles as batch composition changes.
- **prefill buckets**: prompts pad to the next bucket (128/256/.../max) so
  prefill compiles once per bucket, not per length (the retrace-thrash
  killer; SURVEY.md §7 hard part #5).
- **sampling fused into the decode program**: only the sampled token ids
  (max_slots x int32) cross the device->host boundary per step.
- **page cache donated** through the step so XLA updates KV in place.
- host side: admission (claim slot + pages), stop handling, incremental
  detokenization, per-request output queues. The scheduler favors admitting
  prefills as slots free up — the same continuous-batching policy vLLM's
  scheduler applies.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .kv_cache import OutOfPages, PagedKVCache
from .sampling import SamplingParams, sample
from ..utils.tokenizer import load_tokenizer


@dataclasses.dataclass
class Request:
    prompt: str
    params: SamplingParams
    request_id: str = dataclasses.field(
        default_factory=lambda: f"req-{uuid.uuid4().hex[:12]}"
    )
    prompt_tokens: list[int] | None = None
    out_queue: queue.Queue = dataclasses.field(default_factory=queue.Queue)
    created: float = dataclasses.field(default_factory=time.monotonic)
    aborted: bool = False
    finish_reason: str | None = None  # set when the terminal marker arrives


@dataclasses.dataclass
class _Slot:
    request: Request | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    trie_pages: list[int] = dataclasses.field(default_factory=list)  # release()
    private_pages: list[int] = dataclasses.field(default_factory=list)  # free()
    position: int = 0  # position of the NEXT token to decode
    last_token: int = 0
    generated: list[int] = dataclasses.field(default_factory=list)
    emitted_text_len: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


@dataclasses.dataclass
class EngineStats:
    prompt_tokens: int = 0
    generated_tokens: int = 0
    steps: int = 0
    started: float = dataclasses.field(default_factory=time.monotonic)

    def tokens_per_second(self) -> float:
        dt = time.monotonic() - self.started
        return self.generated_tokens / dt if dt > 0 else 0.0


def _stop_safe_len(text: str, stop: tuple[str, ...]) -> int:
    """Longest prefix of ``text`` that cannot be the start of a pending stop
    match: anything past it must be withheld until the stop either completes
    (then truncated) or can no longer match (then flushed)."""
    safe = len(text)
    for stop_s in stop:
        lo = max(0, len(text) - len(stop_s) + 1)
        for start in range(lo, len(text)):
            if stop_s.startswith(text[start:]):
                safe = min(safe, start)
                break
    return safe


class _Finish:
    """Terminal stream marker carrying the OpenAI finish_reason."""

    __slots__ = ("reason",)

    def __init__(self, reason: str = "stop"):
        self.reason = reason


_FINISH = _Finish("stop")


class LLMEngine:
    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params=None,
        *,
        model_dir: str | None = None,
        max_slots: int = 16,
        page_size: int = 16,
        max_model_len: int = 1024,
        n_pages: int | None = None,
        prefill_buckets: tuple[int, ...] = (128, 256, 512, 1024, 2048),
        prefill_batch: int = 4,  # the one compiled prefill batch shape
        enable_prefix_cache: bool = True,
        quantization: str | None = None,  # "int8": weight-only quant serving
        seed: int = 0,
        kv_dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.tokenizer = load_tokenizer(model_dir)
        if params is None:
            if model_dir is not None:
                params = llama.load_hf_weights(model_dir, cfg)
            else:
                params = llama.init_params(jax.random.PRNGKey(seed), cfg)
        if quantization == "int8":
            from ..models.quantize import quantize_llama

            params = quantize_llama(params)
        elif quantization is not None:
            raise ValueError(f"unknown quantization {quantization!r}")
        self.params = params
        self.max_slots = max_slots
        self.max_model_len = max_model_len
        self.pages_per_slot = (max_model_len + page_size - 1) // page_size
        if n_pages is None:
            n_pages = 1 + max_slots * self.pages_per_slot
        self.cache = PagedKVCache.create(
            n_layers=cfg.n_layers,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
            n_pages=n_pages,
            page_size=page_size,
            dtype=kv_dtype,
        )
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_model_len
        ) or (max_model_len,)
        self.prefill_batch = max(1, min(prefill_batch, max_slots))
        from .prefix_cache import PrefixCache

        self.prefix_cache = (
            PrefixCache(self.cache.allocator, page_size)
            if enable_prefix_cache
            else None
        )

        self.slots = [_Slot() for _ in range(max_slots)]
        self.waiting: queue.Queue[Request] = queue.Queue()
        self.stats = EngineStats()
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None

        # host mirrors of device slot state
        self._page_tables = np.zeros((max_slots, self.pages_per_slot), np.int32)
        self._positions = np.zeros((max_slots,), np.int32)
        self._active = np.zeros((max_slots,), bool)
        self._tokens = np.zeros((max_slots,), np.int32)
        self._temps = np.ones((max_slots,), np.float32)
        self._top_ps = np.ones((max_slots,), np.float32)
        self._top_ks = np.zeros((max_slots,), np.int32)
        self._seeds = np.full((max_slots,), -1, np.int32)

        self._decode_jit = jax.jit(self._decode_and_sample, donate_argnums=(1, 2))
        self._prefill_jits: dict[int, object] = {}
        self._chunk_jits: dict[int, object] = {}  # keyed by chunk q_offset

    # -- jitted programs ----------------------------------------------------

    def _decode_and_sample(
        self, params, k_pages, v_pages, tokens, positions, page_tables, active,
        key, temps, top_ps, top_ks, seeds,
    ):
        logits, k_pages, v_pages = llama.decode_step(
            params, tokens, positions, k_pages, v_pages, page_tables, active,
            self.cfg,
        )
        next_tokens = sample(
            logits, key, temps, top_ps, top_ks, seeds=seeds, step_ids=positions
        )
        return next_tokens, k_pages, v_pages

    def _prefill_and_sample(
        self, params, k_pages, v_pages, tokens, page_tables, seq_lens, key,
        temps, top_ps, top_ks, seeds,
    ):
        logits, k_pages, v_pages = llama.prefill(
            params, tokens, k_pages, v_pages, page_tables, seq_lens, self.cfg
        )
        next_tokens = sample(
            logits, key, temps, top_ps, top_ks, seeds=seeds, step_ids=seq_lens
        )
        return next_tokens, k_pages, v_pages

    def _prefill_jit(self, bucket: int):
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_and_sample, donate_argnums=(1, 2))
            self._prefill_jits[bucket] = fn
        return fn

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- public API ---------------------------------------------------------

    def submit(self, prompt: str, params: SamplingParams | None = None) -> Request:
        req = Request(prompt=prompt, params=params or SamplingParams())
        # prompts longer than the largest bucket prefill in chunks; the hard
        # cap is the model length (minus >=1 decode slot)
        req.prompt_tokens = self.tokenizer.encode(prompt)[: self.max_model_len - 1]
        self.waiting.put(req)
        return req

    def generate(self, prompt: str, params: SamplingParams | None = None) -> str:
        """Blocking convenience: submit and collect the full completion."""
        req = self.submit(prompt, params)
        out = []
        for piece in self.stream(req):
            out.append(piece)
        return "".join(out)

    def stream(self, req: Request):
        """Yield text pieces as they decode (SSE-shaped; streaming.py:38-45)."""
        if not self._running:
            self.start()
        while True:
            item = req.out_queue.get()
            if isinstance(item, _Finish):
                req.finish_reason = item.reason
                return
            yield item

    def warmup(self, buckets: tuple[int, ...] | None = None) -> float:
        """Pre-compile the decode step and prefill buckets against trash
        pages (no allocator state touched) — the FAST_BOOT-style cold-start
        control (vllm_inference.py:85-101): pay compiles at boot, not on the
        first user request. Returns seconds spent."""
        if self._running:
            # the scheduler thread donates the same cache buffers; racing it
            # would pass deleted arrays. Warmup is a boot-time API.
            raise RuntimeError("call warmup() before start()")
        t0 = time.monotonic()
        for bucket in buckets or self.prefill_buckets:
            B = self.prefill_batch
            _tok, self.cache.k_pages, self.cache.v_pages = self._prefill_jit(
                (bucket, B)
            )(
                self.params,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.zeros((B, bucket), jnp.int32),
                jnp.zeros((B, self.pages_per_slot), jnp.int32),
                jnp.ones((B,), jnp.int32),
                self._next_key(),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), -1, jnp.int32),
            )
        _tok, self.cache.k_pages, self.cache.v_pages = self._decode_jit(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.zeros((self.max_slots,), jnp.int32),
            jnp.zeros((self.max_slots,), jnp.int32),
            jnp.zeros((self.max_slots, self.pages_per_slot), jnp.int32),
            jnp.zeros((self.max_slots,), bool),
            self._next_key(),
            jnp.ones((self.max_slots,), jnp.float32),
            jnp.ones((self.max_slots,), jnp.float32),
            jnp.zeros((self.max_slots,), jnp.int32),
            jnp.full((self.max_slots,), -1, jnp.int32),
        )
        jax.block_until_ready(self.cache.k_pages)
        return time.monotonic() - t0

    def abort(self, request: Request) -> None:
        """Cancel a request: waiting ones are dropped at admission; active
        ones finish at the next scheduler tick and free their slot/pages
        (the engine-abort surface vLLM exposes for client disconnects)."""
        request.aborted = True

    def start(self) -> "LLMEngine":
        with self._lock:
            if self._running:
                return self
            self._running = True
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the scheduler and release every caller: in-flight and queued
        requests get their terminal _FINISH so stream()/generate() return
        (partial output for in-flight ones) instead of blocking forever."""
        self._running = False
        if self._thread:
            self._thread.join(timeout=10)
        for slot in self.slots:
            if not slot.free:
                slot.request.out_queue.put(_FINISH)
                self._release_slot_pages(slot)
                slot.request = None
        while True:
            try:
                req = self.waiting.get_nowait()
            except queue.Empty:
                break
            req.out_queue.put(_FINISH)

    # -- scheduler loop ------------------------------------------------------

    def _loop(self) -> None:
        import traceback

        while self._running:
            try:
                worked = self.step()
            except Exception:
                # a poisoned request must not kill the serving loop
                traceback.print_exc()
                worked = False
            if not worked:
                time.sleep(0.002)

    def step(self) -> bool:
        """One scheduler tick: admit -> decode -> emit. Returns True if any
        work happened."""
        admitted = self._admit()
        decoded = self._decode_tick()
        return admitted or decoded

    def _admit(self) -> bool:
        """Claim slots+pages for waiting requests, then prefill each bucket's
        admissions as ONE batched jitted call (compile shapes: bucket x
        pow2-padded batch — continuous batching on the prefill side too)."""
        assignments: list[tuple[int, "Request", dict]] = []  # (slot, req, claim)
        while True:
            free_slot = next(
                (
                    i
                    for i, s in enumerate(self.slots)
                    if s.free and i not in {a[0] for a in assignments}
                ),
                None,
            )
            if free_slot is None or self.waiting.empty():
                break
            try:
                req = self.waiting.get_nowait()
            except queue.Empty:
                break
            if req.aborted:
                req.out_queue.put(_FINISH)
                continue
            claim = self._claim_pages(req)
            if claim is None:
                self.waiting.put(req)  # no KV room: wait for a completion
                break
            assignments.append((free_slot, req, claim))

        long_ones = [
            a for a in assignments if a[2]["n_prompt"] > self.prefill_buckets[-1]
        ]
        assignments = [a for a in assignments if a not in long_ones]
        for a in long_ones:
            try:
                self._prefill_long(*a)
            except Exception:
                # same contract as the grouped path: a failed chunked prefill
                # must not leave a half-initialized slot (next decode tick
                # would read uninitialized KV), leak its page claim, or poison
                # the prefix trie with partially-written pages
                import traceback

                traceback.print_exc()
                self._fail_claims([a])
        by_bucket: dict[int, list] = {}
        for a in assignments:
            by_bucket.setdefault(self._bucket_for(a[2]["n_prompt"]), []).append(a)
        for bucket, group in by_bucket.items():
            # chunk to the ONE compiled batch shape per bucket
            for i in range(0, len(group), self.prefill_batch):
                chunk = group[i : i + self.prefill_batch]
                try:
                    self._prefill_group(bucket, chunk)
                except Exception:
                    # a failed prefill must not leak claims, hang callers, or
                    # leave never-written KV pages in the prefix trie
                    import traceback

                    traceback.print_exc()
                    self._fail_claims(chunk)
        return bool(assignments)

    def _fail_claims(self, chunk: list) -> None:
        """Unwind failed prefill claims: invalidate trie pages, free privately
        owned pages, clear the slot, and release the caller."""
        for slot_idx, req, claim in chunk:
            if self.prefix_cache is not None:
                self.prefix_cache.invalidate(claim["trie_pages"])
            # trie pages another request still holds stay theirs;
            # free everything this claim exclusively owns
            owned = [
                p for p in claim["private_pages"]
            ] + [
                p for p in claim["trie_pages"]
                if self.prefix_cache is None
                or p not in self.prefix_cache._by_page
            ]
            self.cache.allocator.free(owned)
            slot = self.slots[slot_idx]
            slot.request = None
            slot.pages = slot.trie_pages = slot.private_pages = []
            self._active[slot_idx] = False
            req.out_queue.put(_Finish("error"))

    def _claim_pages(self, req: Request) -> dict | None:
        """Slot page claim with prefix-cache sharing + eviction pressure."""
        n_prompt = len(req.prompt_tokens)
        max_total = min(n_prompt + req.params.max_tokens, self.max_model_len)
        n_pages = self.cache.pages_for(max_total)
        pc = self.prefix_cache
        shared: list[int] = []
        if pc is not None:
            shared, _ = pc.acquire(req.prompt_tokens)
        need = n_pages - len(shared)
        try:
            fresh = self.cache.allocator.alloc(need)
        except OutOfPages:
            if pc is not None:
                pc.evict(need)  # reclaim zero-ref cached pages and retry
                try:
                    fresh = self.cache.allocator.alloc(need)
                except OutOfPages:
                    pc.release(shared)
                    return None
            else:
                return None
        pages = shared + fresh
        trie_pages, private_pages = list(shared), list(fresh)
        if pc is not None:
            pc.hits += bool(shared)
            pc.misses += not shared
            n_full = n_prompt // self.cache.page_size
            final, displaced = pc.insert(
                req.prompt_tokens, pages[:n_full], len(shared)
            )
            self.cache.allocator.free(displaced)
            trie_pages = list(final)
            private_pages = pages[n_full:]  # everything past the full-prompt
            pages = final + private_pages   # pages is trie-tracked
        return {
            "pages": pages,
            "trie_pages": trie_pages,
            "private_pages": private_pages,
            "n_prompt": n_prompt,
        }

    def _release_slot_pages(self, slot: _Slot) -> None:
        if self.prefix_cache is not None:
            self.prefix_cache.release(slot.trie_pages)
            self.cache.allocator.free(slot.private_pages)
        else:
            self.cache.allocator.free(slot.pages)
        slot.pages, slot.trie_pages, slot.private_pages = [], [], []

    def _prefill_long(self, slot_idx: int, req: Request, claim: dict) -> None:
        """Chunked prefill for prompts beyond the largest bucket: bucket-
        sized chunks attend to the cached prefix via the rectangular flash
        kernel (llama.prefill_chunk) — bounded VMEM at any prompt length."""
        import functools

        pages, n_prompt = claim["pages"], claim["n_prompt"]
        slot = self.slots[slot_idx]
        slot.request = req
        slot.pages = pages
        slot.trie_pages = claim["trie_pages"]
        slot.private_pages = claim["private_pages"]
        slot.generated = []
        slot.emitted_text_len = 0
        table = np.zeros((self.pages_per_slot,), np.int32)
        table[: len(pages)] = pages
        self._page_tables[slot_idx] = table

        C = self.prefill_buckets[-1]
        pad_tok = self.tokenizer.pad_id % self.cfg.vocab_size
        logits = None
        for offset in range(0, n_prompt, C):
            chunk = req.prompt_tokens[offset : offset + C]
            toks = np.full((1, C), pad_tok, np.int32)
            toks[0, : len(chunk)] = chunk
            fn = self._chunk_jits.get(offset)
            if fn is None:
                fn = jax.jit(
                    functools.partial(llama.prefill_chunk, q_offset=offset),
                    static_argnames=("cfg",),
                    donate_argnums=(2, 3),
                )
                self._chunk_jits[offset] = fn
            logits, self.cache.k_pages, self.cache.v_pages = fn(
                self.params,
                jnp.asarray(toks),
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(table[None, :]),
                jnp.asarray([len(chunk)], np.int32),
                cfg=self.cfg,
            )
        p = req.params
        first = sample(
            logits,
            self._next_key(),
            jnp.asarray([p.temperature], np.float32),
            jnp.asarray([p.top_p], np.float32),
            jnp.asarray([p.top_k], np.int32),
            seeds=jnp.asarray([-1 if p.seed is None else p.seed], np.int32),
            step_ids=jnp.asarray([n_prompt], np.int32),
        )
        self.stats.prompt_tokens += n_prompt
        slot.position = n_prompt
        slot.last_token = int(first[0])
        self._accept_token(slot_idx, slot.last_token)

    def _prefill_group(self, bucket: int, group: list) -> None:
        B = self.prefill_batch  # fixed compile shape; short groups pad
        pad_tok = self.tokenizer.pad_id % self.cfg.vocab_size
        tokens = np.full((B, bucket), pad_tok, np.int32)
        tables = np.zeros((B, self.pages_per_slot), np.int32)  # pad rows: trash
        seq_lens = np.ones((B,), np.int32)
        temps = np.ones((B,), np.float32)
        top_ps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        seeds = np.full((B,), -1, np.int32)
        for i, (slot_idx, req, claim) in enumerate(group):
            pages, n_prompt = claim["pages"], claim["n_prompt"]
            slot = self.slots[slot_idx]
            slot.request = req
            slot.pages = pages
            slot.trie_pages = claim["trie_pages"]
            slot.private_pages = claim["private_pages"]
            slot.generated = []
            slot.emitted_text_len = 0
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[: len(pages)] = pages
            self._page_tables[slot_idx] = table
            tokens[i, :n_prompt] = req.prompt_tokens
            tables[i] = table
            seq_lens[i] = n_prompt
            p = req.params
            temps[i], top_ps[i], top_ks[i] = p.temperature, p.top_p, p.top_k
            seeds[i] = -1 if p.seed is None else p.seed

        next_tok, self.cache.k_pages, self.cache.v_pages = self._prefill_jit(
            (bucket, B)
        )(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(tokens),
            jnp.asarray(tables),
            jnp.asarray(seq_lens),
            self._next_key(),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            jnp.asarray(seeds),
        )
        next_np = np.asarray(next_tok)
        for i, (slot_idx, req, claim) in enumerate(group):
            slot = self.slots[slot_idx]
            self.stats.prompt_tokens += claim["n_prompt"]
            slot.position = claim["n_prompt"]
            slot.last_token = int(next_np[i])
            self._accept_token(slot_idx, slot.last_token)

    def _decode_tick(self) -> bool:
        # reap aborted slots before spending a step on them
        for i, s in enumerate(self.slots):
            if not s.free and s.request.aborted:
                s.request.out_queue.put(_FINISH)
                self._release_slot_pages(s)
                s.request = None
                self._active[i] = False
        active_idx = [i for i, s in enumerate(self.slots) if not s.free]
        if not active_idx:
            return False
        self._active[:] = False
        for i in active_idx:
            s = self.slots[i]
            self._active[i] = True
            self._tokens[i] = s.last_token
            self._positions[i] = s.position
            p = s.request.params
            self._temps[i] = p.temperature
            self._top_ps[i] = p.top_p
            self._top_ks[i] = p.top_k
            self._seeds[i] = -1 if p.seed is None else p.seed

        next_tokens, self.cache.k_pages, self.cache.v_pages = self._decode_jit(
            self.params,
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(self._tokens),
            jnp.asarray(self._positions),
            jnp.asarray(self._page_tables),
            jnp.asarray(self._active),
            self._next_key(),
            jnp.asarray(self._temps),
            jnp.asarray(self._top_ps),
            jnp.asarray(self._top_ks),
            jnp.asarray(self._seeds),
        )
        next_np = np.asarray(next_tokens)
        self.stats.steps += 1
        for i in active_idx:
            s = self.slots[i]
            s.position += 1
            s.last_token = int(next_np[i])
            self._accept_token(i, s.last_token)
        return True

    def _accept_token(self, slot_idx: int, token: int) -> None:
        slot = self.slots[slot_idx]
        req = slot.request
        self.stats.generated_tokens += 1
        finished = False
        reason = None
        if token == self.tokenizer.eos_id:
            finished, reason = True, "stop"
        else:
            slot.generated.append(token)
            if len(slot.generated) >= req.params.max_tokens:
                finished, reason = True, "length"
            elif slot.position + 1 >= self.max_model_len:
                finished, reason = True, "length"

        # incremental detokenization: emit the stable new suffix
        text = self.tokenizer.decode(slot.generated)
        if req.params.stop:
            for stop_s in req.params.stop:
                idx = text.find(stop_s)
                if idx >= 0:
                    text = text[:idx]
                    finished, reason = True, "stop"
                    break
        # hold back any trailing text that is still a prefix of a stop string
        # (OpenAI/vLLM contract: stop='END' arriving as 'E','N','D' must not
        # leak 'EN' into the stream before the match completes)
        safe_len = (
            len(text)
            if finished
            else _stop_safe_len(text, req.params.stop)
        )
        new = text[slot.emitted_text_len : safe_len]
        if new and (finished or not new.endswith("�")):
            req.out_queue.put(new)
            slot.emitted_text_len = slot.emitted_text_len + len(new)
        if finished:
            req.out_queue.put(_Finish(reason))
            self._release_slot_pages(slot)
            slot.request = None
            self._active[slot_idx] = False


def build_engine(
    model: str = "llama2-7b",
    model_dir: str | None = None,
    **engine_kw,
) -> LLMEngine:
    """Factory mirroring the reference's MODEL_NAME/engine-flags surface
    (vllm_inference.py:54-58,168-209)."""
    presets = {
        "llama2-7b": llama.LlamaConfig.llama2_7b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
        "tiny": llama.LlamaConfig.tiny,
    }
    if model_dir is not None:
        cfg = llama.LlamaConfig.from_hf_config(f"{model_dir}/config.json")
    else:
        cfg = presets[model]()
    return LLMEngine(cfg, model_dir=model_dir, **engine_kw)
