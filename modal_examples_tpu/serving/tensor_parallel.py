"""Tensor-parallel serving: sharded decode over a device mesh.

The reference enables TP with engine flags (``--tensor-parallel-size``,
vllm_inference.py:179-180; ``--tp-size`` very_large_models.py:247) and lets
vLLM/SGLang drive NCCL. TPU-natively, TP serving is: params placed with the
model's Megatron-layout partition specs over the ``tensor`` ICI axis, a
dense KV cache sharded over the kv-head dimension, and ONE jitted decode
step — XLA inserts the all-reduces. No engine subprocess, no NCCL, no
per-rank code.

The dense cache ([L, B, Hkv, S, D], in-place dynamic-update-slice writes)
is the multi-chip counterpart of the single-chip paged cache: kv-head
sharding keeps every cache byte and its attention math on the chip that owns
the head. (Since round 7 the PAGED engine also keeps its Pallas kernels
under TP — shard_map'd over the same kv-head axis via ops.sharded, see
docs/tensor_parallel.md; this dense path remains the simple, fully
auto-partitioned alternative.)

``kv_dtype="int8"`` stores the dense cache quantized, exactly like the
paged cache: int8 ``[L, B, Hkv, S, D]`` data plus per-token-head f32
``[L, B, Hkv, S]`` scales (a :class:`~..ops.kv_quant.QuantizedKV` per
side), with the scales sharded over the SAME ``tensor``/kv-head axis as
their data so dequantization never crosses chips.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama, layers
from ..ops.kv_quant import (
    QuantizedKV,
    dequantize_kv,
    kv_empty,
    quantize_kv,
    shard_kv,
)


@dataclasses.dataclass
class DenseKVCache:
    k: object  # [L, B, Hkv, S, D] array, or QuantizedKV (int8 + scales)
    v: object
    _pytree = None

    @classmethod
    def create(
        cls, cfg: llama.LlamaConfig, batch: int, max_len: int, mesh=None,
        dtype=jnp.bfloat16, kv_dtype=None,
    ):
        shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
        k = kv_empty(shape, kv_dtype if kv_dtype is not None else dtype)
        v = kv_empty(shape, kv_dtype if kv_dtype is not None else dtype)
        if mesh is not None:
            data_sh = NamedSharding(mesh, P(None, None, "tensor", None, None))
            scale_sh = NamedSharding(mesh, P(None, None, "tensor", None))
            k = shard_kv(k, data_sh, scale_sh)
            v = shard_kv(v, data_sh, scale_sh)
        return cls(k, v)


jax.tree_util.register_dataclass(
    DenseKVCache, data_fields=("k", "v"), meta_fields=()
)


def shard_params_tp(params: dict, cfg: llama.LlamaConfig, mesh: Mesh) -> dict:
    """Place weights with the Megatron TP layout over the ``tensor`` axis."""
    specs = llama.partition_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def decode_step_dense(
    params: dict,
    tokens: jax.Array,  # [B] int32
    cache: DenseKVCache,
    positions: jax.Array,  # [B] int32
    cfg: llama.LlamaConfig,
):
    """One decode token against the dense cache; auto-partitioned under jit.

    Returns (logits [B, vocab], cache). Works on 1 chip or a tensor mesh —
    the partitioning comes entirely from the operands' shardings.
    """
    B = tokens.shape[0]
    S = cache.k.shape[3]
    x = params["embed"][tokens]  # [B, D]
    cos, sin = layers.rotary_embedding(
        positions[:, None], cfg.head_dim, cfg.rope_theta, dtype=jnp.float32
    )
    pos_mask = jnp.arange(S)[None, :] <= positions[:, None]  # [B, S]

    def layer_fn(carry, layer_with_cache):
        x = carry
        layer, k_c, v_c = layer_with_cache  # k_c: [B, Hkv, S, D]
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, 1, cfg.n_heads, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)

        # write this token's K/V at its position (scatter over batch);
        # int8 caches quantize at the write (per token-head amax/127) and
        # scatter the scale with its data
        b_idx = jnp.arange(B)
        if isinstance(k_c, QuantizedKV):
            qk, qv = quantize_kv(k[:, :, 0]), quantize_kv(v[:, :, 0])
            k_c = QuantizedKV(
                data=k_c.data.at[b_idx, :, positions].set(qk.data),
                scale=k_c.scale.at[b_idx, :, positions].set(qk.scale),
            )
            v_c = QuantizedKV(
                data=v_c.data.at[b_idx, :, positions].set(qv.data),
                scale=v_c.scale.at[b_idx, :, positions].set(qv.scale),
            )
            k_att = dequantize_kv(k_c, x.dtype)
            v_att = dequantize_kv(v_c, x.dtype)
        else:
            k_c = k_c.at[b_idx, :, positions].set(k[:, :, 0])
            v_c = v_c.at[b_idx, :, positions].set(v[:, :, 0])
            k_att, v_att = k_c, v_c

        # GQA attention over the cache, masked to live positions
        G = cfg.n_heads // cfg.n_kv_heads
        qg = q.reshape(B, cfg.n_kv_heads, G, D)
        s = jnp.einsum(
            "bhgd,bhsd->bhgs", qg.astype(jnp.float32),
            k_att.astype(jnp.float32),
        ) * (D**-0.5)
        s = jnp.where(pos_mask[:, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgs,bhsd->bhgd", p.astype(v_att.dtype), v_att)
        o = o.reshape(B, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h = layers.swiglu_mlp({n: layer[n] for n in ("gate", "up", "down")}, h)
        return x + h, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        layer_fn, x, (params["layers"], cache.k, cache.v)
    )
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)
    return logits, DenseKVCache(k_new, v_new)


def generate_tp(
    params: dict,
    cfg: llama.LlamaConfig,
    prompts: jax.Array,  # [B, S0] int32 (right-padded)
    prompt_lens: jax.Array,  # [B]
    max_new: int,
    *,
    mesh: Mesh | None = None,
    max_len: int = 256,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    kv_dtype=None,  # "int8": quantized dense cache (halved KV bytes)
) -> jax.Array:
    """Greedy/temperature generation with the dense TP cache: prefill token
    by token (simple, compile-once), then decode max_new tokens."""
    B, S0 = prompts.shape
    if mesh is not None:
        params = shard_params_tp(params, cfg, mesh)
    cache = DenseKVCache.create(
        cfg, B, max_len, mesh, dtype=params["embed"].dtype,
        kv_dtype=kv_dtype,
    )
    key = key if key is not None else jax.random.PRNGKey(0)

    out = jnp.zeros((B, S0 + max_new), jnp.int32)
    out = out.at[:, :S0].set(prompts)
    tokens = prompts[:, 0]
    last_logits = None
    for pos in range(S0 + max_new - 1):
        positions = jnp.full((B,), pos, jnp.int32)
        logits, cache = decode_step_dense(params, tokens, cache, positions, cfg)
        nxt_pos = pos + 1
        if temperature <= 0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature).astype(jnp.int32)
        in_prompt = nxt_pos < prompt_lens
        teacher = out[:, min(nxt_pos, S0 + max_new - 1)]
        tokens = jnp.where(in_prompt, teacher, nxt)
        out = out.at[:, nxt_pos].set(tokens)
    return out
