"""Tensor-parallel serving: sharded decode over a device mesh.

The reference enables TP with engine flags (``--tensor-parallel-size``,
vllm_inference.py:179-180; ``--tp-size`` very_large_models.py:247) and lets
vLLM/SGLang drive NCCL. TPU-natively, TP serving is: params placed with the
model's Megatron-layout partition specs over the ``tensor`` ICI axis, a
dense KV cache sharded over the kv-head dimension, and ONE jitted decode
step — XLA inserts the all-reduces. No engine subprocess, no NCCL, no
per-rank code.

The dense cache ([L, B, Hkv, S, D], in-place dynamic-update-slice writes)
is the multi-chip counterpart of the single-chip paged cache: kv-head
sharding keeps every cache byte and its attention math on the chip that owns
the head. (Paged attention stays the single-chip fast path; a TP paged
kernel via shard_map is a later-round item.)
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama, layers


@dataclasses.dataclass
class DenseKVCache:
    k: jax.Array  # [L, B, Hkv, S, D]
    v: jax.Array
    _pytree = None

    @classmethod
    def create(cls, cfg: llama.LlamaConfig, batch: int, max_len: int, mesh=None, dtype=jnp.bfloat16):
        shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        if mesh is not None:
            sh = NamedSharding(mesh, P(None, None, "tensor", None, None))
            k, v = jax.device_put(k, sh), jax.device_put(v, sh)
        return cls(k, v)


jax.tree_util.register_dataclass(
    DenseKVCache, data_fields=("k", "v"), meta_fields=()
)


def shard_params_tp(params: dict, cfg: llama.LlamaConfig, mesh: Mesh) -> dict:
    """Place weights with the Megatron TP layout over the ``tensor`` axis."""
    specs = llama.partition_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def decode_step_dense(
    params: dict,
    tokens: jax.Array,  # [B] int32
    cache: DenseKVCache,
    positions: jax.Array,  # [B] int32
    cfg: llama.LlamaConfig,
):
    """One decode token against the dense cache; auto-partitioned under jit.

    Returns (logits [B, vocab], cache). Works on 1 chip or a tensor mesh —
    the partitioning comes entirely from the operands' shardings.
    """
    B = tokens.shape[0]
    S = cache.k.shape[3]
    x = params["embed"][tokens]  # [B, D]
    cos, sin = layers.rotary_embedding(
        positions[:, None], cfg.head_dim, cfg.rope_theta, dtype=jnp.float32
    )
    pos_mask = jnp.arange(S)[None, :] <= positions[:, None]  # [B, S]

    def layer_fn(carry, layer_with_cache):
        x = carry
        layer, k_c, v_c = layer_with_cache  # k_c: [B, Hkv, S, D]
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, 1, cfg.n_heads, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)

        # write this token's K/V at its position (scatter over batch)
        b_idx = jnp.arange(B)
        k_c = k_c.at[b_idx, :, positions].set(k[:, :, 0])
        v_c = v_c.at[b_idx, :, positions].set(v[:, :, 0])

        # GQA attention over the cache, masked to live positions
        G = cfg.n_heads // cfg.n_kv_heads
        qg = q.reshape(B, cfg.n_kv_heads, G, D)
        s = jnp.einsum(
            "bhgd,bhsd->bhgs", qg.astype(jnp.float32), k_c.astype(jnp.float32)
        ) * (D**-0.5)
        s = jnp.where(pos_mask[:, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgs,bhsd->bhgd", p.astype(v_c.dtype), v_c)
        o = o.reshape(B, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h = layers.swiglu_mlp({n: layer[n] for n in ("gate", "up", "down")}, h)
        return x + h, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        layer_fn, x, (params["layers"], cache.k, cache.v)
    )
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)
    return logits, DenseKVCache(k_new, v_new)


def generate_tp(
    params: dict,
    cfg: llama.LlamaConfig,
    prompts: jax.Array,  # [B, S0] int32 (right-padded)
    prompt_lens: jax.Array,  # [B]
    max_new: int,
    *,
    mesh: Mesh | None = None,
    max_len: int = 256,
    key: jax.Array | None = None,
    temperature: float = 0.0,
) -> jax.Array:
    """Greedy/temperature generation with the dense TP cache: prefill token
    by token (simple, compile-once), then decode max_new tokens."""
    B, S0 = prompts.shape
    if mesh is not None:
        params = shard_params_tp(params, cfg, mesh)
    cache = DenseKVCache.create(cfg, B, max_len, mesh, dtype=params["embed"].dtype)
    key = key if key is not None else jax.random.PRNGKey(0)

    out = jnp.zeros((B, S0 + max_new), jnp.int32)
    out = out.at[:, :S0].set(prompts)
    tokens = prompts[:, 0]
    last_logits = None
    for pos in range(S0 + max_new - 1):
        positions = jnp.full((B,), pos, jnp.int32)
        logits, cache = decode_step_dense(params, tokens, cache, positions, cfg)
        nxt_pos = pos + 1
        if temperature <= 0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature).astype(jnp.int32)
        in_prompt = nxt_pos < prompt_lens
        teacher = out[:, min(nxt_pos, S0 + max_new - 1)]
        tokens = jnp.where(in_prompt, teacher, nxt)
        out = out.at[:, nxt_pos].set(tokens)
    return out
