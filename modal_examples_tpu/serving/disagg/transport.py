"""KV-page transport: the wire format for migrating paged-KV blocks.

A finished prefill is a set of physical pages in the prefill replica's
:class:`~..kv_cache.PagedKVCache` plus a little sampler state (position,
first token, seed). This module turns that into bytes and back:

- :func:`wire_leaves` enumerates every DEVICE LEAF of the cache pytree —
  2 for bf16 (``k_pages``/``v_pages``), 4 for int8 (``k_pages.data``/
  ``.scale`` and the v pair). It is built on ``jax.tree_util`` flattening,
  not a hand-kept list, so a future 5th leaf shows up here automatically —
  and a static guard (tests/test_static.py) asserts the codec's leaf set
  equals the pytree's, the int8-scales lesson from PR 5 made structural.
- :func:`extract_pages` slices ``n`` pages out of each leaf (the page axis
  is axis 1 on every leaf by layout) into host numpy — one
  :class:`PageBlock`.
- :func:`serialize_block` / :func:`deserialize_block` — a compact binary
  envelope: magic + JSON header (leaf specs, per-leaf crc32, block hashes,
  sampler meta) + raw leaf bytes. int8 blocks ship the int8 payload + f32
  scale rows exactly as stored, so adoption is BIT-EXACT: no re-quantization
  on either side, which is what makes disagg output token-identical to
  unified serving.
- :func:`iter_chunks` / :class:`ChunkAssembler` / :func:`transfer` — chunked
  streaming with per-chunk crc32 and resumable retry: a corrupt or dropped
  chunk is re-sent by sequence number, not the whole payload. Chunks are
  plain picklable tuples, so the same protocol rides the process executor's
  worker pipes or any in-process queue (:class:`LoopbackChannel`).
- :func:`adopt_pages` writes a received block into freshly allocated pages
  of the destination cache — the same ``.at[:, ids].set`` scatter shape the
  prefill page writes use, applied leaf-by-leaf through the pytree.

**Decode-state leg (docs/failover.md).** A live-migrated MID-DECODE request
ships through the same envelope with ``meta["resume"] = {"generated":
[...], "emitted_len": n}`` next to the first-token sampler state — the
accepted-token history and emitted-text cursor the target needs to adopt a
running stream. The extension is purely additive meta: the byte layout,
magic, and leaf framing are unchanged, so a plain PR-6 first-token block
still decodes and adopts everywhere (tests/test_static.py pins the compat
both ways), and a receiver that predates the leg simply ignores it.

See docs/disagg.md for the byte layout and the failure matrix.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import queue
import struct
import time
import zlib

import numpy as np

from ...core.retries import Retries
from ...faults import inject as _inject
from ...observability import metrics as _obs
from ...observability import reqtrace as _rt
from ..health import transfers as _transfer_watermarks

#: envelope magic + version (bump on any layout change)
_MAGIC = b"MTKV1\n"
#: default chunk payload size — small enough that one lost chunk is cheap
#: to resend, large enough that header overhead stays noise
DEFAULT_CHUNK_BYTES = 256 * 1024

#: default backoff between chunk-retry rounds: short (a retry round is a
#: loopback/pipe re-send, not a network RPC) and JITTERED per transfer id —
#: N replicas whose transfers all hit the same flaky channel must not
#: re-send in lockstep (docs/faults.md). ``max_rounds`` x these delays is
#: the transfer's bounded retry budget.
DEFAULT_RETRY_BACKOFF = Retries(
    max_retries=8, initial_delay=0.01, max_delay=0.25, jitter=0.5
)


class TransportError(RuntimeError):
    """Corrupt, incomplete, or incompatible wire data."""


class TransferAborted(TransportError):
    """The transfer's ``should_abort`` tripped mid-stream (client abort or
    deadline while chunks were in flight)."""


# -- cache pytree <-> named leaves -------------------------------------------


def _leaf_name(path) -> str:
    """Stable dotted name for a pytree path, e.g. ``k_pages.data``."""
    parts = []
    for key in path:
        name = getattr(key, "name", None)
        if name is None:
            name = getattr(key, "key", None)
        if name is None:
            name = getattr(key, "idx", None)
        parts.append(str(name))
    return ".".join(parts)


def wire_leaves(cache) -> list:
    """``[(name, device_array)]`` for every device leaf of the cache pytree,
    in flatten order. Built on tree flattening so the codec can never trail
    the cache structure: a new leaf added to :class:`PagedKVCache` (or to
    ``QuantizedKV``) appears here without this module changing."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(cache)[0]
    return [(_leaf_name(path), leaf) for path, leaf in flat]


@dataclasses.dataclass
class PageBlock:
    """``n`` cache pages worth of every leaf, on the host.

    ``leaves[name]`` has the page axis (axis 1) sliced down to the block's
    pages, in page-table order. ``block_hashes`` are the chained
    content hashes of the full prompt pages these pages hold (prefix-cache
    key material — the tiered cache is keyed by them); ``meta`` carries the
    sampler state the decode side needs to continue (position, first token,
    prompt token ids, seed)."""

    leaves: dict
    page_size: int
    kv_dtype: str
    block_hashes: list = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def n_pages(self) -> int:
        first = next(iter(self.leaves.values()))
        return int(first.shape[1])

    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.leaves.values())


def extract_pages(cache, page_ids: list, *, block_hashes=None, meta=None) -> PageBlock:
    """Copy ``page_ids`` (device -> host) out of every cache leaf.

    Every leaf's page axis is axis 1 (``[L, P, page_size, Hkv, ...]`` for
    data, ``[L, P, page_size, Hkv]`` for int8 scale rows), so one gather
    expression covers all present and future leaves."""
    ids = np.asarray(list(page_ids), np.int32)
    leaves = {}
    for name, leaf in wire_leaves(cache):
        if leaf.shape[1] != cache.n_pages:
            raise TransportError(
                f"cache leaf {name!r} does not have the page axis at axis 1 "
                f"(shape {leaf.shape}); the wire codec needs updating"
            )
        leaves[name] = np.asarray(leaf[:, ids])
    return PageBlock(
        leaves=leaves,
        page_size=cache.page_size,
        kv_dtype=cache.kv_dtype,
        block_hashes=list(block_hashes or []),
        meta=dict(meta or {}),
    )


_adopt_scatter = None  # built lazily: jitted donated per-leaf page scatter


def _adopt_scatter_jit():
    """One jitted ``leaf.at[:, ids].set(data)`` with the LEAF DONATED, so
    adoption updates the cache buffer in place instead of allocating a
    second full-size copy per leaf — at HBM-sized caches an un-donated
    scatter would transiently double KV residency per migration. jax.jit
    caches compiled variants per leaf shape/dtype, so one callable serves
    every leaf of both cache forms."""
    global _adopt_scatter
    if _adopt_scatter is None:
        import jax

        _adopt_scatter = jax.jit(
            lambda leaf, ids, data: leaf.at[:, ids].set(data),
            donate_argnums=(0,),
        )
    return _adopt_scatter


def adopt_pages(cache, block: PageBlock, page_ids: list) -> None:
    """Write ``block`` into ``page_ids`` of the destination cache, leaf by
    leaf (the receive-side mirror of :func:`extract_pages`), through a
    donated jitted scatter so the cache is updated in place.

    MUST run on the thread that owns the cache's jit lifecycle (the decode
    engine's scheduler thread): the engine donates these arrays through its
    decode program, and racing that donation would write deleted buffers.
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    if block.kv_dtype != cache.kv_dtype:
        raise TransportError(
            f"kv_dtype mismatch: block is {block.kv_dtype}, destination "
            f"cache is {cache.kv_dtype} — replicas must serve one cache dtype"
        )
    if block.page_size != cache.page_size:
        raise TransportError(
            f"page_size mismatch: block {block.page_size} vs cache "
            f"{cache.page_size}"
        )
    if len(page_ids) != block.n_pages:
        raise TransportError(
            f"adopting {block.n_pages} pages into {len(page_ids)} page ids"
        )
    names = [name for name, _ in wire_leaves(cache)]
    if set(names) != set(block.leaves):
        raise TransportError(
            f"leaf set mismatch: wire {sorted(block.leaves)} vs cache "
            f"{sorted(names)}"
        )
    ids = jnp.asarray(np.asarray(list(page_ids), np.int32))
    flat, treedef = jax.tree_util.tree_flatten(cache)
    named = wire_leaves(cache)
    scatter = _adopt_scatter_jit()
    new_leaves = []
    for (name, leaf), current in zip(named, flat):
        data = block.leaves[name]
        new_leaves.append(scatter(current, ids, jnp.asarray(data)))
    adopted = jax.tree_util.tree_unflatten(treedef, new_leaves)
    # write back EVERY field generically (meta fields unflatten to the same
    # objects): a future data_field leaf must land here without this module
    # changing, or it would ship over the wire and be silently dropped at
    # adoption — the static guard round-trips through this function
    for field in _dc.fields(cache):
        setattr(cache, field.name, getattr(adopted, field.name))


# -- block (de)serialization -------------------------------------------------


def serialize_block(block: PageBlock) -> bytes:
    """Envelope: ``MTKV1\\n`` + u32 header length + JSON header + raw leaf
    bytes in header order. Each leaf records dtype/shape/crc32 so a flipped
    byte is a loud :class:`TransportError`, never silent KV corruption."""
    specs = []
    payload = bytearray()
    for name in sorted(block.leaves):
        arr = np.ascontiguousarray(block.leaves[name])
        buf = arr.tobytes()
        specs.append(
            {
                "name": name,
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "crc32": zlib.crc32(buf) & 0xFFFFFFFF,
                "nbytes": len(buf),
            }
        )
        payload += buf
    header = json.dumps(
        {
            "version": 1,
            "page_size": block.page_size,
            "kv_dtype": block.kv_dtype,
            "block_hashes": list(block.block_hashes),
            "meta": block.meta,
            "leaves": specs,
        }
    ).encode()
    return _MAGIC + struct.pack("<I", len(header)) + header + bytes(payload)


def deserialize_block(data: bytes) -> PageBlock:
    if data[: len(_MAGIC)] != _MAGIC:
        raise TransportError("bad magic: not a KV page block")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    try:
        header = json.loads(data[off : off + hlen])
    except (ValueError, UnicodeDecodeError) as e:
        raise TransportError(f"corrupt block header: {e}") from e
    off += hlen
    leaves = {}
    for spec in header["leaves"]:
        buf = data[off : off + spec["nbytes"]]
        if len(buf) != spec["nbytes"]:
            raise TransportError(
                f"truncated block: leaf {spec['name']!r} short by "
                f"{spec['nbytes'] - len(buf)} bytes"
            )
        if (zlib.crc32(buf) & 0xFFFFFFFF) != spec["crc32"]:
            raise TransportError(f"crc mismatch on leaf {spec['name']!r}")
        leaves[spec["name"]] = np.frombuffer(
            buf, dtype=np.dtype(spec["dtype"])
        ).reshape(spec["shape"])
        off += spec["nbytes"]
    return PageBlock(
        leaves=leaves,
        page_size=int(header["page_size"]),
        kv_dtype=str(header["kv_dtype"]),
        block_hashes=list(header["block_hashes"]),
        meta=dict(header["meta"]),
    )


# -- prefix block hashing ----------------------------------------------------


def chain_hashes(tokens: list, page_size: int) -> list:
    """Chained content hash per FULL page of ``tokens``: ``h_i =
    sha256(h_{i-1} || page_i tokens)``. Position-dependent by construction,
    so the same 16 tokens at different prompt depths never collide — the
    tiered prefix cache's key, and the trie's page identity on the wire."""
    out = []
    prev = b""
    n_full = len(tokens) // page_size
    for i in range(n_full):
        page = tokens[i * page_size : (i + 1) * page_size]
        h = hashlib.sha256(
            prev + b"," + b",".join(str(int(t)).encode() for t in page)
        ).digest()
        out.append(h.hex())
        prev = h
    return out


# -- chunked streaming with resumable retry ----------------------------------


def iter_chunks(
    payload: bytes, transfer_id: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES
) -> list:
    """Split ``payload`` into picklable chunk tuples
    ``("kv_chunk", transfer_id, seq, total, crc32, bytes)``."""
    chunk_bytes = max(1, int(chunk_bytes))
    total = max(1, -(-len(payload) // chunk_bytes))
    out = []
    for seq in range(total):
        piece = payload[seq * chunk_bytes : (seq + 1) * chunk_bytes]
        out.append(
            (
                "kv_chunk",
                transfer_id,
                seq,
                total,
                zlib.crc32(piece) & 0xFFFFFFFF,
                piece,
            )
        )
    return out


class ChunkAssembler:
    """Receive side: collect chunks, detect gaps/corruption, reassemble.

    ``add`` drops corrupt chunks (crc mismatch) and records them as missing
    so the sender's next round re-sends exactly those — resumable retry at
    chunk granularity."""

    def __init__(self, transfer_id: str):
        self.transfer_id = transfer_id
        self.total: int | None = None
        self._chunks: dict[int, bytes] = {}
        self.corrupt = 0

    def add(self, chunk) -> bool:
        """Returns True when the chunk was accepted (valid + ours)."""
        kind, tid, seq, total, crc, piece = chunk
        if kind != "kv_chunk" or tid != self.transfer_id:
            return False
        if self.total is None:
            self.total = int(total)
        if (zlib.crc32(piece) & 0xFFFFFFFF) != crc:
            self.corrupt += 1
            return False
        self._chunks[int(seq)] = piece
        return True

    @property
    def complete(self) -> bool:
        return self.total is not None and len(self._chunks) == self.total

    def missing(self) -> list:
        if self.total is None:
            return []
        return [s for s in range(self.total) if s not in self._chunks]

    def payload(self) -> bytes:
        if not self.complete:
            raise TransportError(
                f"transfer {self.transfer_id}: missing chunks {self.missing()}"
            )
        return b"".join(self._chunks[s] for s in range(self.total))


class LoopbackChannel:
    """In-process chunk channel (the inline-executor shape): ``send``
    enqueues, ``recv`` drains. The seam where a cross-process pipe sits in
    the process executor — and where tests inject corruption, drops, and
    replica death."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue()

    def send(self, chunk) -> None:
        self._q.put(chunk)

    def recv(self, block: bool = False, timeout: float | None = None):
        return self._q.get(block=block, timeout=timeout)


def _mangle(chunk):
    """A corrupted copy of ``chunk``: payload bytes flipped, crc left
    STALE — exactly the wire damage the assembler must catch."""
    kind, tid, seq, total, crc, piece = chunk
    bad = piece[:-1] + bytes([piece[-1] ^ 0xFF]) if piece else piece
    return (kind, tid, seq, total, crc, bad)


def transfer(
    payload: bytes,
    channel,
    *,
    transfer_id: str,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    max_rounds: int = 3,
    should_abort=None,
    backoff: Retries | None = DEFAULT_RETRY_BACKOFF,
) -> bytes:
    """Stream ``payload`` through ``channel`` and reassemble it: send every
    pending chunk, drain what arrived, re-send only the gaps. Raises
    :class:`TransferAborted` the moment ``should_abort()`` trips (checked
    between chunks, so an abort never waits for the tail of a large block)
    and :class:`TransportError` when ``max_rounds`` can't complete the set.

    Retry rounds wait ``backoff.delay_for_attempt(round, key=transfer_id)``
    between attempts — jittered so concurrent transfers over one flaky
    channel don't re-send in lockstep; ``max_rounds`` x those delays bounds
    the retry budget. ``backoff=None`` retries immediately (tests).

    Fault points (docs/faults.md): ``disagg.replica_death`` kills the
    stream mid-transfer, ``disagg.chunk_drop`` swallows one chunk,
    ``disagg.chunk_corrupt`` flips payload bytes under a stale crc, and
    ``disagg.transfer_stall`` holds the sender between chunks WITHOUT an
    error — the gray failure only the progress watchdog can see.

    Progress watermarks (serving/health.py, docs/health.md): the transfer
    registers in the process-wide :data:`~..health.transfers` registry and
    advances its sequence watermark per chunk sent; the fleet watchdog
    aborts a transfer whose watermark goes stale, which surfaces HERE as a
    :class:`TransportError` between chunks — the coordinator's unified
    fallback then re-prefills on the decode side, so a silently stalled
    wire never hangs a request to its deadline.
    """
    chunks = iter_chunks(payload, transfer_id, chunk_bytes)
    asm = ChunkAssembler(transfer_id)
    pending = list(range(len(chunks)))
    _transfer_watermarks.begin(transfer_id)
    try:
        for round_i in range(max(1, int(max_rounds))):
            if round_i and pending:
                _obs.record_disagg_chunk_retries(len(pending))
                if backoff is not None:
                    delay = backoff.delay_for_attempt(round_i, key=transfer_id)
                    # retry backoff as a span event on the ambient request
                    # (the coordinator scopes the migration's trace frame
                    # around this call — docs/observability.md)
                    _rt.ambient_event(
                        "retry_wait", round=round_i, pending=len(pending),
                        delay_s=round(delay, 6),
                    )
                    time.sleep(delay)
            for seq in pending:
                if should_abort is not None and should_abort():
                    raise TransferAborted(f"transfer {transfer_id} aborted")
                if _transfer_watermarks.abort_requested(transfer_id):
                    raise TransportError(
                        f"transfer {transfer_id}: aborted by the progress "
                        "watchdog (stalled between chunks)"
                    )
                _inject.check(
                    "disagg.replica_death",
                    ConnectionError,
                    f"injected: peer died mid-transfer {transfer_id}",
                )
                if _inject.fire("disagg.transfer_stall"):
                    # gray failure: the sender goes quiet between chunks —
                    # no exception, no closed channel, the peer just never
                    # sees the next seq. Only an abort (the watchdog's
                    # stalled-watermark ladder, or the caller's own
                    # abort/deadline) lifts the stall.
                    while not _transfer_watermarks.abort_requested(
                        transfer_id
                    ) and not (should_abort is not None and should_abort()):
                        time.sleep(0.005)
                    if should_abort is not None and should_abort():
                        raise TransferAborted(
                            f"transfer {transfer_id} aborted"
                        )
                    raise TransportError(
                        f"transfer {transfer_id}: aborted by the progress "
                        "watchdog (stalled between chunks)"
                    )
                if _inject.fire("disagg.chunk_drop"):
                    continue  # the chunk vanishes; the next round re-sends it
                chunk = chunks[seq]
                if _inject.fire("disagg.chunk_corrupt"):
                    chunk = _mangle(chunk)
                # per-chunk span (child of the ambient transfer span): a dead
                # channel mid-send still closes it with status=error
                sp = _rt.begin_ambient(
                    "chunk", seq=seq, nbytes=len(chunk[5]), round=round_i
                )
                try:
                    channel.send(chunk)
                except BaseException:
                    _rt.finish_ambient(sp, status="error")
                    raise
                _rt.finish_ambient(sp)
                _transfer_watermarks.progress(transfer_id, seq)
            while True:
                try:
                    received = channel.recv(block=False)
                except queue.Empty:
                    break
                asm.add(received)
            if asm.complete:
                return asm.payload()
            pending = asm.missing()
        raise TransportError(
            f"transfer {transfer_id}: {len(asm.missing())} chunks still "
            f"missing after {max_rounds} rounds ({asm.corrupt} corrupt)"
        )
    finally:
        _transfer_watermarks.end(transfer_id)
