"""Tiered prefix cache: HBM -> host RAM -> ``Volume`` spill and promote.

The trie (:class:`~..prefix_cache.PrefixCache`) keeps shared prompt-prefix
KV in HBM until allocator pressure evicts it — and evicted meant GONE: the
next request over the same system prompt re-pays the memory (and, once
compute-skip lands, the compute). This tier stack catches evictions
instead:

- **host tier** — evicted prefix pages are serialized (the SAME
  page-(de)serialization machinery the disagg wire uses:
  :func:`~.transport.extract_pages` + :func:`~.transport.serialize_block`,
  checksums included) into a bounded host-RAM LRU. Quantized (int8) pages
  spill at ~half the bf16 bytes, so the same budget holds ~2x the blocks.
- **volume tier** — host-LRU overflow demotes to the fleet-wide
  :class:`~..prefix_store.SharedPrefixStore` (content-addressed block
  files on a :class:`~...storage.volume.Volume`), so warm prefixes survive
  replica churn AND cross replicas: a fresh replica promotes a system
  prompt some OTHER replica computed instead of recomputing it. With
  ``shared=True`` the store adds rendezvous spill ownership + dedup
  (docs/prefix_store.md); without it, the store runs as this replica's
  private single-writer tier — same layout, same atomic write discipline.

Keys are CHAINED content hashes (:func:`~.transport.chain_hashes`): block i
hashes its page's tokens together with block i-1's hash, so a page's
identity encodes its whole prefix — the same 16 tokens at two different
prompt depths never alias.

Promotion happens inside the engine's claim path: after the trie's
longest-prefix hit, consecutive lower-tier blocks are allocated a fresh
page, their bytes adopted (bit-exact for int8, value-exact for bf16), and
the page joins the trie as a normal insert. Correctness never depends on
promotion: prefill recomputes and rewrites identical values over promoted
pages (deterministic quantization included — docs/kv_cache.md), exactly as
it does for trie-shared pages.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from ...faults import inject as _inject
from ...observability import metrics as _obs
from ...observability import reqtrace as _rt
from ...utils.log import get_logger
from .transport import (
    PageBlock,
    TransportError,
    adopt_pages,
    chain_hashes,
    deserialize_block,
    extract_pages,
    serialize_block,
)

_log = get_logger("tiered_cache")

#: default host-RAM budget for spilled blocks (MTPU_TIER_HOST_BYTES env)
DEFAULT_HOST_BYTES = 64 * 1024 * 1024


class TieredPrefixCache:
    """Spill/promote tiers below one engine's prefix trie.

    Wired by the engine (``tiered_prefix=`` kwarg): ``prefix_cache.spill``
    points at :meth:`spill_pages`, and the claim path calls
    :meth:`register` (after trie insert) and :meth:`promote` (after trie
    acquire). All entry points run on the cache-owning thread — the same
    thread discipline the decode jits already impose — so device reads and
    writes here never race a donated buffer.
    """

    def __init__(
        self,
        cache,
        prefix_cache,
        *,
        host_bytes: int | None = None,
        volume=None,
        volume_prefix: str = "kv-tier",
        store=None,
        replica: str | None = None,
        shared: bool = False,
    ):
        self.cache = cache
        self.prefix_cache = prefix_cache
        if host_bytes is None:
            try:
                host_bytes = int(
                    os.environ.get("MTPU_TIER_HOST_BYTES", "")
                    or DEFAULT_HOST_BYTES
                )
            except ValueError:
                host_bytes = DEFAULT_HOST_BYTES
        self.host_bytes_budget = int(host_bytes)
        # the volume tier IS a prefix store (docs/prefix_store.md): pass a
        # SharedPrefixStore directly (the fleet-shared tier), or a Volume
        # (a store is built over it — shared=True joins the fleet-wide
        # store, default is this replica's private single-writer tier)
        if store is not None:
            self.store = store
        elif volume is not None:
            from ..prefix_store import SharedPrefixStore

            self.store = SharedPrefixStore(
                volume,
                replica=replica or f"replica-{os.getpid()}",
                root=volume_prefix,
                shared=shared,
            )
        else:
            self.store = None
        self.volume = self.store.volume if self.store is not None else None
        self._lock = threading.Lock()
        #: trie-resident page id -> chained block hash (spill key material)
        self._by_page: dict[int, str] = {}
        #: block hash -> its chain's HEAD hash (spill-ownership key: the
        #: store assigns whole chains, not single blocks, to owners).
        #: Bounded LRU — demotes can happen long after register
        self._chain_of: OrderedDict[str, str] = OrderedDict()
        #: host tier: hash -> serialized single-block bytes, LRU order
        self._host: OrderedDict[str, bytes] = OrderedDict()
        self._host_used = 0
        self.tier_hits = {"host": 0, "volume": 0}
        self.spilled = 0
        self.promoted = 0

    #: bound on the block -> chain-head map (LRU; ~64 bytes/entry of hex)
    CHAIN_MAP_CAP = 65536

    # -- bookkeeping ---------------------------------------------------------

    def _emit_gauges_locked(self) -> None:
        _obs.set_tier_occupancy(
            "host", pages=len(self._host), total_bytes=self._host_used
        )
        if self.store is not None:
            _obs.set_tier_occupancy(
                "volume",
                pages=self.store.n_blocks,
                total_bytes=self.store.total_bytes,
            )

    def register(self, key_tokens: list, trie_pages: list) -> None:
        """Record the chained hash of every trie-resident full-prompt page
        (called after ``PrefixCache.insert``), so a later eviction knows
        what content each physical page holds — and pin the chain in the
        shared store (this replica's refcount: GC keeps blocks any live
        replica may still promote)."""
        hashes = chain_hashes(key_tokens, self.cache.page_size)
        if not hashes:
            return
        with self._lock:
            for pid, h in zip(trie_pages, hashes):
                self._by_page[pid] = h
            for h in hashes:
                self._chain_of.pop(h, None)
                self._chain_of[h] = hashes[0]
            while len(self._chain_of) > self.CHAIN_MAP_CAP:
                self._chain_of.popitem(last=False)
        if self.store is not None:
            self.store.pin(hashes)

    # -- spill (HBM -> host -> volume) ---------------------------------------

    def spill_pages(self, page_ids: list) -> None:
        """Serialize evicted trie pages into the host tier before their HBM
        pages return to the allocator (the ``PrefixCache.spill`` hook).
        Unregistered pages (never inserted through a claim this tier saw)
        are skipped."""
        with self._lock:
            work = [
                (pid, self._by_page.pop(pid))
                for pid in page_ids
                if pid in self._by_page
            ]
        work = [
            (pid, h) for pid, h in work
            if self._lookup_host(h, touch=False) is None  # already spilled
        ]
        if not work:
            return
        # ONE device->host transfer for the whole eviction wave (this runs
        # on the allocator-pressure path): per-page blocks are sliced out
        # of the batched copy on the host
        batch = extract_pages(self.cache, [pid for pid, _ in work])
        for i, (_pid, block_hash) in enumerate(work):
            block = PageBlock(
                leaves={
                    name: arr[:, i : i + 1] for name, arr in batch.leaves.items()
                },
                page_size=batch.page_size,
                kv_dtype=batch.kv_dtype,
            )
            self._host_put(block_hash, serialize_block(block))
            self.spilled += 1
        with self._lock:
            self._emit_gauges_locked()

    def _host_put(self, block_hash: str, data: bytes) -> None:
        with self._lock:
            if block_hash in self._host:
                return
            self._host[block_hash] = data
            self._host_used += len(data)
            # bounded LRU: overflow demotes oldest blocks to the volume
            # tier (or drops them when no volume is configured)
            demote: list[tuple[str, bytes]] = []
            while self._host_used > self.host_bytes_budget and len(self._host) > 1:
                old_hash, old_data = self._host.popitem(last=False)
                self._host_used -= len(old_data)
                demote.append((old_hash, old_data))
        for old_hash, old_data in demote:
            self._demote_to_volume(old_hash, old_data)

    def _demote_to_volume(self, block_hash: str, data: bytes) -> None:
        if self.store is None:
            return
        with self._lock:
            chain = self._chain_of.get(block_hash)
        try:
            self.store.put(block_hash, data, chain=chain)
        except Exception as e:
            # includes the injected owner-death crash: the spill is simply
            # lost here (atomic writes: no torn block lands), and either a
            # surviving replica's spill or a later recompute rewrites it
            _log.warning("volume demote of %s failed: %s", block_hash, e)

    # -- promote (volume -> host -> HBM) -------------------------------------

    def _lookup_host(self, block_hash: str, *, touch: bool = True):
        with self._lock:
            data = self._host.get(block_hash)
            if data is not None and touch:
                self._host.move_to_end(block_hash)
            return data

    def _lookup_volume(self, block_hash: str):
        if self.store is None:
            return None
        data = self.store.get(block_hash)
        if data is None:
            return None
        # fault point (docs/faults.md): the volume's bytes rot IN FLIGHT —
        # promote's crc check drops the block and prefill recomputes it;
        # the stored file is untouched (store.drop_if_corrupt proves that
        # before ever deleting), so a later promote can still succeed
        return _inject.corrupt("tiered.volume_corrupt", data)

    def promote(self, key_tokens: list, *, n_have: int) -> list:
        """Restore consecutive full-prompt pages past the trie's
        ``n_have``-page hit from the lower tiers. Each hit allocates one
        fresh page, adopts the stored bytes into it, and returns it — the
        engine's claim inserts these into the trie like freshly prefilled
        pages (refcount 1), so the block is shared again from here on.
        Stops at the first miss, corrupt block, or allocator exhaustion."""
        hashes = chain_hashes(key_tokens, self.cache.page_size)
        out: list[int] = []
        by_tier = {"host": 0, "volume": 0}
        for block_hash in hashes[n_have:]:
            tier = "host"
            data = self._lookup_host(block_hash)
            if data is None:
                tier = "volume"
                data = self._lookup_volume(block_hash)
            if data is None:
                break
            try:
                block = deserialize_block(data)
            except TransportError as e:
                _log.warning(
                    "dropping corrupt tier block %s: %s", block_hash, e
                )
                with self._lock:
                    stale = self._host.pop(block_hash, None)
                    if stale is not None:
                        self._host_used -= len(stale)
                if tier == "volume" and self.store is not None:
                    # torn/rotten ON DISK -> removed so the recompute's
                    # spill rewrites it; corrupted in flight -> kept
                    self.store.drop_if_corrupt(block_hash)
                break
            if block.kv_dtype != self.cache.kv_dtype:
                break  # cache was rebuilt at a different dtype: stale tier
            n_pages = block.n_pages
            try:
                pages = self.cache.allocator.alloc(n_pages)
            except Exception:
                break  # no room to promote into; callers alloc what's left
            adopt_pages(self.cache, block, pages)
            out.extend(pages)
            # PAGE units, like every other tier counter (a block is one
            # page today, but hit-rate/dedup math must not silently break
            # the day multi-page blocks ship over the same codec)
            self.tier_hits[tier] += n_pages
            by_tier[tier] += n_pages
            _obs.record_tier_hit(tier, n=n_pages)
            if tier == "volume":
                # promote the bytes up a tier too: next hit is RAM-speed
                self._host_put(block_hash, data)
        if out:
            self.promoted += len(out)
            # the claim path scopes the request's ambient trace frame
            # around promotion: the restore shows up on its timeline
            for tier, n in by_tier.items():
                if n:
                    _rt.ambient_event("tier_promote", tier=tier, pages=n)
            with self._lock:
                self._emit_gauges_locked()
        return out

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {
                "host": {
                    "blocks": len(self._host),
                    "bytes": self._host_used,
                    "budget_bytes": self.host_bytes_budget,
                },
                "volume": {
                    "blocks": (
                        self.store.n_blocks if self.store is not None else 0
                    ),
                    "bytes": (
                        self.store.total_bytes
                        if self.store is not None else 0
                    ),
                    "enabled": self.store is not None,
                },
                "hits": dict(self.tier_hits),
                "spilled": self.spilled,
                "promoted": self.promoted,
                "registered_pages": len(self._by_page),
            }
        if self.store is not None:
            out["store"] = self.store.stats()
        return out
