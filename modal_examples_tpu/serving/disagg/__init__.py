"""Disaggregated prefill/decode serving: KV-page transport, role-aware
replicas, and a tiered prefix cache.

The serving-systems papers' architecture gap (PAPERS.md, arxiv 2511.17593):
one engine with a queue couples the two phases of an LLM request that want
opposite hardware shapes — prefill is compute-bound and bursty, decode is
bandwidth-bound and steady. Disaggregation runs them on *different
replicas*: a prefill replica computes the prompt's KV, ships the finished
pages to a decode replica (the Ragged Paged Attention paper's page is the
unit of transfer), and frees them; the decode replica adopts the pages into
its own :class:`~..kv_cache.PagedKVCache` and continues, so neither phase
ever steals the other's step time.

Three modules, each usable alone:

- :mod:`.transport` — the wire half: every device leaf of the paged cache
  (2 for bf16, 4 for int8: data pages + scale rows) extracted per page,
  serialized with checksums, chunked, and reassembled with resumable
  retry. int8 pages ship at half the bytes — PR 5's residency win is also
  the wire win.
- :mod:`.roles` — the control half: :class:`DisaggCoordinator` pairs
  prefill replicas with decode targets (placement via the role-aware
  :class:`~...scheduling.router.PrefixAffinityRouter`), reserves the
  migration's pages in the decode replica's admission controller before a
  byte moves, and falls back to unified serving when no peer exists or a
  transfer dies mid-request.
- :mod:`.tiered_cache` — the memory half: prefix blocks spill
  HBM -> host RAM -> ``Volume`` on eviction and promote back on demand,
  riding the same page (de)serialization machinery, so warm prefixes
  survive replica churn.

See docs/disagg.md for the wire format, the role lifecycle, and the
failure matrix.
"""

from .roles import DisaggCoordinator
from .tiered_cache import TieredPrefixCache
from .transport import (
    ChunkAssembler,
    PageBlock,
    TransferAborted,
    TransportError,
    adopt_pages,
    deserialize_block,
    extract_pages,
    iter_chunks,
    serialize_block,
    wire_leaves,
)

__all__ = [
    "ChunkAssembler",
    "DisaggCoordinator",
    "PageBlock",
    "TransferAborted",
    "TieredPrefixCache",
    "TransportError",
    "adopt_pages",
    "deserialize_block",
    "extract_pages",
    "iter_chunks",
    "serialize_block",
    "wire_leaves",
]
