"""Role-aware replica coordination: the control plane of disaggregated
prefill/decode serving.

:class:`DisaggCoordinator` fronts a fleet of
:class:`~...scheduling.router.EngineReplica` objects carrying roles
(``prefill`` / ``decode`` / ``unified``) and drives one request through the
migration pipeline:

1. **plan** — the role-aware router picks a prefill replica by prefix-block
   affinity and its paired decode target;
2. **reserve** — the migration's full KV-page cost is admitted on the
   DECODE replica before any byte moves (a shed here is an honest 429, not
   a half-migrated request);
3. **prefill** — the prefill replica runs a slot-free
   :meth:`~..engine.LLMEngine.prefill_sync` (its engine never starts a
   decode loop), the finished pages are extracted and freed — trie pages
   stay cached, so the prefill replica's prefix cache keeps getting warmer;
4. **transfer** — the serialized block streams in checksummed chunks with
   resumable retry, abortable between chunks (client abort or deadline);
5. **adopt** — the decode engine adopts the block on ITS scheduler thread
   at admission and continues decoding from the migrated position.

Every failure mode lands in one of two states (docs/disagg.md's failure
matrix): the request completes via **unified fallback** (re-prefill on the
decode-capable side), or it terminates with an honest finish_reason
(``deadline`` / client abort) — with page claims and admission reservations
released on BOTH replicas either way.
"""

from __future__ import annotations

import threading
import time

from ...faults import inject as _inject
from ...observability import metrics as _obs
from ...observability import reqtrace as _rt
from ...scheduling.admission import ShedError
from ...scheduling.policy import DEFAULT_CLASS, ScheduledRequest
from ...utils.log import get_logger
from .transport import (
    DEFAULT_CHUNK_BYTES,
    LoopbackChannel,
    TransferAborted,
    deserialize_block,
    serialize_block,
    transfer,
)

_log = get_logger("disagg")


class Migration:
    """One in-flight page migration (observability / test handle)."""

    __slots__ = ("request", "source", "target", "started_at")

    def __init__(self, request, source: str, target: str):
        self.request = request
        self.source = source
        self.target = target
        self.started_at = time.monotonic()


def _finish_marker(reason: str):
    """The engine's own terminal stream marker class, so a request that
    dies mid-migration (before ever reaching an engine queue) terminates
    its caller's ``stream()`` exactly like an engine-finished one."""
    from ..engine import _Finish

    return _Finish(reason)


class DisaggCoordinator:
    """Front a role-tagged replica fleet with prefill/decode migration.

    Duck-type compatible with :class:`~...scheduling.router.
    PrefixAffinityRouter` where the OpenAI server cares (``replicas`` /
    ``submit`` / ``stream`` / ``abort`` / ``replica_for`` / ``stats``), plus
    ``serving_engines()`` so servers only ever start decode-capable
    engines — a prefill replica's scheduler loop must never run.

    ``channel_factory`` builds the chunk channel per migration (default:
    in-process :class:`LoopbackChannel`; tests inject corrupt/dying
    channels; a cross-process deployment hands the executor's worker
    pipe endpoints here).
    """

    def __init__(
        self,
        replicas: list,
        *,
        prefix_tokens: int = 16,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        max_rounds: int = 3,
        channel_factory=None,
        reprobe_s: float | None = None,  # router unhealthy re-probe interval
        trace_store=None,  # where gateway-side migration spans land
    ):
        from ...scheduling.router import PrefixAffinityRouter

        self.replicas = list(replicas)
        self.router = PrefixAffinityRouter(
            replicas, prefix_tokens=prefix_tokens, reprobe_s=reprobe_s
        )
        self.chunk_bytes = int(chunk_bytes)
        self.max_rounds = int(max_rounds)
        self._channel_factory = channel_factory or LoopbackChannel
        self._trace_store = (
            trace_store if trace_store is not None else _rt.default_store
        )
        if trace_store is not None:
            _rt.register_store(self._trace_store)
        self._lock = threading.Lock()
        self._inflight: dict[str, Migration] = {}
        self.migrations_ok = 0
        self.migrations_fallback = 0
        self.migrations_aborted = 0
        self.pages_migrated = 0
        self.bytes_migrated = 0
        # one model, one cache geometry: peers must agree on the page unit
        # and dtype or adopted blocks would be garbage
        shapes = {
            (r.engine.cache.page_size, r.engine.cache.kv_dtype)
            for r in self.replicas
        }
        if len(shapes) > 1:
            raise ValueError(
                f"replicas disagree on (page_size, kv_dtype): {sorted(shapes)}"
                " — disagg peers must share the cache geometry"
            )
        for r in self.replicas:
            _obs.set_replica_role(r.name, getattr(r, "role", "unified"))

    # -- submission ----------------------------------------------------------

    def submit(
        self,
        prompt: str,
        params=None,
        image=None,
        *,
        priority: str = DEFAULT_CLASS,
        tenant: str = "default",
        trace=_rt.UNSET,
    ):
        """Place one request: disaggregated when a healthy prefill/decode
        pair exists, unified otherwise. Multimodal requests always serve
        unified (image KV does not take the migration path). Raises
        ``ShedError`` when the owning replica's admission rejects it."""
        # the fleet entry point mints the request's distributed trace (the
        # trace id becomes the request id; an upstream None = sampled out
        # and passes through); the disagg plan is a `placement` span, the
        # migration pipeline below opens migrate/transfer/chunk spans, and
        # the prefill/decode replicas parent their own spans under it
        ctx = _rt.resolve_entry_trace(trace, "gateway", store=self._trace_store)
        if image is not None:
            return self.router.submit(
                prompt, params, image=image, priority=priority,
                tenant=tenant, trace=ctx,
            )
        t0_place = time.time()
        with _rt.active(ctx, replica="gateway"):
            prefill_r, decode_r = self.router.plan(prompt)
        _rt.record_span(
            ctx, "placement", start=t0_place, store=self._trace_store,
            replica="gateway",
            prefill_replica=prefill_r.name if prefill_r else "-",
            decode_replica=decode_r.name,
        )
        if prefill_r is None:
            req = decode_r.submit(
                prompt, params, priority=priority, tenant=tenant, trace=ctx
            )
            req._router_replica = decode_r
            return req
        return self._submit_disagg(
            prompt, params, prefill_r, decode_r,
            priority=priority, tenant=tenant, trace=ctx,
        )

    def _submit_disagg(
        self, prompt, params, prefill_r, decode_r, *, priority, tenant,
        trace=None,
    ):
        engine_d = decode_r.engine
        req = engine_d.make_request(
            prompt, params, priority=priority, tenant=tenant, trace=trace
        )
        req._router_replica = decode_r
        ctx = req.trace
        # fault point (docs/faults.md): the decode side sheds the migration
        # reservation — an honest 429 BEFORE any byte moves, the same
        # surface a real kv_pressure shed takes (nothing to unwind: no
        # reservation exists yet, the request never queued anywhere)
        if _inject.fire("disagg.reserve_shed"):
            _obs.record_shed(req.priority, "injected")
            _rt.event(
                ctx, "shed", store=self._trace_store, replica="gateway",
                reason="injected",
            )
            _rt.finish_root(
                ctx, "shed", store=self._trace_store, finish_reason="shed"
            )
            raise ShedError(
                "injected", 1.0,
                f"injected: decode replica {decode_r.name} shed the "
                f"migration reservation for {req.request_id}",
            )
        # migration cost reserved on the DECODE side before any byte moves:
        # the admission controller counts these pages exactly like queued
        # local work, so a decode replica can't be over-committed by
        # migrations it never saw coming
        entry = ScheduledRequest(
            payload=req,
            priority=req.priority,
            tenant=req.tenant,
            cost=engine_d.request_cost(req),
            deadline=req.deadline,
            enqueued_at=engine_d._clock(),
        )
        occ = engine_d.cache.occupancy()
        try:
            engine_d.admission.admit(  # ShedError propagates: honest 429
                entry,
                depths=engine_d.policy.depths(),
                pages_used=occ["pages_used"],
                pages_total=occ["pages_total"],
            )
        except ShedError as e:
            # ONLY real sheds close the trace as "shed" (anything else
            # here is a bug reaching the client as a 500 — the trace must
            # not claim an admission decision that never happened)
            _rt.event(
                ctx, "shed", store=self._trace_store, replica="gateway",
                reason=e.reason,
            )
            _rt.finish_root(
                ctx, "shed", store=self._trace_store, finish_reason="shed"
            )
            raise
        migration = Migration(req, prefill_r.name, decode_r.name)
        with self._lock:
            self._inflight[req.request_id] = migration
            _obs.set_migrations_inflight(len(self._inflight))
        t0 = time.monotonic()
        # the migrate span: prefill + transfer + adopt nest under it —
        # prefill-replica spans parent through req._trace_parent, and the
        # wire context in the block meta carries the same parent across
        # the hop. The ambient frame attaches injected transport faults
        # (chunk corrupt/drop, replica death) to THIS request.
        mig_sp = _rt.begin(
            ctx, "migrate", replica="gateway",
            source=prefill_r.name, target=decode_r.name,
        )
        req._trace_parent = mig_sp.span_id if mig_sp is not None else None
        tr_sp = None
        try:
            with _rt.active(ctx, parent=req._trace_parent, replica="gateway"):
                block, payload = self._prefill_and_pack(prefill_r, req)

                def should_abort() -> bool:
                    if req.aborted:
                        return True
                    if (
                        req.deadline is not None
                        and engine_d._clock() >= req.deadline
                    ):
                        req.deadline_expired = True
                        return True
                    return False

                tr_sp = _rt.begin(
                    ctx, "transfer", parent=req._trace_parent,
                    replica="gateway", wire_bytes=len(payload),
                )
                with _rt.active(
                    ctx,
                    parent=tr_sp.span_id if tr_sp is not None else None,
                    replica="gateway",
                ):
                    wire = transfer(
                        payload,
                        self._channel_factory(),
                        transfer_id=req.request_id,
                        chunk_bytes=self.chunk_bytes,
                        max_rounds=self.max_rounds,
                        should_abort=should_abort,
                    )
                _rt.finish(
                    ctx, tr_sp, store=self._trace_store,
                    chunks=-(-len(payload) // max(1, self.chunk_bytes)),
                )
                if should_abort():
                    raise TransferAborted(req.request_id)
                # fault point: the reassembled block corrupts between wire
                # and adoption (bad DMA, bit rot) — deserialize_block's crc
                # check turns it into a loud TransportError -> unified
                # fallback below
                wire = _inject.corrupt("disagg.adopt_corrupt", wire)
                engine_d.submit_adopted(req, entry, deserialize_block(wire))
            with self._lock:
                self.migrations_ok += 1
                self.pages_migrated += block.n_pages
                self.bytes_migrated += len(payload)
            _obs.record_migration(
                "ok", pages=block.n_pages, wire_bytes=len(payload)
            )
            _rt.finish(
                ctx, mig_sp, store=self._trace_store, result="ok",
                pages=block.n_pages, wire_bytes=len(payload),
            )
            return req
        except TransferAborted:
            engine_d.admission.release(entry)
            with self._lock:
                self.migrations_aborted += 1
            _obs.record_migration("aborted")
            if req.deadline_expired:
                _obs.record_deadline_miss("migrating")
            reason = "deadline" if req.deadline_expired else "stop"
            _rt.finish(ctx, tr_sp, status="aborted", store=self._trace_store)
            _rt.finish(
                ctx, mig_sp, status="aborted", store=self._trace_store,
                result="aborted",
            )
            _rt.finish_request(req, reason, store=self._trace_store)
            req.out_queue.put(_finish_marker(reason))
            return req
        except Exception as e:
            # replica death, wire corruption beyond retry, OutOfPages on the
            # prefill side: unified fallback — the decode-capable replica
            # re-prefills the request from scratch. Reservations/claims are
            # already unwound (prefill_sync releases its claim on failure;
            # the decode reservation releases here).
            engine_d.admission.release(entry)
            with self._lock:
                self.migrations_fallback += 1
            _obs.record_migration("fallback")
            _rt.finish(ctx, tr_sp, status="error", store=self._trace_store)
            _rt.finish(
                ctx, mig_sp, status="error", store=self._trace_store,
                result="fallback",
            )
            if req.aborted:
                _rt.finish_request(req, "stop", store=self._trace_store)
                req.out_queue.put(_finish_marker("stop"))
                return req
            _log.warning(
                "migration %s (%s -> %s) failed (%s: %s); unified re-prefill "
                "on %s",
                req.request_id, prefill_r.name, decode_r.name,
                type(e).__name__, e, decode_r.name,
            )
            req._trace_parent = None  # fallback spans parent at the root
            return engine_d.submit_request(req)  # ShedError propagates
        finally:
            with self._lock:
                self._inflight.pop(req.request_id, None)
                _obs.set_migrations_inflight(len(self._inflight))
            _obs.record_migration_seconds(time.monotonic() - t0)

    def _prefill_and_pack(self, prefill_r, req):
        """Prefill on the source replica, extract the wire block, and free
        the source pages (trie pages stay cached: the prefill replica's
        prefix cache survives the request)."""
        engine_p = prefill_r.engine
        state = engine_p.prefill_sync(req)
        try:
            block = engine_p.extract_request_pages(req, state)
        finally:
            engine_p.release_claim(state["claim"], valid=True)
        return block, serialize_block(block)

    # -- request lifecycle ---------------------------------------------------

    def replica_for(self, req):
        replica = getattr(req, "_router_replica", None)
        if replica is None:
            raise KeyError(f"request {req.request_id} not routed here")
        return replica

    def failover_target(self, exclude: str | None = None):
        """Resume target for a failed in-flight request: delegate to the
        role-aware router (healthy decode-capable replicas only)."""
        return self.router.failover_target(exclude=exclude)

    def stream(self, req):
        """Stream with in-flight failover (serving/failover.py): a decode
        replica dying mid-stream is checkpoint-resumed on a healthy peer
        — the client stream continues token-identically, no visible
        error (docs/failover.md)."""
        from ..failover import stream_with_failover

        yield from stream_with_failover(self, req)

    def migrate_live(self, req, target_name: str | None = None) -> str:
        """Coordinator-planned rebalancing: proactively move one in-flight
        request off its current replica — KV pages and decode state ride
        the same chunked MTKV1 wire the prefill migration uses, and the
        target adopts mid-decode. Returns the
        :func:`~..failover.migrate_request` result string."""
        from ..failover import migrate_request

        source = self.replica_for(req)
        if target_name is not None:
            target = next(
                (r for r in self.replicas if r.name == target_name), None
            )
            if target is None or not target.serves_requests:
                raise KeyError(
                    f"no decode-capable replica named {target_name!r}"
                )
        else:
            target = self.failover_target(exclude=source.name)
            if target is None or target.name == source.name:
                return "gone"  # nowhere better to move it
        return migrate_request(
            source, target, req,
            chunk_bytes=self.chunk_bytes, max_rounds=self.max_rounds,
            channel_factory=self._channel_factory,
        )

    def abort(self, req) -> None:
        """Abort a request wherever it is: still migrating (the transfer
        loop trips between chunks), queued, or decoding."""
        req.aborted = True
        self.replica_for(req).abort(req)

    def migrations(self) -> list:
        """Snapshot of in-flight migrations (observability/tests)."""
        with self._lock:
            return list(self._inflight.values())

    def serving_engines(self) -> list:
        """Engines whose scheduler loop may run: decode-capable replicas
        only. A prefill replica's engine must NEVER be started — its cache
        buffers are owned by the synchronous prefill path."""
        return [r.engine for r in self.replicas if r.serves_requests]

    def stats(self) -> dict:
        with self._lock:
            mig = {
                "ok": self.migrations_ok,
                "fallback": self.migrations_fallback,
                "aborted": self.migrations_aborted,
                "inflight": len(self._inflight),
                "pages": self.pages_migrated,
                "bytes": self.bytes_migrated,
            }
        out = {"migrations": mig, "router": self.router.stats()}
        # fleet view of the shared prefix store (docs/prefix_store.md):
        # one row per replica running a tier, so an operator sees dedup
        # and cross-replica hit attribution side by side
        stores = {}
        for r in self.replicas:
            tiered = getattr(r.engine, "tiered", None)
            if tiered is not None and getattr(tiered, "store", None) is not None:
                stores[r.name] = tiered.store.stats()
        if stores:
            out["prefix_store"] = stores
        return out
