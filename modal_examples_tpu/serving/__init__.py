"""Serving: continuous-batching engine, paged KV cache, sampling, OpenAI API.

The TPU-native replacement for the vLLM/SGLang/TRT-LLM engines every
llm-serving example in the reference shells out to (SURVEY.md §2.2).
"""

from . import disagg, speculative, tensor_parallel
from .engine import LLMEngine, Request, build_engine
from .kv_cache import OutOfPages, PagedKVCache, PageAllocator
from .openai_api import OpenAIServer
from .sampling import SamplingParams, sample

__all__ = [
    "LLMEngine",
    "disagg",
    "OpenAIServer",
    "OutOfPages",
    "PageAllocator",
    "PagedKVCache",
    "Request",
    "SamplingParams",
    "build_engine",
    "sample",
    "speculative",
    "tensor_parallel",
]
