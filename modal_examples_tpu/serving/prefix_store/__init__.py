"""Fleet-wide shared prefix store (docs/prefix_store.md).

One content-addressed, deduplicated KV block store per fleet instead of
one private Volume tier per replica: blocks keyed by chained page hashes,
written once fleet-wide under rendezvous ownership, promotable by any
replica through the MTKV1 wire codec, refcount-GC'd across replicas.
"""

from .ownership import LeaseBoard, rendezvous_owner
from .store import DEFAULT_ROOT, SharedPrefixStore, block_file

__all__ = [
    "DEFAULT_ROOT",
    "LeaseBoard",
    "SharedPrefixStore",
    "block_file",
    "rendezvous_owner",
]
