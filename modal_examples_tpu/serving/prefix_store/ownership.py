"""Ownership + lease layer of the shared prefix store (docs/prefix_store.md).

Every prefix chain (identified by its HEAD chain hash — the first full
page's chained content hash, which pins the whole chain's identity) has one
**owner** replica at any moment: the rendezvous winner
(:func:`~...scheduling.router.rendezvous_score`, the SAME hash the router
places requests with) over the replicas currently registered against the
store. The owner is the replica responsible for spilling that chain's
blocks to the shared Volume — N replicas serving the same tenant
population produce one copy, not N racing writers.

Ownership must survive owner death, so it is backed by two kinds of small
JSON files on the shared volume:

- ``replicas/<name>.json`` — a membership heartbeat. A replica registers at
  boot (``SnapshotWarmFactory`` scale-outs included), refreshes on demand,
  and deregisters on scale-in/quarantine/crash handling. A heartbeat older
  than ``replica_ttl_s`` means the replica is dead for ownership purposes —
  rendezvous simply stops seeing it and its chains remap.
- ``leases/<chain>.json`` — the spill lease the owner holds while writing a
  chain. Acquiring a lease held by a DEAD or EXPIRED owner is a
  **takeover**: counted (``mtpu_prefix_store_owner_takeovers_total``) and
  journaled (``prefix_store.jsonl``), because it is the event the chaos
  ``prefix-store-owner-death`` episode must prove.

All files are written through :class:`~...storage.volume.Volume`'s atomic
write path (fsync + rename), so a torn lease or heartbeat can never be
observed — a crash mid-write leaves the previous value.
"""

from __future__ import annotations

import json
import time

from ...observability import metrics as _obs
from ...observability.journal import named_journal
from ...scheduling.router import rendezvous_score
from ...utils.log import get_logger

_log = get_logger("prefix_store")

#: sub-directories of the store root these files live under (the store's
#: blocks/ sibling); path strings are built HERE and in store.py only —
#: tests/test_static.py bans construction anywhere else in the package
REPLICAS_DIR = "replicas"
LEASES_DIR = "leases"

#: a heartbeat older than this is a dead replica (ownership remaps)
DEFAULT_REPLICA_TTL_S = 60.0
#: a spill lease auto-expires after this long (a wedged owner cannot
#: block a chain's spills forever)
DEFAULT_LEASE_TTL_S = 60.0


def rendezvous_owner(chain: str, names) -> str | None:
    """The rendezvous winner for ``chain`` among replica ``names`` — the
    router's placement hash reused for spill ownership, so the replica a
    shared prefix routes to is (membership permitting) also the replica
    that owns spilling it."""
    names = list(names)
    if not names:
        return None
    key = chain.encode()
    return max(names, key=lambda n: rendezvous_score(key, n))


class LeaseBoard:
    """Membership + per-chain spill leases over one shared volume root.

    One instance per (replica, store); instances on different replicas
    coordinate purely through the volume files, the same way the replicas
    coordinate block contents through the content-addressed block files.
    """

    def __init__(
        self,
        volume,
        root: str,
        replica: str,
        *,
        lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
        replica_ttl_s: float = DEFAULT_REPLICA_TTL_S,
        clock=time.time,
    ):
        self.volume = volume
        self.root = root.strip("/")
        self.replica = replica
        self.lease_ttl_s = float(lease_ttl_s)
        self.replica_ttl_s = float(replica_ttl_s)
        self._clock = clock
        self._journal = named_journal("prefix_store")
        self.takeovers = 0

    # -- paths (the only place these strings are built) ----------------------

    def _replica_path(self, name: str) -> str:
        return f"{self.root}/{REPLICAS_DIR}/{name}.json"

    def _lease_path(self, chain: str) -> str:
        return f"{self.root}/{LEASES_DIR}/{chain}.json"

    def _read_json(self, path: str) -> dict | None:
        try:
            return json.loads(self.volume.read_file(path).decode())
        except (OSError, ValueError):
            return None

    # -- membership ----------------------------------------------------------

    def register(self, *, boot: str | None = None) -> None:
        """Join (or refresh) this replica's membership heartbeat."""
        rec = {"at": self._clock()}
        if boot is not None:
            rec["boot"] = boot
        self.volume.write_file(
            self._replica_path(self.replica), json.dumps(rec).encode()
        )

    heartbeat = register

    def deregister(self) -> None:
        """Leave the membership: this replica's chains remap immediately
        (scale-in, watchdog quarantine, or the owner-death fault path)."""
        try:
            self.volume.remove_file(self._replica_path(self.replica))
        except OSError:
            pass

    def alive_replicas(self) -> list[str]:
        """Members with a fresh heartbeat, sorted (deterministic owner
        math). A stale heartbeat is a crashed replica: not an error, just
        no longer an owner candidate."""
        now = self._clock()
        out = []
        try:
            entries = list(self.volume.listdir(f"{self.root}/{REPLICAS_DIR}"))
        except OSError:
            return []
        for entry in entries:
            base = str(entry).rsplit("/", 1)[-1]
            if not base.endswith(".json"):
                continue
            rec = self._read_json(str(entry))
            if rec is None:
                continue
            if now - float(rec.get("at", 0.0)) <= self.replica_ttl_s:
                out.append(base[: -len(".json")])
        return sorted(out)

    def owner_for(self, chain: str, candidates=None) -> str | None:
        """The chain's current owner: rendezvous over the live membership
        (or an explicit candidate list). ``None`` with no live members —
        callers then spill solo rather than drop the block."""
        return rendezvous_owner(
            chain,
            candidates if candidates is not None else self.alive_replicas(),
        )

    # -- leases --------------------------------------------------------------

    def acquire(self, chain: str) -> bool:
        """Take (or refresh) the spill lease on ``chain``.

        Refused only while a DIFFERENT, LIVE owner holds an unexpired
        lease. Acquiring over a dead or expired owner is a takeover:
        counted and journaled, then the lease is rewritten to this
        replica."""
        now = self._clock()
        rec = self._read_json(self._lease_path(chain))
        if rec is not None and rec.get("owner") != self.replica:
            owner_alive = rec.get("owner") in self.alive_replicas()
            if owner_alive and float(rec.get("expires", 0.0)) > now:
                return False
            self.takeovers += 1
            _obs.record_prefix_store_takeover()
            self._journal.record({
                "at": time.time(),
                "action": "owner_takeover",
                "chain": chain,
                "from": rec.get("owner"),
                "to": self.replica,
                "reason": "owner_dead" if not owner_alive else "lease_expired",
            })
            _log.warning(
                "prefix store lease takeover on chain %s: %s -> %s",
                chain[:12], rec.get("owner"), self.replica,
            )
        self.volume.write_file(
            self._lease_path(chain),
            json.dumps({
                "owner": self.replica,
                "expires": now + self.lease_ttl_s,
                "seq": int(rec.get("seq", 0)) + 1 if rec else 1,
            }).encode(),
        )
        return True

    def release(self, chain: str) -> None:
        """Drop this replica's lease on ``chain`` (no-op on another
        owner's lease — releasing what you don't hold must not steal)."""
        rec = self._read_json(self._lease_path(chain))
        if rec is not None and rec.get("owner") == self.replica:
            try:
                self.volume.remove_file(self._lease_path(chain))
            except OSError:
                pass

    def lease_of(self, chain: str) -> dict | None:
        return self._read_json(self._lease_path(chain))

    def n_leases(self) -> int:
        try:
            return sum(
                1 for e in self.volume.listdir(f"{self.root}/{LEASES_DIR}")
                if str(e).endswith(".json")
            )
        except OSError:
            return 0
