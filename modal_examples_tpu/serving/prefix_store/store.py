"""Fleet-wide shared prefix block store: the deduplicated cross-replica
Volume tier (docs/prefix_store.md).

The tiered prefix cache (docs/disagg.md) used to give every replica a
PRIVATE Volume directory: a warm prefix on replica A was a cold recompute
on replica B, and autoscaler scale-outs booted with weights warm (snapshot
restore) but prefix caches empty — exactly when capacity was added because
load spiked. This store makes the Volume tier ONE fleet-wide,
content-addressed block store instead:

- **dedup by content address** — blocks keyed by the existing
  :func:`~..disagg.transport.chain_hashes` position-dependent identity and
  stored once under ``blocks/block-<hash>.kv``; a second writer of the
  same chain finds the block present and skips the write.
- **rendezvous ownership** (:mod:`.ownership`) — each chain has one owner
  replica responsible for spilling it; non-owners defer instead of racing,
  and owner death remaps the chain with a journaled lease takeover.
- **any replica promotes any replica's spills** — blocks are MTKV1 wire
  envelopes (:func:`~..disagg.transport.serialize_block`), crc per leaf,
  so the reader that deserializes them gets bit-exact int8 / value-exact
  bf16 pages no matter who wrote them.
- **torn/corrupt blocks are dropped, never adopted** — writes are atomic
  (uuid temp + fsync + rename, :meth:`~...storage.volume.Volume.write_file`),
  reads are structurally checked against the MTKV1 header's declared
  sizes, and a block whose STORED bytes fail the full crc check is
  removed so the next recompute's spill rewrites it.
- **bounded GC** — LRU by last-hit (block file mtime, refreshed on every
  hit) with cross-replica refcounts: a block pinned by ANY live replica's
  ``refs/<replica>.json`` survives; sweeps remove at most ``max_remove``
  blocks (the sweep runs on serving boxes, not a compactor fleet).

Series: ``mtpu_prefix_store_hits_total{origin=self|peer}`` /
``mtpu_prefix_store_misses_total`` / ``mtpu_prefix_store_dedup_ratio`` /
``mtpu_prefix_store_bytes`` / ``mtpu_prefix_store_owner_takeovers_total``
(observability/catalog.py). Surfaces: ``tpurun prefixstore`` and the
gateway's ``/prefixstore``.

LAYERING: this module is the ONLY writer of the store's Volume directory —
``tests/test_static.py`` bans block-path construction anywhere else in the
package, so the layout can evolve without call-site archaeology.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time

from ...faults import inject as _inject
from ...observability import metrics as _obs
from ...observability.journal import named_journal
from ...utils.log import get_logger
from ..disagg.transport import _MAGIC, TransportError, deserialize_block
from .ownership import LeaseBoard

_log = get_logger("prefix_store")

#: store layout under the root: content-addressed blocks and per-replica
#: refcount manifests (membership/leases live in :mod:`.ownership`)
BLOCKS_DIR = "blocks"
REFS_DIR = "refs"

#: default store root on the shared volume
DEFAULT_ROOT = "prefix-store"

#: per-replica pin cap: the refs manifest is a refcount, not an archive —
#: the oldest pins age out once a replica references more than this many
#: blocks (GC may then collect them if no other replica pins them either)
PIN_CAP = 8192


def block_file(block_hash: str) -> str:
    """Root-relative path of a content-addressed block — THE one place
    the block layout is spelled (tests/test_static.py enforces it)."""
    return f"{BLOCKS_DIR}/block-{block_hash}.kv"


def _structurally_sound(data: bytes) -> bool:
    """Cheap torn-block check: the MTKV1 magic plus the header's declared
    leaf sizes must account for EXACTLY the file's length. Catches
    truncation (a non-atomic writer's torn spill) without paying the full
    per-leaf crc — that runs at deserialize time in the promote path."""
    if data[: len(_MAGIC)] != _MAGIC:
        return False
    off = len(_MAGIC)
    if len(data) < off + 4:
        return False
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    try:
        header = json.loads(data[off : off + hlen])
    except (ValueError, UnicodeDecodeError):
        return False
    off += hlen
    try:
        total = sum(int(spec["nbytes"]) for spec in header["leaves"])
    except (KeyError, TypeError, ValueError):
        return False
    return len(data) == off + total


class SharedPrefixStore:
    """One replica's handle on the fleet-shared prefix block store.

    Instances on different replicas coordinate purely through the shared
    volume's files: content-addressed blocks, membership heartbeats,
    leases, and refcount manifests. ``shared=False`` degrades to a
    single-writer private tier (no membership, no leases — every chain is
    self-owned), which is how a solo engine's Volume tier runs.
    """

    def __init__(
        self,
        volume,
        *,
        replica: str = "replica-0",
        root: str = DEFAULT_ROOT,
        shared: bool = True,
        lease_ttl_s: float | None = None,
        replica_ttl_s: float | None = None,
        clock=time.time,
    ):
        self.volume = volume
        self.root = root.strip("/")
        self.replica = replica
        self.shared = bool(shared)
        self._clock = clock
        board_kw = {}
        if lease_ttl_s is not None:
            board_kw["lease_ttl_s"] = lease_ttl_s
        if replica_ttl_s is not None:
            board_kw["replica_ttl_s"] = replica_ttl_s
        self.board = LeaseBoard(
            volume, self.root, replica, clock=clock, **board_kw
        )
        self._lock = threading.Lock()
        #: block hash -> stored size (this process's view of the index,
        #: seeded from the directory, grown on put/get)
        self._index: dict[str, int] = {}
        #: hashes found in the LEGACY flat ``<root>/block-<h>.kv`` layout
        #: (pre-store private tiers): readable, never written
        self._legacy: set[str] = set()
        #: blocks THIS instance wrote (hit-origin attribution: a hit on a
        #: block someone else wrote is the cross-replica win)
        self._written: set[str] = set()
        #: blocks this replica references (its refcount contribution)
        self._pins: dict[str, None] = {}
        self.puts = 0
        self.writes = 0
        self.dedup_skips = 0
        self.deferred = 0
        self.hits = {"self": 0, "peer": 0}
        self.misses = 0
        self.invalidated = 0
        self._journal = named_journal("prefix_store")
        self._seed_index()
        if self.shared:
            self.board.register()

    # -- index ---------------------------------------------------------------

    def _seed_index(self) -> None:
        """Discover blocks already in the store (a previous fleet's warmth
        — the whole point). Sizes start 0 and fill lazily on first touch;
        reading every block at boot would make registration proportional
        to the store's size. Also adopts a legacy private tier's flat
        layout read-only, so upgrading a volume keeps it warm."""
        for sub, legacy in ((f"{self.root}/{BLOCKS_DIR}", False),
                            (self.root, True)):
            try:
                entries = list(self.volume.listdir(sub))
            except OSError:
                continue
            for name in entries:
                base = str(name).rsplit("/", 1)[-1]
                if base.startswith("block-") and base.endswith(".kv"):
                    h = base[len("block-"):-len(".kv")]
                    self._index.setdefault(h, 0)
                    if legacy:
                        self._legacy.add(h)

    def _rel(self, block_hash: str) -> str:
        if block_hash in self._legacy:
            return f"{self.root}/block-{block_hash}.kv"
        return f"{self.root}/{block_file(block_hash)}"

    def exists(self, block_hash: str) -> bool:
        # the index is a size cache, NOT presence truth: another replica
        # may have written the block since our last look — or INVALIDATED
        # it (torn/corrupt drop), and a stale index entry here would make
        # put() dedup-skip the respill fleet-wide. Always confirm against
        # the volume.
        if (self.volume.local_path / self._rel(block_hash)).exists():
            with self._lock:
                self._index.setdefault(block_hash, 0)
            return True
        with self._lock:
            self._index.pop(block_hash, None)
            self._legacy.discard(block_hash)
        return False

    @property
    def n_blocks(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._index.values())

    # -- write path ----------------------------------------------------------

    def put(self, block_hash: str, data: bytes, *, chain: str | None = None) -> str:
        """Spill one serialized block. Returns what happened:

        - ``"dedup"`` — already stored fleet-wide (the write N-1 replicas
          no longer pay);
        - ``"deferred"`` — another LIVE replica owns this chain's spills
          (rendezvous said so, or it holds a live lease);
        - ``"written"`` — this replica owned the chain (or runs private)
          and the block is durably, atomically on the volume.
        """
        with self._lock:
            self.puts += 1
        if self.exists(block_hash):
            with self._lock:
                self.dedup_skips += 1
            self._emit_gauges()
            return "dedup"
        if self.shared and chain is not None:
            owner = self.board.owner_for(chain)
            if owner is not None and owner != self.replica:
                with self._lock:
                    self.deferred += 1
                self._emit_gauges()
                return "deferred"
            if not self.board.acquire(chain):
                with self._lock:
                    self.deferred += 1
                self._emit_gauges()
                return "deferred"
        # fault point (docs/faults.md): the chain's owner dies mid-spill —
        # it drops out of the membership and the write below never happens.
        # The atomic temp+rename write discipline means a REAL crash at any
        # point of the write leaves no torn block either; the survivor's
        # next spill of this chain takes the lease over and rewrites it.
        if _inject.fire("prefix_store.owner_death"):
            self.board.deregister()
            raise _inject.FaultError(
                "injected fault: prefix_store.owner_death"
            )
        self.volume.write_file(self._rel(block_hash), data)
        with self._lock:
            self._index[block_hash] = len(data)
            self._written.add(block_hash)
            self.writes += 1
        self.pin([block_hash])
        self._emit_gauges()
        return "written"

    # -- read path -----------------------------------------------------------

    def get(self, block_hash: str) -> bytes | None:
        """Read one block, whoever wrote it. Structurally-unsound (torn)
        bytes are dropped from the store and reported as a miss — the
        caller recomputes; the full per-leaf crc runs downstream at
        deserialize time."""
        try:
            data = self.volume.read_file(self._rel(block_hash))
        except OSError:
            with self._lock:
                self.misses += 1
            _obs.record_prefix_store_miss()
            return None
        if not _structurally_sound(data):
            _log.warning(
                "dropping torn prefix-store block %s (%d bytes)",
                block_hash[:12], len(data),
            )
            self.invalidate(block_hash)
            with self._lock:
                self.misses += 1
            _obs.record_prefix_store_miss()
            return None
        self.touch(block_hash)
        with self._lock:
            self._index[block_hash] = len(data)
            origin = "self" if block_hash in self._written else "peer"
            self.hits[origin] += 1
        _obs.record_prefix_store_hit(origin)
        return data

    def touch(self, block_hash: str) -> None:
        """Refresh the block's last-hit time (the GC's LRU axis)."""
        try:
            os.utime(self.volume.local_path / self._rel(block_hash))
        except OSError:
            pass

    def invalidate(self, block_hash: str) -> None:
        """Remove a block (torn/corrupt): the next recompute respills it."""
        try:
            self.volume.remove_file(self._rel(block_hash))
        except OSError:
            pass
        with self._lock:
            self._index.pop(block_hash, None)
            self._legacy.discard(block_hash)
            self.invalidated += 1

    def drop_if_corrupt(self, block_hash: str) -> bool:
        """A reader's deserialize failed: decide whether the STORED bytes
        are rotten (re-read + full crc). In-flight corruption (the chaos
        ``tiered.volume_corrupt`` injection, a bad DMA) leaves the stored
        block intact — dropping it would throw away a good spill — so
        only a block whose bytes fail the crc ON DISK is removed."""
        try:
            data = self.volume.read_file(self._rel(block_hash))
        except OSError:
            return True
        try:
            deserialize_block(data)
        except (TransportError, ValueError, KeyError, struct.error):
            self.invalidate(block_hash)
            _log.warning(
                "dropped corrupt-on-disk prefix-store block %s",
                block_hash[:12],
            )
            return True
        return False

    # -- refcounts + GC ------------------------------------------------------

    def _refs_path(self, name: str) -> str:
        return f"{self.root}/{REFS_DIR}/{name}.json"

    def pin(self, hashes) -> None:
        """Add blocks to this replica's refcount manifest: while the
        replica is alive, GC keeps them. Bounded (``PIN_CAP``): oldest
        pins age out — the manifest is a refcount, not an archive."""
        with self._lock:
            before = len(self._pins)
            changed = False
            for h in hashes:
                if h in self._pins:
                    self._pins.pop(h)  # re-pin refreshes recency
                else:
                    changed = True
                self._pins[h] = None
            while len(self._pins) > PIN_CAP:
                self._pins.pop(next(iter(self._pins)))
                changed = True
            changed = changed or len(self._pins) != before
            pins = list(self._pins) if changed else None
        if pins is not None:
            self._write_refs(pins)

    def unpin(self, hashes) -> None:
        with self._lock:
            for h in hashes:
                self._pins.pop(h, None)
            pins = list(self._pins)
        self._write_refs(pins)

    def _write_refs(self, pins: list) -> None:
        try:
            self.volume.write_file(
                self._refs_path(self.replica),
                json.dumps({"at": self._clock(), "blocks": pins}).encode(),
            )
        except OSError as e:
            _log.warning("prefix store refs write failed: %s", e)

    def _pinned_fleetwide(self) -> set:
        """Union of every LIVE replica's pins (plus our own, even when
        running private — a dead replica's pins hold nothing)."""
        pinned: set = set()
        with self._lock:
            pinned.update(self._pins)
        alive = set(self.board.alive_replicas()) if self.shared else set()
        try:
            entries = list(self.volume.listdir(f"{self.root}/{REFS_DIR}"))
        except OSError:
            return pinned
        for entry in entries:
            base = str(entry).rsplit("/", 1)[-1]
            if not base.endswith(".json"):
                continue
            name = base[: -len(".json")]
            if name == self.replica or name not in alive:
                continue
            try:
                rec = json.loads(self.volume.read_file(str(entry)).decode())
                pinned.update(rec.get("blocks", ()))
            except (OSError, ValueError):
                continue
        return pinned

    def gc(
        self,
        *,
        max_bytes: int | None = None,
        max_blocks: int | None = None,
        max_remove: int = 64,
    ) -> dict:
        """One bounded LRU sweep: refresh sizes/ages from the directory,
        then remove the oldest-hit UNPINNED blocks until the store fits
        the budgets — at most ``max_remove`` removals per sweep, so the
        sweep's cost is bounded no matter how far over budget churn got."""
        ages: dict[str, float] = {}
        with self._lock:
            known = list(self._index)
        for h in known:
            try:
                st = (self.volume.local_path / self._rel(h)).stat()
            except OSError:
                with self._lock:
                    self._index.pop(h, None)
                continue
            ages[h] = st.st_mtime
            with self._lock:
                self._index[h] = st.st_size
        pinned = self._pinned_fleetwide()
        order = sorted(
            (h for h in ages if h not in pinned), key=ages.__getitem__
        )
        removed, freed = 0, 0
        for h in order:
            if removed >= max_remove:
                break
            with self._lock:
                blocks = len(self._index)
                total = sum(self._index.values())
            over = (
                (max_bytes is not None and total > max_bytes)
                or (max_blocks is not None and blocks > max_blocks)
            )
            if not over:
                break
            with self._lock:
                freed += self._index.get(h, 0)
            self.invalidate(h)
            removed += 1
        self._emit_gauges()
        if removed:
            self._journal.record({
                "at": time.time(),
                "action": "gc_sweep",
                "replica": self.replica,
                "removed": removed,
                "freed_bytes": freed,
                "blocks": self.n_blocks,
                "bytes": self.total_bytes,
                "pinned": len(pinned),
            })
        return {
            "removed": removed,
            "freed_bytes": freed,
            "blocks": self.n_blocks,
            "bytes": self.total_bytes,
            "pinned": len(pinned),
        }

    # -- membership passthrough (the store is the subsystem's one handle) ----

    def register_replica(self, *, boot: str | None = None) -> None:
        self.board.register(boot=boot)

    def heartbeat(self) -> None:
        self.board.heartbeat()

    def deregister_replica(self) -> None:
        self.board.deregister()
        try:
            self.volume.remove_file(self._refs_path(self.replica))
        except OSError:
            pass

    def alive_replicas(self) -> list[str]:
        return self.board.alive_replicas()

    def owner_for(self, chain: str, candidates=None) -> str | None:
        return self.board.owner_for(chain, candidates)

    # -- introspection -------------------------------------------------------

    def dedup_ratio(self) -> float:
        """Logical spill attempts per physical write, this instance's
        view: > 1.0 means the fleet stopped paying N copies."""
        with self._lock:
            return self.puts / max(1, self.writes)

    def _emit_gauges(self) -> None:
        _obs.set_prefix_store_occupancy(
            total_bytes=self.total_bytes, dedup_ratio=self.dedup_ratio()
        )

    def stats(self) -> dict:
        with self._lock:
            out = {
                "replica": self.replica,
                "shared": self.shared,
                "root": self.root,
                "blocks": len(self._index),
                "bytes": sum(self._index.values()),
                "puts": self.puts,
                "writes": self.writes,
                "dedup_skips": self.dedup_skips,
                "deferred": self.deferred,
                "hits": dict(self.hits),
                "misses": self.misses,
                "invalidated": self.invalidated,
                "pins": len(self._pins),
            }
        out["dedup_ratio"] = round(self.dedup_ratio(), 4)
        out["takeovers"] = self.board.takeovers
        if self.shared:
            out["alive_replicas"] = self.alive_replicas()
            out["leases"] = self.board.n_leases()
        return out
